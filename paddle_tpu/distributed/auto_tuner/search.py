"""Candidate generation (reference auto_tuner/search.py grid role)."""
from __future__ import annotations


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def grid_candidates(n_devices, sharding_stages, max_micro, global_batch,
                    enable_sep=False, num_experts=0):
    """``num_experts > 0`` (a MoE workload) additionally grid-searches
    the expert-parallel ``ep`` axis (ISSUE 11 satellite / ROADMAP item
    5); infeasible ep combinations are left to the pruning rules."""
    from .tuner import Candidate

    out = []
    for mp in _divisors(n_devices):
        for pp in _divisors(n_devices // mp):
            for ep in (_divisors(n_devices // (mp * pp))
                       if num_experts else [1]):
                for sep in (_divisors(n_devices // (mp * pp * ep))
                            if enable_sep else [1]):
                    dp = n_devices // (mp * pp * sep * ep)
                    batch_ways = max(dp, 1) * ep   # batch splits dp×ep
                    micros = [m for m in
                              _divisors(max(global_batch
                                            // batch_ways, 1))
                              if m <= max_micro]
                    for stage in sharding_stages:
                        if stage and dp * ep == 1:
                            continue  # nothing to shard over
                        for micro in (micros or [1]):
                            if pp > 1 and micro == 1:
                                continue  # pipeline needs micro-batches
                            out.append(Candidate(
                                dp=dp, mp=mp, pp=pp, sep=sep, ep=ep,
                                sharding_stage=stage,
                                micro_batch=micro))
    return out
