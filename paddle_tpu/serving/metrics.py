"""Serving metrics: counters + per-request latency aggregation.

One ``ServingMetrics`` lives on the engine; the scheduler and the step
loop feed it events, and ``snapshot()`` renders the surface the bench
lane records (queue depth, running/waiting, per-request TTFT and
inter-token latency percentiles, aggregate tok/s, preemption and
page-reclaim counters). Everything is host-side and O(1) per event —
no device sync is ever added for metrics.

Since ISSUE 12 the percentile surface lives on the unified
``observability`` layer: the latency samples are
`observability.Histogram` ring buffers (ONE histogram implementation
process-wide, `percentile` re-exported from there), every counter and
gauge is registered in a per-engine `MetricsRegistry`, and
``ServingEngine.metrics_text()`` renders that registry as Prometheus
text exposition. Each engine gets its OWN registry so concurrent
engines (tests run several) stay isolated; the engine-wide queue-depth
/ running gauges are mirrored into the process-global registry too.
"""
from __future__ import annotations

import time

from ..observability import MetricsRegistry, percentile
from ..observability import registry as _global_registry

__all__ = ["ServingMetrics", "percentile"]


class ServingMetrics:
    # int counters kept as plain attributes (the engine increments them
    # in place); expose() publishes them through lazy gauges
    _COUNTERS = ("submitted", "admitted", "resumed", "finished",
                 "preemptions", "evicted_pages", "prefill_chunks",
                 "decode_steps", "generated_tokens",
                 "spec_dispatches", "spec_proposed", "spec_accepted",
                 "spec_emitted", "kv_evictions", "kv_onloads")
    _GAUGES = ("queue_depth", "running")

    def __init__(self, clock=time.perf_counter, registry=None,
                 slo=None):
        self.clock = clock
        # optional observability.SLOTracker (ISSUE 13): every retired
        # request's TTFT/ITL samples feed the declared objectives
        self.slo = slo
        self.start_time = clock()
        # counters
        self.submitted = 0
        self.admitted = 0
        self.resumed = 0          # re-admissions of preempted requests
        self.finished = 0
        self.preemptions = 0
        self.evicted_pages = 0    # pages reclaimed by preemption
        self.prefill_chunks = 0
        self.decode_steps = 0
        self.generated_tokens = 0
        # speculative decoding (ISSUE 16): per-slot-dispatch accounting
        # — proposed counts draft tokens scored, accepted the ones that
        # survived verification, emitted every token the spec path
        # delivered (accepted + the correction/bonus token)
        self.spec_dispatches = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_emitted = 0
        # host-ring KV migration (ISSUE 18): evictions parked a victim's
        # pages in host memory instead of discarding them; onloads
        # brought them back without a re-prefill
        self.kv_evictions = 0
        self.kv_onloads = 0
        # gauges (refreshed every engine step)
        self.queue_depth = 0
        self.running = 0
        # per-request latency samples (appended at finish) — ONE ring
        # histogram implementation (observability.Histogram): supports
        # append/extend like the plain lists these used to be, plus
        # O(1) observe and lazy p50/p99
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.ttft_s = self.registry.histogram("serving.ttft_s",
                                              window=4096)
        self.itl_s = self.registry.histogram("serving.itl_s",
                                             window=8192)
        self.request_preemptions = self.registry.histogram(
            "serving.request_preemptions", window=4096)
        for name in self._COUNTERS:
            self.registry.gauge(f"serving.{name}").set_fn(
                (lambda n: lambda: getattr(self, n))(name))
        for name in self._GAUGES:
            self.registry.gauge(f"serving.{name}").set_fn(
                (lambda n: lambda: getattr(self, n))(name))
        self.registry.gauge("serving.tok_s").set_fn(
            lambda: round(self.generated_tokens
                          / max(self.clock() - self.start_time, 1e-9),
                          2))
        # the two speculative-decoding health gauges (ISSUE 16): how
        # good the draft is, and what each target dispatch yields
        self.registry.gauge("serving.spec.accept_rate").set_fn(
            lambda: round(self.spec_accepted
                          / max(self.spec_proposed, 1), 4))
        self.registry.gauge("serving.spec.tokens_per_dispatch").set_fn(
            lambda: round(self.spec_emitted
                          / max(self.spec_dispatches, 1), 4))

    # -- event feeds ------------------------------------------------------
    def on_submit(self):
        self.submitted += 1

    def on_admit(self, resumed: bool):
        self.admitted += 1
        if resumed:
            self.resumed += 1

    def on_preempt(self, pages_reclaimed: int):
        self.preemptions += 1
        self.evicted_pages += int(pages_reclaimed)

    def on_token(self):
        self.generated_tokens += 1

    def on_finish(self, handle):
        self.finished += 1
        itls = handle.inter_token_latencies
        if handle.ttft is not None:
            self.ttft_s.observe(handle.ttft)
        self.itl_s.extend(itls)
        self.request_preemptions.observe(handle.preemptions)
        if self.slo is not None:
            if handle.ttft is not None:
                self.slo.observe_metric("ttft_s", handle.ttft)
            for itl in itls:
                self.slo.observe_metric("itl_s", itl)

    def observe(self, queue_depth: int, running: int):
        self.queue_depth = queue_depth
        self.running = running
        # engine-level load gauges mirrored into the process-global
        # registry (last engine observed wins — the always-on surface)
        g = _global_registry()
        g.gauge("serving.queue_depth").set(queue_depth)
        g.gauge("serving.running").set(running)

    # -- surface ----------------------------------------------------------
    def expose(self) -> str:
        """Prometheus text exposition of this engine's registry."""
        return self.registry.expose()

    def snapshot(self) -> dict:
        elapsed = max(self.clock() - self.start_time, 1e-9)
        return {
            "submitted": self.submitted,
            "admitted": self.admitted,
            "resumed": self.resumed,
            "finished": self.finished,
            "preemptions": self.preemptions,
            "evicted_pages": self.evicted_pages,
            "prefill_chunks": self.prefill_chunks,
            "decode_steps": self.decode_steps,
            "generated_tokens": self.generated_tokens,
            "spec_dispatches": self.spec_dispatches,
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            "spec_emitted": self.spec_emitted,
            "spec_accept_rate": round(
                self.spec_accepted / max(self.spec_proposed, 1), 4),
            "spec_tokens_per_dispatch": round(
                self.spec_emitted / max(self.spec_dispatches, 1), 4),
            "kv_evictions": self.kv_evictions,
            "kv_onloads": self.kv_onloads,
            "queue_depth": self.queue_depth,
            "running": self.running,
            "elapsed_s": round(elapsed, 4),
            "tok_s": round(self.generated_tokens / elapsed, 2),
            "ttft_p50_s": self.ttft_s.percentile(50),
            "ttft_p99_s": self.ttft_s.percentile(99),
            "itl_p50_s": self.itl_s.percentile(50),
            "itl_p99_s": self.itl_s.percentile(99),
        }
