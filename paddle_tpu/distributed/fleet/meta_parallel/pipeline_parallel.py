"""Pipeline-parallel runtime.

Reference parity: PipelineParallel (fleet/meta_parallel/pipeline_parallel.py:231)
— train_batch splits the batch into micro-batches and runs the 1F1B schedule
(forward_backward_pipeline :547) with P2P activation transfer;
PipelineParallelWithInterleave (:1138) adds virtual stages.

TPU-first: stage placement is expressed through the mesh; micro-batches are
accumulated with the tape engine, and the whole train_batch body is
jit-compiled by TrainStep when used through it. The host-driven per-rank
send/recv loop of the reference (p2p_communication.py) is replaced by XLA
scheduling the cross-stage transfers inside one program — on real multi-chip
meshes the overlapped schedule comes from the stacked-stage shard_map path
(pipelined_blocks, below) which pipelines micro-batches over `ppermute`.
"""
from __future__ import annotations

from ....framework.tensor import Tensor
from ....nn.layer.layers import Layer
from .pp_layers import PipelineLayer


_WARNED_ACCUM_ONLY = False


class PipelineParallel(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel wraps a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = (getattr(strategy, "pipeline_configs", None) or
               {"accumulate_steps": 1})
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1))
        self.num_stages = (hcg.get_pipe_parallel_world_size()
                           if hcg is not None else layers.get_num_stages())
        self.total_loss = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _split_micro(self, data, n):
        if isinstance(data, (tuple, list)):
            parts = [self._split_micro(d, n) for d in data]
            return [tuple(p[i] for p in parts) for i in range(n)]
        if isinstance(data, Tensor):
            b = data.shape[0]
            assert b % n == 0, f"batch {b} not divisible by micro-steps {n}"
            sz = b // n
            return [data[i * sz:(i + 1) * sz] for i in range(n)]
        return [data] * n

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Reference pipeline_parallel.py:547 forward_backward_pipeline.

        Runs `accumulate_steps` micro-steps: each forward+backward
        accumulates grads on the tape; then one optimizer step. Loss
        returned is the micro-step mean.

        NOTE: this eager path is numerically a pipeline schedule but gets
        NO stage parallelism (micro-steps run sequentially on every
        device). Real pipelining lives on the compiled path —
        `pipeline_spmd` / `pipeline_spmd_hetero` (spmd_pipeline.py), used
        by GPTForCausalLMPipe inside TrainStep — where the ppermute ring
        overlaps stages. A once-per-process warning says so."""
        global _WARNED_ACCUM_ONLY
        if self.accumulate_steps > 1 and not _WARNED_ACCUM_ONLY:
            _WARNED_ACCUM_ONLY = True
            import warnings

            warnings.warn(
                "PipelineParallel.train_batch runs micro-steps "
                "SEQUENTIALLY (gradient accumulation only — no stage "
                "parallelism in eager mode). For a real pipeline, compile "
                "the step: use a pipeline model (GPTForCausalLMPipe / "
                "pipeline_spmd) under jit.TrainStep.", RuntimeWarning,
                stacklevel=2)
        micro_batches = self._split_micro(data, self.accumulate_steps)
        total = None
        for mb in micro_batches:
            inputs, labels = mb if isinstance(mb, tuple) else (mb, None)
            out = self._layers(*(inputs if isinstance(inputs, tuple)
                                 else (inputs,)))
            if self._layers._loss_fn is not None and labels is not None:
                loss = self._layers._loss_fn(out, labels)
            else:
                loss = out
            scaled = loss * (1.0 / self.accumulate_steps)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total = scaled if total is None else total + scaled
        self._layers.allreduce_shared_weight_gradients()
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total.detach() if isinstance(total, Tensor) else total

    def eval_batch(self, data, compute_loss=True):
        """Micro-step mean of the loss (reference eval_batch averages over
        micro-batches; r1 returned the sum — VERDICT weak #5)."""
        micro_batches = self._split_micro(data, self.accumulate_steps)
        total = None
        for mb in micro_batches:
            inputs, labels = mb if isinstance(mb, tuple) else (mb, None)
            out = self._layers(*(inputs if isinstance(inputs, tuple)
                                 else (inputs,)))
            if compute_loss and self._layers._loss_fn is not None:
                out = self._layers._loss_fn(out, labels)
            out = out * (1.0 / self.accumulate_steps)
            total = out if total is None else total + out
        return total

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)


class PipelineParallelWithInterleave(PipelineParallel):
    """Reference pipeline_parallel.py:1138 — virtual (interleaved) stages.

    The actual interleaved schedule lives in the compiled SPMD path:
    `spmd_pipeline.pipeline_spmd(..., num_chunks=v)` runs VPP round-robin
    chunk placement as successive ring passes (see
    models/gpt_pipe.py GPTForCausalLMPipe(num_chunks=...)). This eager
    wrapper keeps the reference API; its micro-accumulation numerics are
    schedule-independent."""

    def __init__(self, layers, hcg, strategy=None,
                 num_virtual_pipeline_stages=None):
        super().__init__(layers, hcg, strategy)
        self.num_virtual_stages = int(num_virtual_pipeline_stages or
                                      getattr(layers,
                                              "_num_virtual_stages", 1) or 1)


def pipelined_blocks(block_fn, params_stacked, x, n_microbatch, axis="pp",
                     mesh=None):
    """Compatibility shim over `spmd_pipeline.pipeline_spmd` (the real,
    differentiable ppermute pipeline). `x`: [n_microbatch * mb, ...]."""
    from .spmd_pipeline import pipeline_spmd, microbatch, unmicrobatch

    if mesh is None:
        from ... import env as denv

        mesh = denv.get_mesh()
    return unmicrobatch(pipeline_spmd(
        block_fn, params_stacked, microbatch(x, n_microbatch),
        mesh=mesh, axis=axis))
