"""Expert-parallel mixture-of-experts
(reference python/paddle/incubate/distributed/models/moe/)."""
from .gate import NaiveGate, top1_gating, top2_gating  # noqa: F401
from .grad_clip import ClipGradForMOEByGlobalNorm  # noqa: F401
from .moe_layer import (  # noqa: F401
    ExpertFFN, MoELayer, global_gather, global_scatter,
)
