"""Device-memory observability (ISSUE 14): compiled-step HBM profiles
on every jitted step path, live-buffer attribution that sums to the
`jax.live_arrays()` total, the sharded-vs-replicated storage receipt,
OOM forensics through the flight recorder, `/memz`, page-pool stats,
and the zero-retrace guarantee of the instrumentation itself."""
import gc
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.optimizer as popt
from paddle_tpu import observability as obs
from paddle_tpu.models import (
    GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
)

TINY = dict(vocab_size=96, hidden_size=32, num_layers=4,
            num_attention_heads=2, max_position_embeddings=16,
            hidden_dropout_prob=0.0, attention_dropout_prob=0.0)


def _batch(rows=8, seed=0):
    rng = np.random.default_rng(seed)
    ids = paddle.to_tensor(
        rng.integers(0, TINY["vocab_size"], (rows, 16)), dtype="int64")
    labels = paddle.to_tensor(
        rng.integers(0, TINY["vocab_size"], (rows, 16)), dtype="int64")
    return ids, labels


def _fused_step(seed=0):
    cfg = GPTConfig(**TINY, scan_layers=True)
    paddle.seed(seed)
    model = GPTForCausalLM(cfg)
    opt = popt.AdamW(learning_rate=1e-3, parameters=model.parameters())
    return GPTPretrainingCriterion(), model, opt


def _assert_profile_sane(prof):
    s = prof.summary()
    assert s["peak_bytes"] and s["peak_bytes"] > 0, s
    # the arg+out+temp-alias identity is exact only when the peak was
    # DERIVED from those stats; a jaxlib-reported scheduled peak may
    # sit below the sum (not all temps live at once)
    if s["peak_source"] == "derived":
        assert s["peak_bytes"] == (s["argument_bytes"]
                                   + s["output_bytes"] + s["temp_bytes"]
                                   - (s["alias_bytes"] or 0)), s
    else:
        assert s["peak_source"] == "reported", s
        assert s["peak_bytes"] <= (s["argument_bytes"]
                                   + s["output_bytes"]
                                   + s["temp_bytes"]), s
    assert prof.top_buffers, "no buffers parsed from the compiled HLO"
    sizes = [b["bytes"] for b in prof.top_buffers]
    assert sizes == sorted(sizes, reverse=True), sizes
    assert prof.largest_buffer_bytes == sizes[0]
    for b in prof.top_buffers:
        assert b["bytes"] > 0 and b["count"] >= 1
        assert b["dtype"] and b["shape"].startswith("[")
        assert b["op"], b
    return s


class TestHloBufferParse:
    def test_parse_shapes_ops_and_provenance(self):
        text = (
            'ENTRY %main (p0: f32[8,16]) -> f32[8,16] {\n'
            '  %p0 = f32[8,16]{1,0} parameter(0), '
            'metadata={op_name="x"}\n'
            '  %big = bf16[128,256]{1,0} dot(f32[8,16]{1,0} %p0), '
            'metadata={op_name="jit(step)/dot_general"}\n'
            '  ROOT %t = (f32[8,16]{1,0}, s32[4]{0}) tuple(%p0, %p0)\n'
            '}\n')
        bufs = obs.parse_hlo_buffers(text, top_k=None)
        by_op = {b["op"]: b for b in bufs}
        assert by_op["dot"]["bytes"] == 128 * 256 * 2
        assert by_op["dot"]["op_name"] == "jit(step)/dot_general"
        assert by_op["parameter"]["bytes"] == 8 * 16 * 4
        # tuple result: one buffer PER element
        assert by_op["tuple"]["dtype"] in ("f32", "s32")
        assert sum(b["count"] for b in bufs
                   if b["name"] == "t") == 2
        assert bufs[0]["bytes"] == max(b["bytes"] for b in bufs)

    def test_duplicate_buffers_collapse_with_count(self):
        line = ('  %a.1 = f32[64]{0} add(f32[64]{0} %x, f32[64]{0} %y), '
                'metadata={op_name="jit(f)/add"}\n')
        bufs = obs.parse_hlo_buffers("x = 1\n" + line * 5, top_k=None)
        assert len(bufs) == 1 and bufs[0]["count"] == 5

    def test_operand_shapes_are_not_result_buffers(self):
        text = '  %d = f32[2,2]{1,0} dot(f32[999,999]{1,0} %huge)\n'
        bufs = obs.parse_hlo_buffers(text, top_k=None)
        assert len(bufs) == 1 and bufs[0]["bytes"] == 16

    def test_dtype_widths(self):
        from paddle_tpu.observability.memory import _dtype_bytes

        assert _dtype_bytes("f32") == 4 and _dtype_bytes("bf16") == 2
        assert _dtype_bytes("pred") == 1 and _dtype_bytes("s64") == 8
        assert _dtype_bytes("u8") == 1


class TestCompiledProfiles:
    def test_eager_train_step_profile(self):
        from paddle_tpu.jit import TrainStep

        crit, _, _ = _fused_step()
        cfg = GPTConfig(**TINY, scan_layers=False)
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        opt = popt.AdamW(learning_rate=1e-3, parameters=m.parameters())
        step = TrainStep(m, lambda mm, a, b: crit(mm(a), b), opt)
        ids, labels = _batch()
        with pytest.raises(RuntimeError, match="built step"):
            step.memory_profile(ids, labels)
        step(ids, labels)
        prof = step.memory_profile(ids, labels)
        s = _assert_profile_sane(prof)
        # params + opt state dominate the arguments
        n_param_bytes = sum(int(np.prod(p.shape)) * 4
                            for p in m.parameters())
        assert s["argument_bytes"] >= 3 * n_param_bytes
        # gauges published under the step-class name
        g = obs.registry().get("mem.compiled.TrainStep.peak_bytes")
        assert g is not None and g.value == s["peak_bytes"]

    def test_fused_scan_profile_and_zero_retrace(self):
        from paddle_tpu.jit import FusedScanTrainStep

        crit, model, opt = _fused_step()
        step = FusedScanTrainStep(model, opt, criterion=crit)
        ids, labels = _batch()
        step(ids, labels)
        prof = step.memory_profile(ids, labels)
        _assert_profile_sane(prof)
        # the AOT profile must not add executables or sentinel events
        step(ids, labels)
        st = step.retrace_stats()
        assert st["signatures"] == 1 and st["unexpected"] == 0, st
        if hasattr(step._jitted, "_cache_size"):
            assert step._jitted._cache_size() == 1

    def test_sharded_scan_profile(self):
        import jax
        from jax.sharding import Mesh

        from paddle_tpu.distributed import env as denv
        from paddle_tpu.jit import ShardedFusedScanTrainStep

        crit, model, opt = _fused_step()
        mesh = Mesh(np.asarray(jax.devices("cpu")[:8]), ("sharding",))
        denv.set_mesh(mesh)
        step = ShardedFusedScanTrainStep(model, opt, criterion=crit,
                                         mesh=mesh, axis="sharding")
        ids, labels = _batch()
        step(ids, labels)
        prof = step.memory_profile(ids, labels)
        _assert_profile_sane(prof)
        # sharded storage: a scrape-time owner walk must not gather
        from paddle_tpu.jit.sharded_scan import _STALE, _data_slot

        rep = obs.live_registry().report(publish=False)
        assert rep["owners"].get("params.scan_shards", 0) > 0, \
            rep["owners"]
        slot = _data_slot()
        assert all(slot.__get__(p) is _STALE for _, p in step._s_train)
        step(ids, labels)
        assert step.retrace_stats()["signatures"] == 1

    def test_pipeline_scan_profile(self):
        import jax

        from paddle_tpu.distributed import env as denv
        from paddle_tpu.jit.pipeline_step import PipelineScanTrainStep

        crit, model, opt = _fused_step()
        mesh = denv.build_mesh({"dp": 2, "pp": 2},
                               devices=jax.devices("cpu")[:4])
        denv.set_mesh(mesh)
        step = PipelineScanTrainStep(model, opt, criterion=crit,
                                     mesh=mesh, axis="dp",
                                     pp_axis="pp", num_micro=2)
        ids, labels = _batch(rows=4)    # local batch 2 = num_micro
        step(ids, labels)
        prof = step.memory_profile(ids, labels)
        _assert_profile_sane(prof)

    def test_decode_and_serving_step_profiles(self):
        from paddle_tpu.jit.decode_step import GenerationEngine
        from paddle_tpu.serving import ServingEngine

        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=64,
                        hidden_dropout_prob=0.0,
                        attention_dropout_prob=0.0)
        m = GPTForCausalLM(cfg)
        m.eval()
        for kind in ("dense", "paged"):
            eng = GenerationEngine(m, kind=kind, batch=2, max_len=32)
            eng.generate(np.ones((2, 4), np.int64), 2)
            tc = eng.decode_step.trace_count
            prof = eng.memory_profile()
            _assert_profile_sane(prof)
            # a profile is AOT analysis on a FRESH jit copy: the live
            # decode executable and its trace counter are untouched
            assert eng.decode_step.trace_count == tc
        srv = ServingEngine(m, max_slots=2, max_len=32, page_size=8,
                            chunk_size=8)
        srv.submit(np.ones((4,), np.int32), 3)
        srv.run(max_steps=500)
        prof = srv.memory_profile()
        _assert_profile_sane(prof)
        g = obs.registry().get("mem.compiled.ServeDecodeStep.peak_bytes")
        assert g is not None and g.value == prof.peak_bytes


class TestLiveAttribution:
    def test_owners_sum_to_live_total(self):
        from paddle_tpu.jit import FusedScanTrainStep

        crit, model, opt = _fused_step(seed=3)
        step = FusedScanTrainStep(model, opt, criterion=crit)
        ids, labels = _batch()
        step(ids, labels)
        rep = obs.live_buffer_report()
        assert (sum(rep["owners"].values()) + rep["untagged_bytes"]
                == rep["total_bytes"]), rep
        n_param_bytes = sum(int(np.prod(p.shape)) * 4
                            for p in model.parameters())
        assert rep["owners"]["params"] >= n_param_bytes
        assert rep["owners"]["opt_state"] >= 2 * n_param_bytes
        # gauges land on scrape
        assert obs.registry().get("mem.live.total_bytes").value \
            == rep["total_bytes"]
        assert obs.registry().get("mem.live.params").value \
            == rep["owners"]["params"]

    def test_replication_counts_device_resident_bytes(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.observability.memory import device_bytes

        mesh = Mesh(np.asarray(jax.devices("cpu")[:8]), ("dp",))
        sharded = jax.device_put(
            jnp.zeros((8, 4), jnp.float32), NamedSharding(mesh, P("dp")))
        replicated = jax.device_put(
            jnp.zeros((8, 4), jnp.float32), NamedSharding(mesh, P()))
        assert device_bytes(sharded) == 8 * 4 * 4
        assert device_bytes(replicated) == 8 * 4 * 4 * 8

    def test_dead_producer_drops_out(self):
        import jax.numpy as jnp

        class Owner:
            def __init__(self):
                self.arrs = [jnp.ones((64,), jnp.float32)]

            def _mem_owners(self):
                return {"ephemeral_owner": self.arrs}

        o = Owner()
        obs.live_registry().track(o)
        obs.live_registry().track(o)        # idempotent
        rep = obs.live_registry().report(publish=False)
        assert rep["owners"].get("ephemeral_owner") == 256, rep
        del o
        gc.collect()
        rep = obs.live_registry().report(publish=False)
        assert "ephemeral_owner" not in rep["owners"]

    def test_vanished_owner_gauge_zeroed(self):
        import jax.numpy as jnp

        class Owner:
            def __init__(self):
                self.arrs = [jnp.ones((64,), jnp.float32)]

            def _mem_owners(self):
                return {"vanishing_owner": self.arrs}

        o = Owner()
        obs.live_registry().track(o)
        obs.live_buffer_report()
        g = obs.registry().get("mem.live.vanishing_owner")
        assert g is not None and g.value == 256
        del o
        gc.collect()
        obs.live_buffer_report()
        # phantom bytes must not survive on the scrape surface
        assert g.value == 0

    def test_prefetch_ring_tagged(self):
        from paddle_tpu.io.device_prefetcher import DevicePrefetcher

        batches = [(np.ones((4, 16), np.int64),
                    np.ones((4, 16), np.int64)) for _ in range(4)]
        pf = DevicePrefetcher(iter(batches), depth=2, to_tensor=False)
        try:
            next(iter(pf))
            import time

            deadline = time.time() + 5
            rep = obs.live_registry().report(publish=False)
            while ("prefetch_ring" not in rep["owners"]
                   and time.time() < deadline):
                time.sleep(0.02)    # producer thread fills the ring
                rep = obs.live_registry().report(publish=False)
            assert rep["owners"].get("prefetch_ring", 0) > 0, \
                rep["owners"]
        finally:
            pf.close()


class TestStorageReceipt:
    def test_sharded_vs_replicated_profile_delta(self):
        # the PR-11 receipt through the ONE profile implementation:
        # probe HLO max buffer 49,984 elems (sharded) vs 65,536
        # (replicated) — also asserted in the hermetic memory lane,
        # where the measured numbers land in BENCH_r*.json
        from paddle_tpu.jit.sharded_scan import build_probe_lowered
        from paddle_tpu.observability.memory import (
            CompiledMemoryProfile,
        )

        profs = {}
        for storage in ("replicated", "sharded"):
            lowered = build_probe_lowered(param_storage=storage)
            profs[storage] = CompiledMemoryProfile.from_lowered(lowered)
        s, r = profs["sharded"], profs["replicated"]
        assert s.peak_bytes < r.peak_bytes
        assert s.top_buffers[0]["elems"] == 49984, s.top_buffers[0]
        assert r.top_buffers[0]["elems"] == 65536, r.top_buffers[0]


class TestOomForensics:
    def test_is_oom_error(self):
        assert obs.is_oom_error(RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 1 bytes"))
        assert obs.is_oom_error(RuntimeError("Resource exhausted"))
        assert not obs.is_oom_error(ValueError("shape mismatch"))
        assert not obs.is_oom_error(KeyboardInterrupt())

    def test_synthetic_oom_dumps_and_reraises(self, tmp_path,
                                              monkeypatch):
        from paddle_tpu.jit import FusedScanTrainStep

        monkeypatch.setenv("PADDLE_FLIGHT_DIR", str(tmp_path))
        crit, model, opt = _fused_step(seed=5)
        step = FusedScanTrainStep(model, opt, criterion=crit)
        ids, labels = _batch()
        step(ids, labels)

        class Boom:
            def __init__(self, orig):
                self.orig = orig

            def __call__(self, *a, **k):
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: Out of memory while trying "
                    "to allocate 17179869184 bytes")

            def lower(self, *a, **k):
                return self.orig.lower(*a, **k)

        orig = step._jitted
        step._jitted = Boom(orig)
        try:
            with pytest.raises(RuntimeError,
                               match="RESOURCE_EXHAUSTED"):
                step(ids, labels)
        finally:
            step._jitted = orig
        dump = obs.last_oom_report()
        assert dump["step"] == "FusedScanTrainStep"
        assert dump["live"]["total_bytes"] > 0
        assert dump["compiled"]["peak_bytes"] > 0
        assert dump["compiled"]["top_buffers"]
        assert dump["dump_path"] and \
            dump["dump_path"].startswith(str(tmp_path))
        with open(dump["dump_path"]) as f:
            disk = json.load(f)
        ev = [e for e in disk["events"] if e.get("kind") == "oom"]
        assert ev and ev[-1]["compiled_peak_bytes"] == \
            dump["compiled"]["peak_bytes"]
        assert ev[-1]["top_buffers"]
        # counted, step still healthy at one executable
        assert obs.registry().get("mem.oom.count").value >= 1
        step(ids, labels)
        if hasattr(step._jitted, "_cache_size"):
            assert step._jitted._cache_size() == 1

    def test_non_oom_errors_do_not_dump(self, monkeypatch):
        from paddle_tpu.observability import memory as M

        calls = []
        monkeypatch.setattr(M, "dump_oom",
                            lambda *a, **k: calls.append(1))
        with pytest.raises(ValueError):
            with M.oom_guard(step="x"):
                raise ValueError("not an oom")
        assert not calls


class TestMemz:
    def test_global_memz_endpoint(self):
        import urllib.request

        from urllib.error import HTTPError

        with obs.DebugServer(port=0) as srv:
            body = json.load(urllib.request.urlopen(
                f"{srv.url}/memz", timeout=5))
            try:
                listing = json.load(urllib.request.urlopen(
                    f"{srv.url}/nope", timeout=5))
            except HTTPError as e:
                assert e.code == 404
                listing = json.load(e)
        assert body["live"]["total_bytes"] > 0
        assert isinstance(body["compiled"], dict)
        assert "memz" in listing["endpoints"]

    def test_engine_memz_includes_pool(self):
        import urllib.request

        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=64,
                        hidden_dropout_prob=0.0,
                        attention_dropout_prob=0.0)
        m = GPTForCausalLM(cfg)
        m.eval()
        from paddle_tpu.serving import ServingEngine

        eng = ServingEngine(m, max_slots=2, max_len=32, page_size=8,
                            chunk_size=8)
        port = eng.start_debug_server()
        try:
            body = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/memz", timeout=5))
        finally:
            eng.stop_debug_server()
        assert body["pool"]["total_pages"] == eng.num_pages - 1
        assert body["pool"]["used_pages"] == 0
        # pool gauges ride the engine scrape too
        assert "serving_kv_free_pages" in eng.metrics_text()


class TestPoolStats:
    def _cache(self, num_pages=17, page_size=8, max_slots=4):
        from paddle_tpu.inference.kv_cache import PagedKVCache

        return PagedKVCache(1, 2, 8, num_pages=num_pages,
                            page_size=page_size, max_slots=max_slots,
                            pages_per_seq=8)

    def test_invariants_and_per_slot_counts(self):
        c = self._cache()
        st = c.pool_stats()
        assert st["total_pages"] == 16 and st["trash_pages"] == 1
        assert st["used_pages"] == 0 and st["fragmentation"] == 0.0
        s0 = c.allocate(20)          # 3 pages
        s1 = c.allocate(9)           # 2 pages
        st = c.pool_stats()
        assert st["slot_pages"] == {s0: 3, s1: 2}
        assert st["used_pages"] == 5
        assert st["used_pages"] + st["free_pages"] == st["total_pages"]
        assert st["occupancy"] == round(5 / 16, 4)

    def test_fragmentation_tracks_free_contiguity(self):
        c = self._cache()
        s0 = c.allocate(24)          # pages
        s1 = c.allocate(24)
        assert c.pool_stats()["fragmentation"] == 0.0
        c.free(s0)                   # hole before s1's pages
        st = c.pool_stats()
        assert st["fragmentation"] > 0.0
        assert st["max_contiguous_free"] < st["free_pages"]
        c.free(s1)
        st = c.pool_stats()
        assert st["fragmentation"] == 0.0
        assert st["max_contiguous_free"] == st["free_pages"] \
            == st["total_pages"]

    def test_kv_pools_tagged_for_live_attribution(self):
        c = self._cache()
        rep = obs.live_registry().report(publish=False)
        want = sum(a.nbytes for a in c.k_layers + c.v_layers)
        assert rep["owners"].get("kv_pages", 0) >= want
