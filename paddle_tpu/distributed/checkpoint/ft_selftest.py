"""Hermetic fault-tolerance selftest (bench.py ``fault_tolerance`` lane).

Run under a cpu-forced env (bench.py's stripped subprocess /
tools/cpu_env.sh):

    python -m paddle_tpu.distributed.checkpoint.ft_selftest [--trials N]

Four lanes, one JSON line (landing verbatim in BENCH_r*.json):

  kill      — a victim subprocess saves checkpoints in a tight loop and
              is SIGKILLed at a randomized point per trial;
              ``restore_or_init`` must always come back with a complete,
              checksum-verified checkpoint (never a torn one), at a step
              the victim actually committed.
  flip      — one flipped byte in a committed chunk file must fail
              manifest verification and restore must fall back to the
              previous valid step.
  resume    — FusedScanTrainStep: save at step k, restore into a fresh
              model/optimizer, continue — the continued loss trajectory
              is BIT-identical to an uninterrupted run.
  async     — the train loop blocks only for the device→host snapshot;
              records blocked vs background-IO milliseconds (PERF.md's
              async-save overlap numbers).

``--victim <dir>`` is the child mode the kill lane spawns: save
checkpoints 0,1,2,... into <dir> forever, printing ``committed K`` after
every commit, until killed.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

_VICTIM_ARRAY_KB = 192      # per-array payload: big enough that a save
_VICTIM_ARRAYS = 4          # takes ~ms, so random kills land mid-write


def _victim_state(step: int):
    rng = np.random.default_rng(step)
    n = _VICTIM_ARRAY_KB * 1024 // 4
    return {f"w{i}": rng.standard_normal(n).astype(np.float32)
            for i in range(_VICTIM_ARRAYS)} | {"step_scalar": step}


def victim_main(root: str):
    from .manager import CheckpointManager

    extra = _victim_state(0)
    mgr = CheckpointManager(root, extra_state=extra, max_to_keep=3)
    step = 0
    while True:
        extra.clear()
        extra.update(_victim_state(step))
        mgr.save(step)
        print(f"committed {step}", flush=True)
        step += 1


def run_kill_lane(trials: int = 8, seed: int = 0):
    """SIGKILL the victim at randomized points; every restore must land
    on a committed, checksum-verified step with intact payloads. The
    kill schedule comes from the shared FaultInjector (ISSUE 19) —
    same seeded stream the ad-hoc rng used, every kill logged."""
    import shutil
    import tempfile

    from ...observability import faults
    from .load_state_dict import verify_checkpoint
    from .manager import CheckpointManager

    inj = faults.install(seed)
    inj.arm("proc.sigkill", every=1, times=trials)
    mid_save_hits = 0
    for trial in range(trials):
        root = tempfile.mkdtemp(prefix="ftkill_")
        try:
            child = subprocess.Popen(
                [sys.executable, "-m",
                 "paddle_tpu.distributed.checkpoint.ft_selftest",
                 "--victim", root],
                stdout=subprocess.PIPE, text=True,
                cwd=os.getcwd(), env=dict(os.environ))
            # let it commit at least one step, then kill at a random
            # moment inside the save cadence
            first = child.stdout.readline()
            assert first.startswith("committed"), first
            time.sleep(inj.uniform(0.0, 0.25))
            faults.fire("proc.sigkill", trial=trial, pid=child.pid)
            child.send_signal(signal.SIGKILL)
            child.wait()
            committed = [int(ln.split()[1])
                         for ln in [first] + child.stdout.read().split("\n")
                         if ln.startswith("committed")]
            # a *.tmp_* dir left behind == the kill landed mid-save
            if any(".tmp_" in n for n in os.listdir(root)):
                mid_save_hits += 1
            extra = _victim_state(0)
            mgr = CheckpointManager(root, extra_state=extra)
            got = mgr.restore_or_init()
            if got is None:
                raise AssertionError(
                    f"trial {trial}: no restorable checkpoint (victim "
                    f"committed {committed})")
            verify_checkpoint(os.path.join(root, f"step_{got}"))
            # the pipe is a prefix of truth (the victim may have
            # committed once more between our last read and the kill)
            if committed and got < max(committed):
                raise AssertionError(
                    f"trial {trial}: restored {got} < last confirmed "
                    f"commit {max(committed)}")
            want = _victim_state(got)
            for k, v in want.items():
                if k == "step_scalar":
                    assert extra[k] == got, (extra[k], got)
                elif not np.array_equal(np.asarray(extra[k]), v):
                    raise AssertionError(
                        f"trial {trial}: tensor {k} corrupt after "
                        f"restore of step {got}")
        finally:
            shutil.rmtree(root, ignore_errors=True)
    faults.reset()
    return {"trials": trials, "mid_save_kills": mid_save_hits,
            "injected_kills": inj.hits.get("proc.sigkill", 0)}


def run_flip_lane(seed: int = 0):
    """One flipped byte in a chunk file -> manifest catches it, restore
    falls back to the previous committed step. The flip comes through
    the manager's armed ``ckpt.chunk.flip`` fault point (ISSUE 19) —
    one injection implementation, not an ad-hoc byte poke."""
    import shutil
    import tempfile

    from ...observability import faults
    from .load_state_dict import verify_checkpoint
    from .manager import CheckpointManager
    from .utils import CheckpointError

    root = tempfile.mkdtemp(prefix="ftflip_")
    inj = faults.install(seed)
    # the manager probes the point once per save: fire on the SECOND
    # save, so step_0 stays intact as the fallback target
    inj.arm("ckpt.chunk.flip", at=2)
    try:
        extra = _victim_state(0)
        mgr = CheckpointManager(root, extra_state=extra)
        for step in (0, 1):
            extra.clear()
            extra.update(_victim_state(step))
            mgr.save(step)
        assert inj.hits.get("ckpt.chunk.flip", 0) >= 2, inj.hits
        try:
            verify_checkpoint(os.path.join(root, "step_1"))
            return {"detected": False}
        except CheckpointError:
            pass
        extra2 = _victim_state(0)
        mgr2 = CheckpointManager(root, extra_state=extra2)
        got = mgr2.restore_or_init()
        ok = (got == 0 and extra2["step_scalar"] == 0
              and np.array_equal(np.asarray(extra2["w0"]),
                                 _victim_state(0)["w0"]))
        return {"detected": True, "fell_back_to": got, "ok": bool(ok)}
    finally:
        faults.reset()
        shutil.rmtree(root, ignore_errors=True)


def _tiny_gpt_step(seed=0, lr=1e-2):
    import itertools

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as popt
    import paddle_tpu.nn.layer.layers as _layers
    from paddle_tpu.jit import FusedScanTrainStep
    from paddle_tpu.models import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    # auto param names come from a process-global counter; a REAL resume
    # rebuilds the model in a fresh process (counter back at 0), so an
    # in-process restore rehearsal must reset it the same way for the
    # optimizer state keys to line up
    _layers._param_counter = itertools.count()

    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_attention_heads=2, max_position_embeddings=16,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                    scan_layers=True)
    paddle.seed(seed)
    model = GPTForCausalLM(cfg)
    opt = popt.AdamW(learning_rate=lr, parameters=model.parameters())
    step = FusedScanTrainStep(model, opt,
                              criterion=GPTPretrainingCriterion())
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, 96, (4, 12)), dtype="int64")
    labels = paddle.to_tensor(rng.integers(0, 96, (4, 12)),
                              dtype="int64")
    return model, opt, step, ids, labels


def run_resume_lane(async_save=True):
    """Save at step 2, restore into a FRESH model/optimizer, continue:
    the continued losses must be BIT-identical to an uninterrupted run,
    and async save must block only for the host snapshot."""
    import shutil
    import tempfile

    from .manager import CheckpointManager

    root = tempfile.mkdtemp(prefix="ftresume_")
    try:
        model, opt, step, ids, labels = _tiny_gpt_step()
        straight = [float(step(ids, labels)) for _ in range(5)]

        model, opt, step, ids, labels = _tiny_gpt_step()
        mgr = CheckpointManager(os.path.join(root, "ck"), model=model,
                                optimizer=opt, async_save=async_save)
        part1 = [float(step(ids, labels)) for _ in range(3)]
        mgr.save(2)
        mgr.wait()
        timings = dict(mgr.last_timings)

        model2, opt2, step2, ids, labels = _tiny_gpt_step(seed=123)
        step2.ensure_built()            # optimizer state slots exist
        mgr2 = CheckpointManager(os.path.join(root, "ck"), model=model2,
                                 optimizer=opt2)
        got = mgr2.restore_or_init()
        assert got == 2, got
        part2 = [float(step2(ids, labels)) for _ in range(2)]
        resumed = part1 + part2
        bit_identical = all(a == b for a, b in zip(straight, resumed))
        return {
            "bit_identical": bool(bit_identical),
            "straight": straight, "resumed": resumed,
            "async_blocked_ms": round(timings.get("blocked_s", 0) * 1e3,
                                      3),
            "async_snapshot_ms": round(
                timings.get("snapshot_s", 0) * 1e3, 3),
            "async_io_ms": round(timings.get("io_s", 0) * 1e3, 3),
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def main(argv):
    if "--victim" in argv:
        victim_main(argv[argv.index("--victim") + 1])
        return
    trials = (int(argv[argv.index("--trials") + 1])
              if "--trials" in argv else 8)
    rec = {"metric": "fault_tolerance_selftest"}
    try:
        rec["kill"] = run_kill_lane(trials=trials)
        rec["flip"] = run_flip_lane()
        rec["resume"] = run_resume_lane()
        ok = (rec["flip"].get("detected") and rec["flip"].get("ok")
              and rec["resume"]["bit_identical"])
        rec["check"] = "pass" if ok else f"FAIL: {rec}"[:400]
    except Exception as e:
        rec["check"] = f"FAIL: {type(e).__name__}: {e}"[:400]
    print(json.dumps(rec))


if __name__ == "__main__":
    main(sys.argv[1:])
