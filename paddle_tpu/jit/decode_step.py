"""Compiled, retrace-free generation: prefill/decode split over a KV cache.

The serving-side sibling of train_step.py: the eager dygraph decode step
(embedding, N cached-attention blocks, LM head, sampling) is traced ONCE
into a jitted function over a (params, cache-state) pytree and then
executed as one fused XLA program per generated token, with the big KV
buffers DONATED so steady-state decoding is allocation-free. Everything
that varies per step — the token ids, the write position, the RNG key —
is a traced input, so nothing retraces and nothing recompiles after the
first step (the `trace_count` probe asserts exactly that in tests).

Prefill is the separate compile: the prompt is padded to a length
BUCKET (powers-of-two by default) and run through the full causal
forward (the flash/SDPA path) once while every layer's K/V is written
into the cache. jax.jit's shape-keyed executable cache gives one
program per bucket; the true prompt length is a traced scalar/vector,
so any prompt inside a bucket reuses its program.

Cache state is threaded as TWO pytrees: the KV pool buffers (donated —
they are the HBM-dominant part and are consumed functionally every
step) and the small metadata (positions, page tables, seq_lens — NOT
donated, because the host-side continuous-batching bookkeeping reads
and rewrites page tables between steps and a donated buffer would be
dead by then).

Two cache shapes (inference/kv_cache.py): "dense" (aligned batch, one
dynamic_update_slice per layer per step) and "paged" (ragged seq_lens +
page-pool cache in the Ragged-Paged-Attention layout, slot allocate/
free continuous-batching bookkeeping on the host side).
"""
from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
import numpy as np

import time

from ..framework.autograd import no_grad
from ..framework.tensor import Tensor
from ..nn.functional.sampling import sample_logits, sample_logits_per_slot
from ..observability import RetraceSentinel
from ..observability import enabled as _obs_enabled
from ..observability import registry as _obs_registry
from .train_step import _tree_data, _tree_wrap

__all__ = ["GenerationEngine", "DecodeStep", "PrefillStep",
           "ChunkPrefillStep", "ServeDecodeStep",
           "DEFAULT_PREFILL_BUCKETS"]

DEFAULT_PREFILL_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048)

_BUFFER_KEYS = {"dense": ("layers",), "paged": ("k_layers", "v_layers")}


def _legacy_jax():
    return getattr(sys.modules.get("paddle_tpu"), "jax_compat_legacy",
                   False)


def _split_state(kind, state):
    buf_keys = _BUFFER_KEYS[kind]
    return ({k: state[k] for k in buf_keys},
            {k: v for k, v in state.items() if k not in buf_keys})


class _Step:
    """Shared machinery: trace counting, jit/eager dispatch, donation."""

    # serving steps set this: the continuous-batching bookkeeping
    # rewrites SOME metadata leaves between calls (a freed slot pulls
    # seq_lens to host, an untouched step leaves it on device), and a
    # call-to-call varying numpy/device mix PER LEAF keys a fresh
    # executable per combination (measured: silent mid-serve
    # recompiles). Pinning every leaf to host numpy = one cache key;
    # the D2H is a few hundred bytes on arrays the serving loop reads
    # synchronously anyway. The GenerationEngine steps keep it off —
    # their meta leaves are already call-to-call consistent, and the
    # pull-down would serialize decode dispatch per token.
    _pin_meta_host = False
    # sentinel config (ISSUE 12): argument names for attribution, and
    # the args whose SHAPE legitimately varies (prefill length buckets
    # — one expected executable per bucket)
    _arg_names = ()
    _bucketed_args = ()

    def __init__(self, engine, donate_cache):
        self.engine = engine
        # donation is a pure perf lever; the legacy jaxlib (0.4.x CPU)
        # corrupts donated buffers under real program sizes (see
        # TrainStep), so it is forced off there
        self._donate = (donate_cache and engine.compiled
                        and not _legacy_jax())
        self._jitted = None
        self.trace_count = 0   # traces when compiled, calls when eager
        self._sentinel = RetraceSentinel(type(self).__name__,
                                         bucketed=self._bucketed_args)
        # per-call DISPATCH time (enqueue, not device completion —
        # results stay async) on the PROCESS-GLOBAL registry, keyed by
        # step class: a whole-process view (concurrent engines share
        # one histogram, like the global serving.queue_depth mirror) —
        # per-request timing lives on the engine's trace spans. One
        # cached histogram object: ~1µs observe, no registry lookup.
        self._obs_on = _obs_enabled()
        self._dispatch_hist = (_obs_registry().histogram(
            f"jit.{type(self).__name__}.dispatch_ms")
            if self._obs_on else None)

    def _fn(self, *args):
        raise NotImplementedError

    def retrace_stats(self):
        """Sentinel receipt: distinct signatures (= expected compiles),
        cache hits, and attributed unexpected recompiles."""
        return self._sentinel.stats()

    def cache_size(self):
        """Number of compiled executables (jax.jit's cache), -1 when the
        runtime does not expose it."""
        if self._jitted is None:
            return 0
        try:
            return self._jitted._cache_size()
        except Exception:
            return -1

    def lowered_text(self, *args):
        """StableHLO/HLO text of the step for the given example args
        (compile-guard tests grep this for dynamic-update-slice).
        Traces a fresh copy — neither the live jit cache nor the
        trace_count probe is affected."""
        saved = self.trace_count
        try:
            return jax.jit(self._fn).lower(*args).as_text()
        finally:
            self.trace_count = saved

    def memory_profile(self, *args, top_k=8, publish=True):
        """Compiled-step HBM accounting (ISSUE 14): AOT buffer-
        assignment stats of this step program for the given example
        args — with the REAL donation config, so the KV pools show up
        as alias bytes, not double-counted temps. Traces a fresh jit
        copy (an AOT analysis must not perturb the live executable
        cache or the trace_count probe); publishes
        ``mem.compiled.<step>.*`` gauges."""
        from ..observability.memory import CompiledMemoryProfile

        saved = self.trace_count
        try:
            jitted = jax.jit(
                self._fn, donate_argnums=(1,) if self._donate else ())
            prof = CompiledMemoryProfile.from_jitted(jitted, *args,
                                                     top_k=top_k)
        finally:
            self.trace_count = saved
        if publish:
            prof.publish(name=type(self).__name__)
        return prof

    def _dispatch(self, args):
        """The guarded compiled call: a RESOURCE_EXHAUSTED here dumps
        compiled + live memory forensics through the flight recorder
        before re-raising (observability.memory; ISSUE 14)."""
        try:
            return self._jitted(*args)
        except Exception as e:
            from ..observability import memory as _mem

            if _mem.is_oom_error(e):
                _mem.dump_oom(
                    e, step=type(self).__name__,
                    profile=lambda: self.memory_profile(
                        *args, publish=False))
            raise

    def __call__(self, *args):
        if not self.engine.compiled:
            # eager: the paged metadata lives as host numpy between
            # steps and the step bodies index it with `.at[]` — lift
            # it to jax arrays (a no-op for leaves already on device)
            args = list(args)
            args[2] = {k: jnp.asarray(v) for k, v in args[2].items()}
            return self._fn(*args)
        if self._jitted is None:
            self._jitted = jax.jit(
                self._fn,
                donate_argnums=(1,) if self._donate else ())
        if self._pin_meta_host:
            args = list(args)
            args[2] = {k: np.asarray(v) for k, v in args[2].items()}
        # the exact post-pinning call args — a numpy/device mix drift
        # in the metadata (the PR-6 silent-recompile class) shows up
        # here as an attributed placement/kind change
        self._sentinel.observe(tuple(args), names=self._arg_names)
        if self._dispatch_hist is None:
            return self._dispatch(args)
        tc0 = self.trace_count
        t0 = time.perf_counter()
        out = self._dispatch(args)
        # a call that TRACED just paid compile time (minutes for big
        # models) — one such sample would permanently skew a histogram
        # whose steady-state entries are ~1ms, so only steady-state
        # dispatches are recorded
        if self.trace_count == tc0:
            self._dispatch_hist.observe(
                (time.perf_counter() - t0) * 1e3)
        return out

    # -- shared step body helpers ---------------------------------------
    def _enter(self, params, buffers, meta):
        eng = self.engine
        for p, d in zip(eng._params, params):
            p._data = d
        eng.cache.load_state(_tree_wrap({**buffers, **meta}))

    def _exit_state(self):
        """Read back + split the cache state produced by the step."""
        return _split_state(self.engine.kind,
                            _tree_data(self.engine.cache.state()))

    def _sample(self, logits, key):
        eng = self.engine
        if eng.do_sample:
            key, sub = jax.random.split(key)
            ids = sample_logits(logits, key=sub,
                                temperature=eng.temperature,
                                top_k=eng.top_k, top_p=eng.top_p)
        else:
            ids = sample_logits(logits, key=None)
        return ids, key


class _BindCtx:
    """Snapshot the live params/cache for the duration of one trace and
    restore the concrete state after (a tracing error must not leave
    tracers bound in the model — same contract as TrainStep)."""

    def __init__(self, engine):
        self.engine = engine

    def __enter__(self):
        eng = self.engine
        self._saved_params = [p._data for p in eng._params]
        self._saved_cache = eng.cache.state()
        return self

    def __exit__(self, *exc):
        eng = self.engine
        for p, d in zip(eng._params, self._saved_params):
            p._data = d
        eng.cache.load_state(self._saved_cache)
        return False


class PrefillStep(_Step):
    """Bucketed prompt pass: write all layers' K/V, sample token 0."""

    _arg_names = ("params", "buffers", "meta", "ids", "lens",
                  "slot_ids", "key")
    _bucketed_args = ("ids",)

    def _fn(self, params, buffers, meta, ids, lens, slot_ids, key):
        self.trace_count += 1
        eng = self.engine
        with no_grad(), _BindCtx(eng):
            self._enter(params, buffers, meta)
            cache = eng.cache
            b = ids.shape[0]
            lens_b = jnp.broadcast_to(lens.reshape(-1), (b,)) \
                .astype(jnp.int32)
            hidden = eng.model.gpt.prefill(
                Tensor._wrap(ids), cache,
                seq_lens=Tensor._wrap(lens_b),
                slot_ids=Tensor._wrap(slot_ids))
            # last VALID position per row (traced -> bucket-stable)
            last = jnp.take_along_axis(
                hidden._data, (lens_b - 1)[:, None, None]
                .astype(jnp.int32), axis=1)[:, 0]        # [b, h]
            logits = eng.model.head(Tensor._wrap(last))._data
            if cache.kind == "dense":
                cache.pos = Tensor._wrap(
                    lens.reshape(()).astype(jnp.int32))
            else:
                sl = _data_of(cache.seq_lens)
                cache.seq_lens = Tensor._wrap(
                    sl.at[slot_ids].set(lens_b))
            ids_next, key = self._sample(logits, key)
            new_buffers, new_meta = self._exit_state()
        return ids_next, logits, new_buffers, new_meta, key


class DecodeStep(_Step):
    """One-token cached decode step — compiled once, donated KV pools."""

    _arg_names = ("params", "buffers", "meta", "tokens", "key")

    def _fn(self, params, buffers, meta, tokens, key):
        self.trace_count += 1
        eng = self.engine
        with no_grad(), _BindCtx(eng):
            self._enter(params, buffers, meta)
            cache = eng.cache
            b = tokens.shape[0]
            if cache.kind == "dense":
                pos_ids = jnp.broadcast_to(
                    _data_of(cache.pos).reshape(1, 1),
                    (b, 1)).astype(jnp.int32)
            else:
                pos_ids = _data_of(cache.seq_lens)[:, None] \
                    .astype(jnp.int32)
            hidden = eng.model.gpt.decode_step(
                Tensor._wrap(tokens.reshape(b, 1)), cache,
                Tensor._wrap(pos_ids))
            logits = eng.model.head(hidden)._data[:, 0]   # [b, vocab]
            # advance the write positions
            if cache.kind == "dense":
                cache.pos = Tensor._wrap(_data_of(cache.pos) + 1)
            else:
                sl = _data_of(cache.seq_lens)
                act = _data_of(cache.active)
                cache.seq_lens = Tensor._wrap(
                    jnp.where(act, sl + 1, sl))
            ids_next, key = self._sample(logits, key)
            new_buffers, new_meta = self._exit_state()
        return ids_next, logits, new_buffers, new_meta, key


def _data_of(x):
    return x._data if isinstance(x, Tensor) else x


# ---------------------------------------------------------------------------
# serving-tier steps (paddle_tpu/serving): chunked prefill + per-slot RNG
# ---------------------------------------------------------------------------

class ChunkPrefillStep(_Step):
    """One bounded chunk of one prompt (continuous batching): write the
    chunk's K/V at positions [start, start+c) of its slot, attending
    over the context cached so far, and sample the prefill-complete
    token with the request's OWN RNG stream.

    Chunks are padded to a small set of chunk buckets, so jax.jit's
    shape-keyed cache holds one program per bucket and long prompts
    interleave with decode steps at a bounded per-chunk cost (TTFT for
    resident sequences stays bounded while a long prompt prefills).
    The sampled token is only meaningful when this was the final chunk
    — the host discards it otherwise. Paged cache only."""

    _pin_meta_host = True
    _arg_names = ("params", "buffers", "meta", "ids", "slot_ids",
                  "start", "lens_new", "seeds")
    _bucketed_args = ("ids",)

    def _fn(self, params, buffers, meta, ids, slot_ids, start, lens_new,
            seeds):
        self.trace_count += 1
        eng = self.engine
        with no_grad(), _BindCtx(eng):
            self._enter(params, buffers, meta)
            cache = eng.cache
            hidden = eng.model.gpt.prefill_chunk(
                Tensor._wrap(ids), cache, Tensor._wrap(slot_ids),
                Tensor._wrap(start), Tensor._wrap(lens_new))
            # last VALID chunk position per row (traced, bucket-stable)
            last = jnp.take_along_axis(
                hidden._data,
                (lens_new - start - 1)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]                             # [b, h]
            logits = eng.model.head(Tensor._wrap(last))._data
            sl = _data_of(cache.seq_lens)
            cache.seq_lens = Tensor._wrap(
                sl.at[slot_ids].set(lens_new))
            # sample position = total context length after this chunk —
            # identical to what the decode step would use at the same
            # context, which is what makes preempt-resume re-prefill
            # reproduce the original stream (exactly, wherever this
            # path's logits match the decode path's — bitwise on the
            # shared XLA fallback; kernel-level numerics on chip)
            ids_next = sample_logits_per_slot(
                logits, seeds, lens_new, temperature=eng.temperature,
                top_k=eng.top_k, top_p=eng.top_p,
                greedy=not eng.do_sample)
            new_buffers, new_meta = self._exit_state()
        return ids_next, logits, new_buffers, new_meta


class ServeDecodeStep(_Step):
    """`decode_burst` one-token decode steps over the full slot batch,
    fused into ONE compiled program: one dispatch + one host sync
    yields k tokens per slot (multi-step scheduling — the per-call
    host cost is what dominates a continuous-batching loop on small
    steps). Sampling uses PER-SLOT RNG streams: slot i samples with
    fold_in(PRNGKey(seeds[i]), ctx_len_i), so a request's tokens are
    bit-reproducible no matter which other sequences share the batch
    (admissions/retirements around it cannot perturb its stream).
    Inactive slots (free, or still chunk-prefilling) write to the
    trash page, attend nothing and keep their seq_lens — their sampled
    output is garbage the host discards. A slot whose request finishes
    mid-burst saturates its seq_len at the engine window and writes
    past its reserved pages onto the trash page — more host-discarded
    garbage."""

    _pin_meta_host = True
    _arg_names = ("params", "buffers", "meta", "tokens", "seeds")

    def _fn(self, params, buffers, meta, tokens, seeds):
        self.trace_count += 1
        eng = self.engine
        with no_grad(), _BindCtx(eng):
            self._enter(params, buffers, meta)
            cache = eng.cache
            b = tokens.shape[0]
            cur, toks = tokens, []
            # unrolled: burst length is a small engine constant, so
            # this stays one trace / one executable
            for _ in range(eng.decode_burst):
                pos_ids = _data_of(cache.seq_lens)[:, None] \
                    .astype(jnp.int32)
                hidden = eng.model.gpt.decode_step(
                    Tensor._wrap(jnp.reshape(cur, (b, 1))), cache,
                    Tensor._wrap(pos_ids))
                logits = eng.model.head(hidden)._data[:, 0]  # [b, v]
                sl = _data_of(cache.seq_lens)
                act = _data_of(cache.active)
                new_sl = jnp.where(act,
                                   jnp.minimum(sl + 1, eng.max_len), sl)
                cache.seq_lens = Tensor._wrap(new_sl)
                cur = sample_logits_per_slot(
                    logits, seeds, new_sl, temperature=eng.temperature,
                    top_k=eng.top_k, top_p=eng.top_p,
                    greedy=not eng.do_sample)
                toks.append(cur)
            new_buffers, new_meta = self._exit_state()
        return jnp.stack(toks), logits, new_buffers, new_meta


class GenerationEngine:
    """Prefill + decode orchestration over one (model, cache) pair.

    Construction picks the cache shape; `generate()` runs prompt ->
    tokens end to end. The jitted steps live on the engine, so holding
    an engine (models cache them per signature, GPTForCausalLM.generate)
    means steady-state decoding never retraces or recompiles.
    """

    def __init__(self, model, kind="dense", batch=1, max_len=128,
                 do_sample=False, top_k=0, top_p=1.0, temperature=1.0,
                 compiled=True, cache_dtype=None, page_size=16,
                 prefill_buckets=DEFAULT_PREFILL_BUCKETS, donate=True):
        cfg = model.config
        model.gpt._check_decodable()
        if max_len > cfg.max_position_embeddings:
            raise ValueError(
                f"max_len={max_len} exceeds max_position_embeddings="
                f"{cfg.max_position_embeddings}")
        self.model = model
        self.kind = kind
        self.batch = batch
        self.max_len = max_len
        self.do_sample = bool(do_sample)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.temperature = float(temperature)
        self.compiled = bool(compiled)
        # buckets must COVER max_len: a prompt between the largest
        # power-of-two bucket and max_len is within capacity and must
        # not fall through _bucket()
        buckets = tuple(sorted(bkt for bkt in prefill_buckets
                               if bkt <= max_len))
        if not buckets or buckets[-1] < max_len:
            buckets = buckets + (max_len,)
        self.prefill_buckets = buckets
        self._params = list(model.parameters())
        if kind not in ("dense", "paged"):
            raise ValueError(f"unknown cache kind {kind!r}")
        self._cache_dtype = cache_dtype or jnp.float32
        self._page_size = page_size
        self.cache = self._make_cache()
        self.prefill_step = PrefillStep(self, donate_cache=donate)
        self.decode_step = DecodeStep(self, donate_cache=donate)
        # live-buffer attribution (ISSUE 14): a decode-only process has
        # no train step to claim the model weights (the cache claims
        # its own pools)
        from ..observability.memory import live_registry

        live_registry().track(self)

    def _mem_owners(self):
        # shard-backed params (a sharded-storage train step sharing
        # this model) are skipped: reading them would GATHER on scrape,
        # and the owning step already claims the shards
        return {"params": [p._data for p in self._params
                           if not getattr(type(p), "_shard_backed",
                                          False)]}

    def _make_cache(self):
        """Fresh cache with this engine's geometry — also the recovery
        path when a failed generate leaves donated buffers dead."""
        from ..inference.kv_cache import DenseKVCache, PagedKVCache

        cfg = self.model.config
        nh = cfg.num_attention_heads
        hd = cfg.hidden_size // nh
        if self.kind == "dense":
            return DenseKVCache(cfg.num_layers, self.batch,
                                self.max_len, nh, hd,
                                dtype=self._cache_dtype)
        pages_per_seq = -(-self.max_len // self._page_size)
        return PagedKVCache(
            cfg.num_layers, nh, hd,
            num_pages=1 + self.batch * pages_per_seq,
            page_size=self._page_size, max_slots=self.batch,
            pages_per_seq=pages_per_seq, dtype=self._cache_dtype)

    # -- memory observability (ISSUE 14) ---------------------------------
    def memory_profile(self, top_k=8, publish=True):
        """Compiled decode-step memory profile for THIS engine's
        geometry (model params + KV pools + metadata at the live
        shapes) — see `_Step.memory_profile`."""
        buffers, meta = _split_state(self.kind,
                                     _tree_data(self.cache.state()))
        tok = jnp.zeros((self.batch,), jnp.int32)
        key = jax.random.PRNGKey(0)
        return self.decode_step.memory_profile(
            self._param_data(), buffers, meta, tok, key,
            top_k=top_k, publish=publish)

    # -- helpers ---------------------------------------------------------
    def _bucket(self, s):
        for bkt in self.prefill_buckets:
            if bkt >= s:
                return bkt
        raise ValueError(
            f"prompt length {s} exceeds the largest prefill bucket "
            f"{self.prefill_buckets[-1]} (max_len {self.max_len})")

    def _param_data(self):
        return [p._data for p in self._params]

    def generate(self, input_ids, max_new_tokens, seq_lens=None,
                 eos_token_id=None, seed=None, return_logits=False):
        """input_ids: [batch, prompt] int array (right-padded when
        `seq_lens` gives ragged true lengths — paged cache only).
        Returns int32 Tensor [batch, max_new_tokens] (plus the per-step
        logits [batch, max_new_tokens, vocab] when return_logits)."""
        ids = np.asarray(input_ids)
        b, s = ids.shape
        if b != self.batch:
            raise ValueError(f"engine batch {self.batch}, got {b}")
        if s + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {s} + {max_new_tokens} new tokens exceeds the "
                f"engine max_len {self.max_len}")
        cache = self.cache
        lens = (np.full((b,), s, np.int32) if seq_lens is None
                else np.asarray(seq_lens, np.int32).reshape(b))
        slots = list(range(b))
        if self.kind == "dense":
            if len(set(lens.tolist())) > 1:
                raise ValueError(
                    "the dense cache needs an aligned batch (one shared "
                    "prompt length); use use_cache='paged' for ragged "
                    "prompts")
            cache.pos = jnp.zeros((), jnp.int32)
            lens_in = jnp.asarray(lens[0], jnp.int32)
        else:
            # fresh slots for this batch (continuous-batching entry)
            for slot in list(cache._slot_pages):
                cache.free(slot)
            slots = [cache.allocate(int(L)) for L in lens]
            lens_in = jnp.asarray(lens, jnp.int32)
        slot_arr = jnp.asarray(slots, jnp.int32)

        bucket = self._bucket(s)
        if bucket > s:
            ids = np.concatenate(
                [ids, np.zeros((b, bucket - s), ids.dtype)], axis=1)
        if seed is None:
            # draw from the framework RNG stream (eager sampling
            # semantics): repeated sampled generates must differ
            from ..framework import random as _random

            key = _random.next_key()
        else:
            key = jax.random.PRNGKey(int(seed))
        buffers, meta = _split_state(self.kind,
                                     _tree_data(cache.state()))
        try:
            tok, logits, buffers, meta, key = self.prefill_step(
                self._param_data(), buffers, meta, jnp.asarray(ids),
                lens_in, slot_arr, key)
            toks, logit_steps = [tok], [logits]
            cur = lens.copy()
            for _ in range(int(max_new_tokens) - 1):
                if self.kind == "paged":
                    # grow page tables on demand (host bookkeeping;
                    # the device table is just a refreshed input, not
                    # a retrace)
                    for j, slot in enumerate(slots):
                        cache.reserve(slot, int(cur[j]) + 1)
                    meta["page_tables"] = cache.page_tables
                tok, logits, buffers, meta, key = self.decode_step(
                    self._param_data(), buffers, meta, tok, key)
                toks.append(tok)
                if return_logits:
                    logit_steps.append(logits)
                cur += 1
            cache.load_state({**buffers, **meta})
        except BaseException:
            # the steps DONATE the KV buffers, and the model keeps this
            # engine cached — an abort mid-loop would leave the cache
            # pointing at consumed buffers, so rebuild it pristine
            self.cache = self._make_cache()
            raise
        if self.kind == "paged":
            for slot in slots:
                cache.free(slot)
        out = np.stack([np.asarray(t) for t in toks], axis=1)
        if eos_token_id is not None:
            done = np.zeros((b,), bool)
            for t in range(out.shape[1]):
                out[done, t] = eos_token_id
                done |= out[:, t] == eos_token_id
        out_t = Tensor._wrap(jnp.asarray(out.astype(np.int32)))
        if return_logits:
            logits_arr = np.stack(
                [np.asarray(lg, np.float32) for lg in logit_steps],
                axis=1)
            return out_t, Tensor._wrap(jnp.asarray(logits_arr))
        return out_t
