"""Checkpoint metadata types.

Reference parity: python/paddle/distributed/checkpoint/metadata.py —
``Metadata`` maps every logical tensor to the list of saved chunks
(``LocalTensorMetadata``: global offset + local shape) and each chunk to the
file that holds it (``storage_metadata``). The TPU build keys chunks by
their global index ranges taken from ``jax.Array.addressable_shards``
instead of process-group ranks.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple


@dataclass(frozen=True)
class LocalTensorMetadata:
    """One saved chunk of a logical tensor (global placement + dtype)."""

    global_offset: Tuple[int, ...]
    local_shape: Tuple[int, ...]
    dtype: str


@dataclass(frozen=True)
class LocalTensorIndex:
    """Key of a chunk: (tensor name, global offset)."""

    tensor_key: str
    global_offset: Tuple[int, ...]


@dataclass
class Metadata:
    """Global checkpoint manifest (written once, by the coordinator).

    ``file_checksums`` maps every chunk file to its ``(crc32, size)`` at
    write time: a reader verifies bytes before trusting a chunk, and the
    manager's ``restore_or_init`` uses it to reject a checkpoint whose
    files were truncated or flipped after commit. Metadata pickled before
    the field existed unpickles without it — readers use
    ``getattr(meta, "file_checksums", {})``.
    """

    state_dict_metadata: Dict[str, List[LocalTensorMetadata]] = field(
        default_factory=dict)
    storage_metadata: Dict[LocalTensorIndex, str] = field(
        default_factory=dict)
    flat_mapping: Dict[str, Tuple[str, ...]] = field(default_factory=dict)
    file_checksums: Dict[str, Tuple[int, int]] = field(
        default_factory=dict)
