"""Full hybrid parallelism (ISSUE 8): dp×mp Megatron tensor sharding +
the dp×pp ring pipeline over the sharded fused scan, planner-picked
layouts. Runs on the conftest 8-virtual-CPU-device host mesh."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as popt
from paddle_tpu.distributed import env as denv
from paddle_tpu.jit import (
    PipelineScanTrainStep, ShardedFusedScanTrainStep, TrainStep,
    select_train_step,
)
from paddle_tpu.models import (
    GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
)

TINY = dict(vocab_size=96, hidden_size=32, num_layers=4,
            num_attention_heads=2, max_position_embeddings=16,
            hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
N_DEV = 8
LOSS_TOL = 5e-4          # the sharded_scan_selftest parity bar
PARAM_REL_TOL = 5e-3
PARAM_ABS = 5e-4


@pytest.fixture(autouse=True)
def _clean_mesh():
    denv.reset()
    yield
    denv.reset()


def _devs(n=N_DEV):
    devs = jax.devices("cpu")[:n]
    if len(devs) < n:
        pytest.skip(f"needs {n} virtual cpu devices")
    return devs


def _batch(bs=N_DEV, seq=12, vocab=96, seed=0):
    rng = np.random.default_rng(seed)
    return (paddle.to_tensor(rng.integers(0, vocab, (bs, seq)),
                             dtype="int64"),
            paddle.to_tensor(rng.integers(0, vocab, (bs, seq)),
                             dtype="int64"))


def _build(step_kind, mesh=None, clip=True, steps=3, lr=1e-2,
           cfg_over=None, **kw):
    cfg = GPTConfig(**{**TINY, **(cfg_over or {})}, scan_layers=True)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    opt = popt.AdamW(learning_rate=lr, parameters=model.parameters(),
                     grad_clip=(nn.ClipGradByGlobalNorm(0.05) if clip
                                else None))
    if step_kind == "eager":
        step = TrainStep(model, lambda m, a, b: crit(m(a), b), opt)
    elif step_kind == "pipe":
        step = PipelineScanTrainStep(model, opt, criterion=crit,
                                     mesh=mesh, **kw)
    else:
        step = ShardedFusedScanTrainStep(model, opt, criterion=crit,
                                         mesh=mesh, **kw)
    ids, labels = _batch(vocab=cfg.vocab_size)
    losses = [float(step(ids, labels)) for _ in range(steps)]
    return losses, model, step


def _param_rel(m1, m2):
    """Worst allclose-style violation over all params: |a-b| measured
    against rtol*|a| + atol (atol 5e-5 — Adam's sqrt(v) amplifies
    float-noise-level grad differences on near-zero params into large
    RELATIVE drift that says nothing about parity)."""
    worst = 0.0
    for (_, p1), (_, p2) in zip(m1.named_parameters(),
                                m2.named_parameters()):
        a = np.asarray(p1._data, np.float32)
        b = np.asarray(p2._data, np.float32)
        denom = PARAM_REL_TOL * np.abs(a) + 5e-5
        worst = max(worst, float(np.max(np.abs(a - b) / denom)))
    return worst * PARAM_REL_TOL   # scaled so the threshold reads as rtol


def _ldiff(a, b):
    return max(abs(x - y) for x, y in zip(a, b))


# ---------------------------------------------------------------------------
# dp×mp: Megatron tensor sharding inside the scan
# ---------------------------------------------------------------------------

def test_dpmp_parity_vs_dp_only_and_eager():
    """dp4×mp2 loss/param trajectories match the dp-only sharded scan
    and the eager TrainStep within the selftest tolerances, with the
    global-norm clip ACTIVE (acceptance bar of ISSUE 8)."""
    devs = _devs()
    from jax.sharding import Mesh

    mesh_dp = Mesh(np.asarray(devs), ("sharding",))
    denv.set_mesh(mesh_dp)
    eager, m_e, _ = _build("eager")
    noclip, _, _ = _build("eager", clip=False)
    assert _ldiff(eager, noclip) > 10 * LOSS_TOL   # clip not inert
    dp_only, m_dp, _ = _build("sharded", mesh=mesh_dp, axis="sharding")

    mesh_mp = Mesh(np.asarray(devs).reshape(4, 2), ("dp", "mp"))
    denv.set_mesh(mesh_mp)
    dpmp, m_mp, step = _build("sharded", mesh=mesh_mp, axis="dp",
                              mp_axis="mp")
    assert step._axes == ("dp", "mp") and step._degree == 8
    assert _ldiff(dpmp, eager) < LOSS_TOL
    assert _ldiff(dpmp, dp_only) < LOSS_TOL
    assert _param_rel(m_e, m_mp) < PARAM_REL_TOL
    assert _param_rel(m_dp, m_mp) < PARAM_REL_TOL
    # optimizer state sharded 1/(dp*mp) on live shapes
    opt_flat = step._opt._accumulators["moment1"]["__scan_shard_s0__"]
    assert len(opt_flat.addressable_shards) == 8
    assert opt_flat.addressable_shards[0].data.shape[-1] * 8 \
        == opt_flat.shape[-1]


def test_dpmp_untied_vocab_parallel_head():
    """tie_word_embeddings=False routes the separate [H, V] lm_head
    through the vocab-parallel sharded CE (transposed row shard)."""
    devs = _devs()
    from jax.sharding import Mesh

    over = dict(tie_word_embeddings=False)
    mesh_dp = Mesh(np.asarray(devs), ("sharding",))
    denv.set_mesh(mesh_dp)
    eager, m_e, _ = _build("eager", cfg_over=over)
    mesh_mp = Mesh(np.asarray(devs).reshape(4, 2), ("dp", "mp"))
    denv.set_mesh(mesh_mp)
    dpmp, m_mp, _ = _build("sharded", mesh=mesh_mp, axis="dp",
                           mp_axis="mp", cfg_over=over)
    assert _ldiff(dpmp, eager) < LOSS_TOL
    assert _param_rel(m_e, m_mp) < PARAM_REL_TOL


def test_sharded_fused_ce_matches_full_fused_ce():
    """The vocab-parallel sharded fused CE == the full vocab-tiled CE,
    losses and BOTH grads — including the padded-tile case where padded
    columns alias the next rank's global vocab ids (the regression that
    motivated the in-kernel valid mask)."""
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.ops.pallas.fused_cross_entropy import (
        fused_cross_entropy, sharded_fused_cross_entropy,
    )

    devs = _devs(4)
    mesh = Mesh(np.asarray(devs), ("mp",))
    rng = np.random.default_rng(0)
    N, H, V, MP = 24, 16, 96, 4          # vloc=24 pads to the 128 tile
    h = jnp.asarray(rng.standard_normal((N, H)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((V, H)) * 0.1, jnp.float32)
    lbl = jnp.asarray(rng.integers(0, V, (N,)), jnp.int32).at[3].set(
        -100)
    vloc = V // MP

    def run(h, w, lbl):
        def body(h, w, lbl):
            r = jax.lax.axis_index("mp")
            wl = jax.lax.dynamic_slice_in_dim(w, r * vloc, vloc, 0)

            def f(h, wl):
                losses = sharded_fused_cross_entropy(h, wl, lbl,
                                                     r * vloc, "mp")
                m = (lbl != -100).astype(jnp.float32)
                return jnp.sum(losses * m) / jnp.maximum(jnp.sum(m),
                                                         1.0)

            loss, vjpf = jax.vjp(f, h, wl)
            dh, dwl = vjpf(jnp.float32(1.0))
            dh_sum = jax.lax.psum(dh, "mp") / MP
            dw_full = jax.lax.psum(jax.lax.dynamic_update_slice_in_dim(
                jnp.zeros_like(w), dwl, r * vloc, 0), "mp") / MP
            return loss, dh_sum, dw_full

        return jax.shard_map(body, mesh=mesh, in_specs=(P(), P(), P()),
                             out_specs=(P(), P(), P()),
                             check_vma=False)(h, w, lbl)

    loss_s, dh_s, dw_s = jax.jit(run)(h, w, lbl)

    def ref(h, w, lbl):
        losses = fused_cross_entropy(h, w, lbl)
        m = (lbl != -100).astype(jnp.float32)
        return jnp.sum(losses * m) / jnp.maximum(jnp.sum(m), 1.0)

    loss_r, (dh_r, dw_r) = jax.value_and_grad(ref, (0, 1))(h, w, lbl)
    assert abs(float(loss_s) - float(loss_r)) < 1e-6
    assert float(jnp.max(jnp.abs(dh_s - dh_r))) < 1e-6
    assert float(jnp.max(jnp.abs(dw_s - dw_r))) < 1e-6


def test_mp_hlo_grads_reduced_in_scan_no_full_gather():
    """HLO receipt for the acceptance criterion: the dp×mp program's
    grad reduce-scatters run over the FLATTENED dp+mp product (the mp
    assembly rides the data-parallel scatter — no separate mp grad
    all-reduce/gather), the in-block mp psums are all-reduces on the mp
    axis alone, and every all-gather is the update scan's param gather
    over dp+mp — there is NO mp-only or unclassified gather that a
    full-gradient assembly would show."""
    import importlib.util
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "hlo_overlap", os.path.join(root, "tools", "hlo_overlap.py"))
    hlo = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(hlo)

    devs = _devs()
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(devs).reshape(4, 2), ("dp", "mp"))
    denv.set_mesh(mesh)
    cfg = GPTConfig(**TINY, scan_layers=True)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    opt = popt.AdamW(learning_rate=1e-2, parameters=model.parameters(),
                     grad_clip=nn.ClipGradByGlobalNorm(0.05))
    step = ShardedFusedScanTrainStep(model, opt,
                                     criterion=GPTPretrainingCriterion(),
                                     mesh=mesh, axis="dp", mp_axis="mp")
    step.ensure_built()
    state = step._extract_state()
    ids = jnp.zeros((8, 12), jnp.int32)
    text = step._jitted.lower(state, jnp.float32(1e-2), ids, ids,
                              None).compile().as_text()
    v = hlo.analyze(text, axis_degrees={"dp": 4, "mp": 2})
    per = v["per_axis_counts"]
    assert per.get("mp", {}).get("all-reduce", 0) >= 2 * TINY[
        "num_layers"], per      # >= 2 row-parallel psums per layer
    assert per.get("dp+mp", {}).get("reduce-scatter", 0) >= 1, per
    # no grad traffic outside the classified patterns, and no gathers
    # anywhere but the flattened dp+mp param gather
    assert "other" not in per, per
    for label, kinds in per.items():
        if label != "dp+mp":
            assert "all-gather" not in kinds, per
    assert v["counts"].get("reduce-scatter", 0) == per["dp+mp"][
        "reduce-scatter"]


def test_mp_rejects_attention_dropout_and_custom_criterion():
    devs = _devs()
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(devs).reshape(4, 2), ("dp", "mp"))
    denv.set_mesh(mesh)
    cfg = GPTConfig(**{**TINY, "attention_dropout_prob": 0.1},
                    scan_layers=True)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    opt = popt.AdamW(learning_rate=1e-2, parameters=model.parameters())
    with pytest.raises(ValueError, match="attention dropout"):
        ShardedFusedScanTrainStep(model, opt, mesh=mesh, axis="dp",
                                  mp_axis="mp")
    cfg2 = GPTConfig(**TINY, scan_layers=True)
    paddle.seed(0)
    model2 = GPTForCausalLM(cfg2)
    opt2 = popt.AdamW(learning_rate=1e-2,
                      parameters=model2.parameters())
    with pytest.raises(ValueError, match="vocab-parallel"):
        ShardedFusedScanTrainStep(model2, opt2, mesh=mesh, axis="dp",
                                  mp_axis="mp",
                                  criterion=lambda a, b: a.sum())


# ---------------------------------------------------------------------------
# dp×pp: the ring pipeline schedule
# ---------------------------------------------------------------------------

def test_pipeline_parity_dp2pp2():
    """dp2×pp2 ring pipeline matches the eager TrainStep and the
    dp-only sharded scan within the selftest tolerances."""
    devs = _devs()
    from jax.sharding import Mesh

    mesh_dp = Mesh(np.asarray(devs), ("sharding",))
    denv.set_mesh(mesh_dp)
    eager, m_e, _ = _build("eager")
    mesh_pp = denv.build_mesh({"dp": 2, "pp": 2}, devices=devs[:4])
    denv.set_mesh(mesh_pp)
    pp, m_pp, step = _build("pipe", mesh=mesh_pp, num_micro=2)
    assert set(step._axes) == {"dp", "pp"}
    assert _ldiff(pp, eager) < LOSS_TOL
    assert _param_rel(m_e, m_pp) < PARAM_REL_TOL
    stats = step.schedule_stats()
    assert stats["pp"] == 2 and stats["virtual_stages_per_rank"] == 2
    assert stats["bubble_ratio"] == pytest.approx(1 / 3)


def test_pipeline_microbatch_grads_match_accumulated_single_stage():
    """The ring schedule's micro-batched gradient == the sequential
    single-stage accumulation of the same micro-batches (the
    TrainStep(accum_steps=k) contract): the degree-1 pp ring IS that
    accumulation loop. The LOSS is bit-identical; gradients agree to
    float-ulp level (<= 1e-7 — XLA fuses the ring and the sequential
    program differently, so last-ulp equality across the two compiled
    programs is not guaranteed; the schedule itself contributes exact
    zeros for bubble ticks and exact ppermute transport)."""
    ids, labels = _batch()

    def probe(pp, ndev):
        cfg = GPTConfig(**TINY, scan_layers=True)
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
        opt = popt.AdamW(learning_rate=1e-2,
                         parameters=model.parameters())
        mesh = denv.build_mesh({"dp": ndev // pp, "pp": pp},
                               devices=_devs(ndev))
        denv.set_mesh(mesh)
        step = PipelineScanTrainStep(model, opt,
                                     criterion=GPTPretrainingCriterion(),
                                     mesh=mesh, num_micro=4)
        loss, G, o = step.grads_probe(ids, labels)
        return (float(loss), [np.asarray(g) for g in G],
                [np.asarray(g) for g in o])

    l_ring, G_ring, o_ring = probe(2, 2)     # dp1×pp2 ring
    l_seq, G_seq, o_seq = probe(1, 1)        # dp1×pp1: sequential accum
    assert l_ring == l_seq                   # bit-identical loss
    for a, b in zip(G_ring + o_ring, G_seq + o_seq):
        assert float(np.max(np.abs(a - b))) <= 1e-7


def test_pipeline_rejects_bad_configs():
    devs = _devs()
    mesh = denv.build_mesh({"dp": 2, "pp": 2}, devices=devs[:4])
    denv.set_mesh(mesh)
    cfg = GPTConfig(**{**TINY, "hidden_dropout_prob": 0.1},
                    scan_layers=True)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    opt = popt.AdamW(learning_rate=1e-2, parameters=model.parameters())
    # dropout under pp is LEGAL since ISSUE 11 (per-(micro, stage) PRNG
    # offsets) — construction must succeed; the determinism/grad tests
    # live in tests/test_sharded_storage.py
    PipelineScanTrainStep(model, opt, mesh=mesh, num_micro=2)
    mesh3 = denv.build_mesh({"dp": 2, "pp": 3}, devices=devs[:6])
    denv.set_mesh(mesh3)
    cfg2 = GPTConfig(**TINY, scan_layers=True)
    paddle.seed(0)
    model2 = GPTForCausalLM(cfg2)
    opt2 = popt.AdamW(learning_rate=1e-2,
                      parameters=model2.parameters())
    with pytest.raises(ValueError, match="divisible by pp"):
        PipelineScanTrainStep(model2, opt2, mesh=mesh3, num_micro=2)


@pytest.mark.slow
def test_full_3d_hybrid_dp_mp_pp_parity():
    """The composition: dp2×mp2×pp2 (all three axes live) still matches
    the eager trajectory — the mp block slicing rides chunk_apply inside
    the pp ring, and grads scatter over the flattened 3-axis product."""
    devs = _devs()
    from jax.sharding import Mesh

    mesh_dp = Mesh(np.asarray(devs), ("sharding",))
    denv.set_mesh(mesh_dp)
    eager, m_e, _ = _build("eager")
    mesh = denv.build_mesh({"dp": 2, "mp": 2, "pp": 2}, devices=devs)
    denv.set_mesh(mesh)
    tri, m_t, step = _build("pipe", mesh=mesh, axis="dp", mp_axis="mp",
                            pp_axis="pp", num_micro=2)
    assert step._degree == 8 and len(step._axes) == 3
    assert _ldiff(tri, eager) < LOSS_TOL
    assert _param_rel(m_e, m_t) < PARAM_REL_TOL


# ---------------------------------------------------------------------------
# compile discipline
# ---------------------------------------------------------------------------

def test_one_compile_per_mesh_signature():
    """Repeated steps on one mesh signature reuse ONE executable for
    both hybrid classes (the retrace probes of the acceptance bar)."""
    devs = _devs()
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(devs).reshape(4, 2), ("dp", "mp"))
    denv.set_mesh(mesh)
    _, _, step = _build("sharded", mesh=mesh, axis="dp", mp_axis="mp",
                        steps=3)
    assert step._jitted._cache_size() == 1
    mesh_pp = denv.build_mesh({"dp": 2, "pp": 2}, devices=devs[:4])
    denv.set_mesh(mesh_pp)
    _, _, pstep = _build("pipe", mesh=mesh_pp, num_micro=2, steps=3)
    assert pstep._jitted._cache_size() == 1


# ---------------------------------------------------------------------------
# planner-picked layouts
# ---------------------------------------------------------------------------

def _spec(vocab=96, batch=8):
    from paddle_tpu.distributed.auto_tuner import spec_of_model

    cfg = GPTConfig(**{**TINY, "vocab_size": vocab}, scan_layers=True)
    return spec_of_model(cfg, global_batch=batch, seq_len=12)


def test_planner_picks_pruned_feasible_layout():
    """pick_layout returns a feasible (pruning-clean) layout covering
    all devices, ranked by the calibrated cost model — and prefers pure
    dp when collectives are expensive relative to compute (the host-
    mesh regime), mp when intra-chip links are effectively free."""
    from paddle_tpu.distributed.auto_tuner import pick_layout
    from paddle_tpu.distributed.auto_tuner.prune import prune_candidates

    slow_links = {"coll_lat_us": 500.0, "ici_gbps": 1e9,
                  "pp_tick_ms": 1.0, "peak_flops": 1e12}
    dec = pick_layout(_spec(), 8, backend=slow_links, env={})
    c = dec["candidate"]
    assert c.degree == 8 and c.pruned_reason is None
    assert prune_candidates([c], _spec(), 16.0)[0].pruned_reason is None
    assert dec["source"] == "planner" and len(dec["ranking"]) >= 3
    assert (c.dp, c.mp, c.pp) == (8, 1, 1)

    fast_links = {"coll_lat_us": 0.1, "ici_gbps": 4e11,
                  "pp_tick_ms": 1e-4, "peak_flops": 1e12}
    # a model too big per-chip forces splitting; with free links the
    # planner should reach for model parallelism, and the pick must
    # still be feasible under the HBM estimate it was pruned with
    big = _spec(vocab=96, batch=32)
    big.params = int(4e9)
    dec2 = pick_layout(big, 8, hbm_gb=16.0, backend=fast_links, env={})
    c2 = dec2["candidate"]
    assert c2.pruned_reason is None and c2.degree == 8
    assert c2.mp > 1 or c2.pp > 1 or c2.sharding_stage >= 1
    assert c2.estimated_mem_gb <= 16.0


def test_planner_env_override_and_infeasible_rejection():
    from paddle_tpu.distributed.auto_tuner import pick_layout
    from paddle_tpu.distributed.auto_tuner.select import LAYOUT_ENV

    dec = pick_layout(_spec(), 8, backend={"peak_flops": 1e12},
                      env={LAYOUT_ENV: "dp=4,mp=2"})
    c = dec["candidate"]
    assert (c.dp, c.mp, c.pp) == (4, 2, 1) and dec["source"] == "env"
    # infeasible forced layout fails loudly: 96 heads%5 etc — use mp=5
    with pytest.raises(ValueError, match="infeasible"):
        pick_layout(_spec(), 10, backend={},
                    env={LAYOUT_ENV: "dp=2,mp=5"})


def test_select_train_step_dispatch_and_auto():
    """Explicit meshes dispatch by active axes; auto=True plans, builds
    the mesh, and returns a runnable step carrying the decision."""
    devs = _devs()
    mesh_mp = denv.build_mesh({"dp": 4, "mp": 2}, devices=devs)
    denv.set_mesh(mesh_mp)
    cfg = GPTConfig(**TINY, scan_layers=True)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    opt = popt.AdamW(learning_rate=1e-2, parameters=model.parameters())
    crit = GPTPretrainingCriterion()
    step = select_train_step(model, opt, criterion=crit, mesh=mesh_mp)
    assert isinstance(step, ShardedFusedScanTrainStep)
    assert step._axes == ("dp", "mp")

    denv.reset()
    mesh_pp = denv.build_mesh({"dp": 2, "pp": 2}, devices=devs[:4])
    denv.set_mesh(mesh_pp)
    paddle.seed(0)
    model2 = GPTForCausalLM(cfg)
    opt2 = popt.AdamW(learning_rate=1e-2,
                      parameters=model2.parameters())
    step2 = select_train_step(model2, opt2, criterion=crit,
                              mesh=mesh_pp, num_micro=2)
    assert isinstance(step2, PipelineScanTrainStep)

    denv.reset()
    paddle.seed(0)
    model3 = GPTForCausalLM(cfg)
    opt3 = popt.AdamW(learning_rate=1e-2,
                      parameters=model3.parameters())
    step3 = select_train_step(model3, opt3, criterion=crit, auto=True,
                              global_batch=8)
    assert step3.layout_decision["candidate"].degree >= 1
    ids, labels = _batch()
    assert np.isfinite(float(step3(ids, labels)))


# ---------------------------------------------------------------------------
# fleet end-to-end wiring
# ---------------------------------------------------------------------------

def test_fleet_hybrid_end_to_end():
    """fleet.init(strategy) with mp_degree / pp_degree > 1 reaches the
    hybrid steps through distributed_model(...).train_step(...)."""
    import paddle_tpu.distributed.fleet as fleet

    _devs()
    ids, labels = _batch()
    crit = GPTPretrainingCriterion()
    cfg = GPTConfig(**TINY, scan_layers=True)

    strat = fleet.DistributedStrategy()
    strat.hybrid_configs.update({"dp_degree": 4, "mp_degree": 2})
    fleet.init(is_collective=True, strategy=strat)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    opt = popt.AdamW(learning_rate=1e-2, parameters=model.parameters())
    dm = fleet.distributed_model(model)
    step = dm.train_step(opt, criterion=crit)
    assert isinstance(step, ShardedFusedScanTrainStep)
    assert step._axes == ("dp", "mp")
    assert np.isfinite(float(step(ids, labels)))

    denv.reset()
    strat2 = fleet.DistributedStrategy()
    strat2.hybrid_configs.update({"dp_degree": 2, "pp_degree": 2})
    strat2.pipeline_configs = {"accumulate_steps": 2}
    fleet.init(is_collective=True, strategy=strat2)
    paddle.seed(0)
    model2 = GPTForCausalLM(cfg)
    opt2 = popt.AdamW(learning_rate=1e-2,
                      parameters=model2.parameters())
    dm2 = fleet.distributed_model(model2)
    assert type(dm2).__name__ == "HybridParallel"
    step2 = dm2.train_step(opt2, criterion=crit)
    assert isinstance(step2, PipelineScanTrainStep)
    assert step2._num_micro == 2          # strategy accumulate_steps
    assert np.isfinite(float(step2(ids, labels)))
