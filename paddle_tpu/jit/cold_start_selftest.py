"""Hermetic cold-start A/B probe (ISSUE 17): compile vs deserialize.

Run as ``python -m paddle_tpu.jit.cold_start_selftest`` in a clean
JAX_PLATFORMS=cpu subprocess (bench.py --selftest / --cold-start wires
this through the usual env-strip recipe) and prints ONE JSON line.

The probe spawns a PROCESS PAIR sharing one fresh compile-cache
directory — persistence claims need process death between write and
read, in-process "warm" numbers only measure jax's own caches:

- COLD child: empty cache. Builds the selftest GPT fused-scan train
  step + the paged decode engine, pays trace+COMPILE on first dispatch,
  serializes into the cache.
- WARM child: same code, same seeds, same cache dir. First dispatch
  trace+DESERIALIZES.

Gates (all land in the BENCH record):

- warm first train step <= ``ratio_gate`` x cold (default 0.5: the
  headline claim — at selftest scale compile is only ~2x the shared
  trace+lower cost, so passing here means real models, where compile
  dominates, do far better);
- warm served every program from the cache (>= 1 disk hit, 0 misses);
- BIT-IDENTICAL cold vs warm: train losses over 2 steps, the updated
  parameter checksum, and the greedy paged-decode token stream;
- retrace sentinel strict-clean in both children (no unexpected
  recompiles under the cache).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_CHILD = "--child"


def _workload(cache_dir):
    """One process's life: enable the cache, build + run the train and
    decode paths, report timings/outputs/cache traffic."""
    from .compile_cache import set_cache_dir

    cache = set_cache_dir(cache_dir)

    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as popt
    from ..models import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )
    from .fused_scan_step import FusedScanTrainStep

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=4,
                    num_attention_heads=4, max_position_embeddings=64,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                    scan_layers=True)
    model = GPTForCausalLM(cfg)
    opt = popt.AdamW(learning_rate=1e-3,
                     parameters=model.parameters())
    step = FusedScanTrainStep(model, opt,
                              criterion=GPTPretrainingCriterion())
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(1, cfg.vocab_size, (2, 64)),
                           dtype="int64")

    t0 = time.perf_counter()
    loss0 = float(step(ids, ids))
    first_train_ms = (time.perf_counter() - t0) * 1e3
    loss1 = float(step(ids, ids))
    psum = float(np.sum([np.asarray(p._data, np.float64).sum()
                         for p in model.parameters()]))

    # serve decode path (the jit/decode_step _Step programs)
    paddle.seed(1)
    dcfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                     num_attention_heads=4, max_position_embeddings=64,
                     hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    dm = GPTForCausalLM(dcfg)
    dm.eval()
    drng = np.random.default_rng(1)
    prompt = paddle.to_tensor(drng.integers(1, 64, (2, 8)),
                              dtype="int64")
    t0 = time.perf_counter()
    out = dm.generate(prompt, max_new_tokens=6, use_cache="paged")
    first_decode_ms = (time.perf_counter() - t0) * 1e3
    tokens = np.asarray(out._data).tolist()

    st = cache.stats() if cache is not None else {}
    return {
        "first_train_step_ms": round(first_train_ms, 1),
        "first_decode_ms": round(first_decode_ms, 1),
        "loss0": repr(loss0), "loss1": repr(loss1),
        "param_sum": repr(psum),
        "decode_tokens": tokens,
        "cache_hits": st.get("hits"), "cache_misses": st.get("misses"),
        "cache_entries": st.get("entries"),
        "train_sentinel": step.retrace_stats(),
    }


def run_probe(ratio_gate=0.5, timeout=600):
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="paddle_tpu_cold_start_")
    env = dict(os.environ)
    env.pop("PADDLE_TPU_COMPILE_CACHE", None)  # _workload sets its own
    runs = {}
    for phase in ("cold", "warm"):
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.jit.cold_start_selftest",
             _CHILD, cache_dir],
            env=env, capture_output=True, text=True, timeout=timeout)
        line = next((ln for ln in r.stdout.splitlines()
                     if ln.startswith("{")), None)
        if r.returncode != 0 or line is None:
            return {"check": f"FAIL: {phase} child rc={r.returncode}: "
                             f"{r.stderr[-300:]}"}
        runs[phase] = json.loads(line)
    cold, warm = runs["cold"], runs["warm"]

    ratio = warm["first_train_step_ms"] / max(
        cold["first_train_step_ms"], 1e-9)
    identical = all(cold[k] == warm[k] for k in
                    ("loss0", "loss1", "param_sum", "decode_tokens"))
    clean = (cold["train_sentinel"]["unexpected"] == 0
             and warm["train_sentinel"]["unexpected"] == 0)
    fails = []
    if ratio > ratio_gate:
        fails.append(f"warm/cold ratio {ratio:.3f} > {ratio_gate}")
    if not (cold["cache_misses"] and warm["cache_hits"]):
        fails.append("cache traffic wrong way (cold must miss, warm "
                     "must hit)")
    if warm["cache_misses"]:
        fails.append(f"warm process MISSED {warm['cache_misses']} "
                     "programs (unstable cache key)")
    if not identical:
        fails.append("cold vs warm outputs not bit-identical")
    if not clean:
        fails.append("retrace sentinel unexpected != 0")
    return {
        "cold_first_train_step_ms": cold["first_train_step_ms"],
        "warm_first_train_step_ms": warm["first_train_step_ms"],
        "warm_over_cold_ratio": round(ratio, 4),
        "ratio_gate": ratio_gate,
        "cold_first_decode_ms": cold["first_decode_ms"],
        "warm_first_decode_ms": warm["first_decode_ms"],
        "cached_programs": warm["cache_hits"],
        "warm_misses": warm["cache_misses"],
        "bit_identical": identical,
        "sentinel_clean": clean,
        "check": "pass" if not fails else "FAIL: " + "; ".join(fails),
    }


if __name__ == "__main__":
    if _CHILD in sys.argv:
        print(json.dumps(_workload(sys.argv[sys.argv.index(_CHILD) + 1])))
    else:
        print(json.dumps(run_probe()))
