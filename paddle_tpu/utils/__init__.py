"""Utility namespace (paddle.utils parity: flags, deprecated, download stub,
layers_utils map_structure/flatten)."""
from . import flags  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401


def try_import(module_name, err_msg=None):
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or f"required module {module_name} not found") from e


def deprecated(update_to="", since="", reason="", level=0):
    def decorator(fn):
        return fn

    return decorator


def run_check():
    """paddle.utils.run_check parity: verify the install can compute."""
    import jax

    import paddle_tpu as pt

    x = pt.ones([2, 2])
    y = (x @ x).sum()
    assert float(y) == 8.0
    devs = jax.devices()
    print(f"paddle_tpu is installed successfully! devices: {devs}")
    return True


# -- nested-structure helpers (python/paddle/utils/layers_utils.py parity) ---

def flatten(nest):
    out = []

    def _walk(x):
        if isinstance(x, (list, tuple)):
            for v in x:
                _walk(v)
        elif isinstance(x, dict):
            for k in sorted(x):
                _walk(x[k])
        else:
            out.append(x)

    _walk(nest)
    return out


def pack_sequence_as(structure, flat):
    it = iter(flat)

    def _pack(s):
        if isinstance(s, (list, tuple)):
            return type(s)(_pack(v) for v in s)
        if isinstance(s, dict):
            return {k: _pack(s[k]) for k in sorted(s)}
        return next(it)

    return _pack(structure)


def map_structure(func, *structures):
    flats = [flatten(s) for s in structures]
    mapped = [func(*vals) for vals in zip(*flats)]
    return pack_sequence_as(structures[0], mapped)


class download:
    @staticmethod
    def get_weights_path_from_url(url, md5sum=None):
        raise RuntimeError(
            "no network egress in this environment; pass local weight paths"
        )


class cpp_extension:
    """Stub of paddle.utils.cpp_extension; custom native ops use the
    csrc/ ctypes toolchain instead (see csrc/README)."""

    @staticmethod
    def load(name, sources, **kwargs):
        raise NotImplementedError(
            "use paddle_tpu.utils.cpp_build.build_extension (ctypes-based)"
        )


def require_version(min_version, max_version=None):
    """reference utils.require_version: assert the installed framework
    version is inside [min_version, max_version]."""
    from .. import version as _v

    import re as _re

    def parse(s):
        # numeric prefix of each dotted component ('0-tpu' -> 0); pad to
        # 3 so '3.0' vs '3.0.0' compare equal
        parts = []
        for p in str(s).split(".")[:3]:
            m = _re.match(r"\d+", p)
            parts.append(int(m.group()) if m else 0)
        while len(parts) < 3:
            parts.append(0)
        return tuple(parts)

    cur = parse(_v.full_version)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {_v.full_version} < required "
            f"{min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {_v.full_version} > allowed "
            f"{max_version}")
