"""Pallas TPU kernel pack — the fused-kernel library.

Reference parity: paddle/phi/kernels/fusion/ (~90k LoC of fused CUDA
kernels) and the flash-attn entry paddle/phi/kernels/gpu/flash_attn_kernel.cu.
TPU-first: the hot fused ops are hand-written Pallas kernels over the MXU
(flash attention here; more land as profiling demands), everything else is
left to XLA fusion.
"""
from . import flash_attention  # noqa: F401
from . import fused_cross_entropy  # noqa: F401
from . import paged_attention  # noqa: F401
from . import splash_attention  # noqa: F401
