"""Chaos-hardening tests (ISSUE 19).

Fast, fully scripted lanes against the process-global FaultInjector
and the self-healing fleet: trigger grammar (at/every/prob/times/
match), seeded determinism, log-vs-hits accounting, corrupt hand-off
blobs rejected by crc32 before allocation, per-request deadlines,
brown-out shedding below the healthy-capacity watermark, replica-kill
re-dispatch with bit-exact token parity, and hung-join accounting at
stop(). The randomized multi-seed churn sweep is marked ``slow``
(tier-1 runs only the deterministic lanes); the heavyweight recovery
lanes (stuck watchdog, elastic resume, MTTR measurement) live in the
bench ``chaos`` selftest, not here.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.observability import faults
from paddle_tpu.observability.faults import FaultError, FaultInjector
from paddle_tpu.serving import FleetRouter, ServingEngine
from paddle_tpu.serving.request import FinishReason, RequestState


@pytest.fixture(autouse=True)
def _quiet_faults():
    yield
    faults.reset()


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=96,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


KW = dict(max_slots=4, max_len=96, page_size=8, chunk_size=16,
          prefill_batch=2)


def _pin_sessions(target, others, n):
    from paddle_tpu.serving.router import rendezvous_score

    out, i = [], 0
    while len(out) < n:
        s = f"chaos{i}"
        i += 1
        if all(rendezvous_score(s, target) > rendezvous_score(s, o)
               for o in others):
            out.append(s)
    return out


def _fired(inj, point):
    """Firing count for one point (``hits`` counts every PROBE)."""
    return sum(1 for e in inj.log if e["point"] == point)


# ---------------------------------------------------------------------------
# FaultInjector trigger grammar
# ---------------------------------------------------------------------------

class TestFaultInjector:
    POINT = "serving.step.raise"

    def test_unknown_point_rejected(self):
        inj = FaultInjector()
        with pytest.raises(ValueError, match="unknown fault point"):
            inj.arm("serving.step.tpyo")

    def test_quiet_fast_path(self):
        faults.reset()
        assert faults.active() is None
        assert faults.fire(self.POINT) is None
        assert not faults.should_fire(self.POINT)
        assert faults.maybe_delay("serving.step.stuck") == 0.0
        faults.maybe_raise(self.POINT)   # no injector -> no raise

    def test_at_fires_on_exactly_the_nth_hit(self):
        inj = FaultInjector()
        inj.arm(self.POINT, at=3, times=None)
        fired = [inj.fire(self.POINT, {}) is not None
                 for _ in range(5)]
        assert fired == [False, False, True, False, False]
        assert inj.hits[self.POINT] == 5          # every probe counted
        assert _fired(inj, self.POINT) == 1       # one firing logged

    def test_at_accepts_a_set_of_hits(self):
        inj = FaultInjector()
        inj.arm(self.POINT, at=(2, 4), times=None)
        fired = [inj.fire(self.POINT, {}) is not None
                 for _ in range(5)]
        assert fired == [False, True, False, True, False]

    def test_every_kth_hit(self):
        inj = FaultInjector()
        inj.arm(self.POINT, every=2, times=None)
        fired = [inj.fire(self.POINT, {}) is not None
                 for _ in range(6)]
        assert fired == [False, True, False, True, False, True]

    def test_times_bounds_total_fires(self):
        inj = FaultInjector()
        inj.arm(self.POINT, every=1, times=2)
        fired = [inj.fire(self.POINT, {}) is not None
                 for _ in range(5)]
        assert fired == [True, True, False, False, False]

    def test_one_shot_default(self):
        inj = FaultInjector()
        inj.arm(self.POINT)
        assert inj.fire(self.POINT, {}) is not None
        assert inj.fire(self.POINT, {}) is None

    def test_prob_is_seed_deterministic(self):
        def schedule(seed):
            inj = FaultInjector(seed=seed)
            inj.arm(self.POINT, prob=0.5, times=None)
            return [inj.fire(self.POINT, {}) is not None
                    for _ in range(64)]

        a, b = schedule(7), schedule(7)
        assert a == b                      # same seed -> same schedule
        assert 0 < sum(a) < 64             # and it is genuinely random
        assert schedule(8) != a

    def test_match_restricts_to_context(self):
        inj = FaultInjector()
        spec = inj.arm(self.POINT, at=1, match={"engine": "d0"})
        assert inj.fire(self.POINT, {"engine": "d1"}) is None
        assert spec.seen == 0              # non-matching hits don't count
        assert inj.fire(self.POINT, {"engine": "d0"}) is spec
        assert inj.log[-1]["engine"] == "d0"

    def test_maybe_raise_and_delay(self):
        inj = faults.install(0)
        inj.arm(self.POINT, message="boom")
        with pytest.raises(FaultError, match="boom"):
            faults.maybe_raise(self.POINT)
        inj.arm("serving.step.stuck", delay_s=0.001)
        t0 = time.perf_counter()
        assert faults.maybe_delay("serving.step.stuck") == 0.001
        assert time.perf_counter() - t0 >= 0.001

    def test_summary_and_register(self):
        inj = FaultInjector(seed=3)
        inj.arm(self.POINT, at=1)
        inj.fire(self.POINT, {"engine": "d0"})
        s = inj.summary()
        assert s["seed"] == 3
        assert s["hits"] == {self.POINT: 1}
        assert s["fired"][0]["point"] == self.POINT
        assert s["armed"][0]["fired"] == 1
        p = faults.register("serving.step.raise", "idempotent")
        assert p in faults.FAULT_POINTS

    def test_flip_byte_is_a_single_bit(self):
        inj = FaultInjector(seed=1)
        buf = np.zeros(32, np.uint8)
        idx = inj.flip_byte(buf)
        assert buf[idx] == 0x01 and buf.sum() == 1
        inj.flip_byte(buf, index=idx)      # flip back
        assert buf.sum() == 0


# ---------------------------------------------------------------------------
# corrupt hand-off blobs die at the crc32 gate, before allocation
# ---------------------------------------------------------------------------

class TestCorruptBlob:
    def _cache(self):
        from paddle_tpu.inference.kv_cache import PagedKVCache

        return PagedKVCache(num_layers=2, num_kv_heads=2, head_dim=4,
                            num_pages=17, page_size=8, max_slots=4,
                            pages_per_seq=6)

    def test_flip_rejected_before_allocation(self):
        src = self._cache()
        slot = src.allocate(21)
        src._host("seq_lens")[slot] = 21
        blob = src.export_slot(slot)

        inj = faults.install(0)
        inj.arm("kv.handoff.corrupt")
        assert faults.corrupt_blob("kv.handoff.corrupt", blob)
        assert _fired(inj, "kv.handoff.corrupt") == 1

        dst = self._cache()
        free_before = len(dst._free_pages)
        with pytest.raises(ValueError, match="corrupt"):
            dst.import_slot(blob)
        assert len(dst._free_pages) == free_before   # nothing allocated

    def test_quiet_point_leaves_blob_alone(self):
        src = self._cache()
        slot = src.allocate(13)
        src._host("seq_lens")[slot] = 13
        blob = src.export_slot(slot)
        assert not faults.corrupt_blob("kv.handoff.corrupt", blob)
        dst = self._cache()
        assert dst.import_slot(blob) >= 0


# ---------------------------------------------------------------------------
# serving lanes (tiny model; deterministic scripted faults)
# ---------------------------------------------------------------------------

def _engine_clean(eng):
    lk = eng.leak_check()
    assert (lk["free_pages"] == lk["total_pages"]
            and lk["free_slots"] == lk["total_slots"]
            and lk["resident_slot_pages"] == 0
            and lk["leased_slots"] == 0), lk


class TestDeadline:
    def test_queue_expiry_frees_everything(self, model):
        eng = ServingEngine(model, **KW)
        h = eng.submit(np.arange(1, 9, dtype=np.int32), 8, seed=1,
                       deadline_s=0.0)
        eng.run()
        assert h.done
        assert h.finish_reason is FinishReason.DEADLINE_EXCEEDED
        assert len(h.output_tokens) == 0
        _engine_clean(eng)


class TestBrownout:
    def test_sheds_below_watermark_keeps_priority(self, model):
        fleet = FleetRouter(
            model=model, decode_replicas=2, engine_kw=KW, seed=7,
            watchdog={},
            brownout=dict(watermark=0.75, priority_floor=1))
        # deterministic death: no stepping needed — an error-flagged
        # replica is DEAD on the next watchdog tick
        fleet._by_name["d0"].error = RuntimeError("chaos: d0 died")
        assert fleet._watchdog_tick()
        assert fleet.recoveries and \
            fleet.recoveries[0]["cause"] == "error"
        assert fleet._brownout_active()

        shed = fleet.submit(np.arange(1, 7, dtype=np.int32), 4,
                            seed=1, priority=0)
        assert shed.done and shed.state is RequestState.FAILED
        assert shed.finish_reason is FinishReason.SHED
        assert len(shed.output_tokens) == 0

        kept = fleet.submit(np.arange(1, 7, dtype=np.int32), 3,
                            seed=2, priority=1)
        fleet.run()
        assert kept.done and len(kept.output_tokens) == 3
        assert kept.finish_reason is not FinishReason.SHED
        lk = fleet.leak_check()
        assert lk["clean"], lk


class TestKillRedispatch:
    def test_replica_kill_streams_bit_identical(self, model):
        rng = np.random.default_rng(7)
        prompts = [rng.integers(1, 64, (int(rng.integers(4, 11)),))
                   .astype(np.int32) for _ in range(3)]
        budgets = [int(rng.integers(4, 7)) for _ in range(3)]

        ref_eng = ServingEngine(model, **KW)
        rhs = [ref_eng.submit(p, b, seed=100 + i)
               for i, (p, b) in enumerate(zip(prompts, budgets))]
        ref_eng.run()
        ref = [list(h.output_tokens) for h in rhs]

        inj = faults.install(0)
        inj.arm("serving.step.raise", at=3, match={"engine": "d0"},
                message="chaos: kill d0")
        fleet = FleetRouter(model=model, decode_replicas=2,
                            engine_kw=KW, seed=7, watchdog={})
        sessions = _pin_sessions("d0", ["d1"], 2)
        fhs = [fleet.submit(p, b, seed=100 + i,
                            session=(sessions[i] if i < 2 else None))
               for i, (p, b) in enumerate(zip(prompts, budgets))]
        fleet.run()

        assert [list(h.output_tokens) for h in fhs] == ref, \
            "replica kill changed a token stream"
        assert all(h.done for h in fhs)
        assert _fired(inj, "serving.step.raise") == 1
        recs = fleet.recoveries
        assert len(recs) == 1 and recs[0]["replica"] == "d0"
        assert recs[0]["cause"] == "error"
        assert recs[0]["safe_harvest"] is True
        snap = fleet.metrics_snapshot()
        assert snap["quarantined_replicas"] == ["d0"], snap
        lk = fleet.leak_check()
        assert lk["clean"], lk


class TestHungJoin:
    def test_hung_thread_recorded_and_strict_raises(self, model):
        # no warmup on purpose: the wedge must land on the FIRST
        # worked step, before anything compiles — stop() then hits a
        # replica sleeping through its join timeout
        inj = faults.install(0)
        inj.arm("serving.step.stuck", at=1, match={"engine": "d0"},
                delay_s=0.6)
        fleet = FleetRouter(model=model, decode_replicas=2,
                            engine_kw=KW, seed=7, threaded=True,
                            join_timeout_s=0.05)
        fleet.start()
        try:
            session = _pin_sessions("d0", ["d1"], 1)[0]
            fleet.submit(np.ones((8,), np.int32), 2, seed=1,
                         session=session)
            time.sleep(0.15)           # let d0 enter the wedge
            out = fleet.stop()
            assert out["hung_replicas"] == ["d0"], out
            assert any(e["action"] == "replica_hung"
                       for e in fleet.events), fleet.events
            assert fleet.metrics_snapshot()["hung_replicas"] == ["d0"]
            with pytest.raises(RuntimeError):
                fleet.stop(strict=True)
        finally:
            for r in (list(fleet._replicas) + list(fleet._retired)
                      + list(fleet._quarantined)):
                if r.thread is not None:
                    r.thread.join(5.0)


# ---------------------------------------------------------------------------
# randomized multi-seed churn (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 2, 3])
def test_churn_multi_seed_exactly_once(model, seed):
    """Seeded-random kills under load: whatever fires, every stream
    stays bit-identical to the fault-free single engine (zero
    duplicated, zero lost tokens) and the fleet leaks nothing.
    ``times=2`` over 3 replicas guarantees a survivor; quarantined
    replicas never step again, so both firings land on live prey."""
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(1, 64, (int(rng.integers(4, 24)),))
               .astype(np.int32) for _ in range(8)]
    budgets = [int(rng.integers(4, 10)) for _ in range(8)]

    ref_eng = ServingEngine(model, **KW)
    rhs = [ref_eng.submit(p, b, seed=1000 + i)
           for i, (p, b) in enumerate(zip(prompts, budgets))]
    ref_eng.run()
    ref = [list(h.output_tokens) for h in rhs]
    _engine_clean(ref_eng)

    inj = faults.install(seed)
    inj.arm("serving.step.raise", prob=0.08, times=2,
            message=f"chaos churn seed={seed}")
    fleet = FleetRouter(model=model, decode_replicas=3, engine_kw=KW,
                        seed=seed, watchdog={})
    fhs = [fleet.submit(p, b, seed=1000 + i)
           for i, (p, b) in enumerate(zip(prompts, budgets))]
    fleet.run()

    assert [list(h.output_tokens) for h in fhs] == ref, \
        f"seed {seed}: churn changed a token stream"
    assert all(h.done for h in fhs)
    assert len(fleet.recoveries) == _fired(inj, "serving.step.raise")
    lk = fleet.leak_check()
    assert lk["clean"], lk
