"""SUMMA matmul over the ``(rows, cols)`` grid.

Van de Geijn & Watts' Scalable Universal Matrix Multiplication Algorithm,
the workhorse of "Large Scale Distributed Linear Algebra With TPUs"
(PAPERS.md, arXiv 2112.09017): C = A @ B with A, B, C all 2-D
block-sharded — rank (i, j) holds A_ij [M/r, K/c], B_ij [K/r, N/c] and
produces C_ij [M/r, N/c]. The contraction dim is walked in `npanels`
panels of width kb = K/npanels; each step broadcasts A's panel along the
``cols`` axis (owner block-column) and B's panel along the ``rows`` axis
(owner block-row) and accumulates the local [M/r, kb] x [kb, N/c]
product in fp32. Only panel-sized buffers ever cross the wire or live
per rank — no rank materializes a full operand or result
(`probe.assert_no_full_matrix` is the receipt).

The broadcast is the shard_map idiom `psum(where(owner, panel, 0))` —
one all-reduce per panel per operand over ONE mesh axis, which is what
`tools/hlo_overlap.py` counts per axis in the collective receipt.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ._grid import (
    COLS, ROWS, as_array, block_cyclic_permutation, cached_jit,
    default_grid, grid_shape, inverse_permutation, pad2, place, wrap_like,
)

__all__ = ["matmul", "summa_lowered"]


def _npanels(r, c, panels):
    """Panel count: a common multiple of r and c, so every panel sits
    inside one block-column of A AND one block-row of B."""
    base = (r * c) // math.gcd(r, c)
    if panels is None:
        return base
    return max(1, -(-int(panels) // base)) * base


def _summa_fn(r, c, npanels, out_dtype):
    """The per-rank SUMMA body: a [mL, K/c], b [K/r, nL] -> c [mL, nL]."""

    def fn(a, b):
        i = lax.axis_index(ROWS)
        j = lax.axis_index(COLS)
        kb_a = npanels // c          # panels per block-column of A
        kb_b = npanels // r          # panels per block-row of B
        kb = (a.shape[1] * c) // npanels
        acc = jnp.zeros((a.shape[0], b.shape[1]), jnp.float32)
        for t in range(npanels):
            jt, oa = divmod(t, kb_a)
            it, ob = divmod(t, kb_b)
            a_pan = lax.dynamic_slice_in_dim(a, oa * kb, kb, 1)
            a_pan = jnp.where(j == jt, a_pan, jnp.zeros_like(a_pan))
            a_pan = lax.psum(a_pan, COLS)
            b_pan = lax.dynamic_slice_in_dim(b, ob * kb, kb, 0)
            b_pan = jnp.where(i == it, b_pan, jnp.zeros_like(b_pan))
            b_pan = lax.psum(b_pan, ROWS)
            acc = acc + jnp.dot(a_pan, b_pan,
                                preferred_element_type=jnp.float32)
        return acc.astype(out_dtype)

    return fn


def _build_summa(grid, npanels, a_shape, b_shape, dtype):
    r, c = grid_shape(grid)
    spec = P(ROWS, COLS)
    fn = _summa_fn(r, c, npanels, dtype)
    return jax.jit(jax.shard_map(fn, mesh=grid, in_specs=(spec, spec),
                                 out_specs=spec, check_vma=False))


def _prepare(a, b, grid, panels):
    """Pad operands to grid/panel multiples; returns everything the
    compiled call and the probe need."""
    if grid is None:
        grid = default_grid()
    r, c = grid_shape(grid)
    np_ = _npanels(r, c, panels)
    # np_ is a common multiple of r and c, so padding K to np_ also
    # makes the K/c and K/r local splits exact
    kmul = np_
    a_p, (m, k) = pad2(a, r, kmul)
    b_p, (k2, n) = pad2(b, kmul, c)
    if k != k2:
        raise ValueError(
            f"matmul inner dims disagree: {a.shape} @ {b.shape}")
    spec = P(ROWS, COLS)
    a_p = place(a_p, grid, spec)
    b_p = place(b_p, grid, spec)
    return grid, np_, a_p, b_p, (m, k, n)


def matmul(a, b, grid=None, panels=None, block_size=None):
    """Distributed C = A @ B via SUMMA on a ``(rows, cols)`` grid.

    ``panels`` raises the panel count (finer pipelining; rounded up to a
    common multiple of the grid degrees). ``block_size`` distributes the
    operands BLOCK-CYCLICALLY with that block edge (ScaLAPACK layout —
    load-balances triangular/banded structure; square grids only): the
    cyclic layout is realized as a pure index permutation of each global
    dim, SUMMA runs on the permuted blocks, and the result permutes
    back — bit-identical math, different rank ownership.
    """
    a_d, wrap_a = as_array(a)
    b_d, wrap_b = as_array(b)
    if a_d.ndim != 2 or b_d.ndim != 2:
        raise ValueError(
            f"distributed.matmul is 2-D (got {a_d.shape} @ {b_d.shape});"
            " batch with a vmap over the leading dims")
    if grid is None:
        grid = default_grid(square=block_size is not None)
    r, c = grid_shape(grid)
    perms = None
    if block_size is not None:
        if r != c:
            raise ValueError(
                "block-cyclic layout needs a square grid (the one "
                f"K-permutation must be cyclic over both the {c} "
                f"block-columns of A and the {r} block-rows of B); got "
                f"{r}x{c} — build_grid(square=True)")
        bs = int(block_size)
        # pad every dim to block*degree multiples before permuting
        a_d, (m0, k0) = pad2(a_d, bs * r, bs * c)
        b_d, (_, n0) = pad2(b_d, bs * r, bs * c)
        pm = block_cyclic_permutation(a_d.shape[0], r, bs)
        pk = block_cyclic_permutation(a_d.shape[1], c, bs)
        pn = block_cyclic_permutation(b_d.shape[1], c, bs)
        a_d = jnp.take(jnp.take(a_d, pm, 0), pk, 1)
        b_d = jnp.take(jnp.take(b_d, pk, 0), pn, 1)
        perms = (pm, pn, m0, n0)
    grid, np_, a_p, b_p, (m, k, n) = _prepare(a_d, b_d, grid, panels)
    fn = cached_jit(
        ("summa", grid, np_, a_p.shape, b_p.shape, str(a_p.dtype)),
        lambda: _build_summa(grid, np_, a_p.shape, b_p.shape,
                             a_p.dtype))
    out = fn(a_p, b_p)
    if perms is not None:
        pm, pn, m0, n0 = perms
        out = jnp.take(jnp.take(out, inverse_permutation(pm), 0),
                       inverse_permutation(pn), 1)[:m0, :n0]
    else:
        out = out[:m, :n]
    return wrap_like(out, wrap_a or wrap_b)


def summa_lowered(m, k, n, grid=None, panels=None, dtype=jnp.float32):
    """Lower (never run) the SUMMA program for the given global shapes —
    the compiled text is what the collective receipt inspects."""
    a = jnp.zeros((m, k), dtype)
    b = jnp.zeros((k, n), dtype)
    grid, np_, a_p, b_p, _ = _prepare(a, b, grid, panels)
    jit_fn = _build_summa(grid, np_, a_p.shape, b_p.shape, a_p.dtype)
    return jit_fn.lower(a_p, b_p)
