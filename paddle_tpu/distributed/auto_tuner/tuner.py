"""Auto-tuner over hybrid-parallel configurations.

Reference parity: python/paddle/distributed/auto_tuner/tuner.py — generate
candidate (dp, mp, pp, sharding, micro_batch) configs, prune infeasible
ones, rank by a cost model, optionally measure the survivors. TPU-first
cost model: the scaling-book decomposition — per-step compute
flops/(chips*peak), TP activation collectives over ICI per layer, PP
bubble (pp-1)/micro, ZeRO gather/scatter traffic — with an HBM-fit
estimator doing the hard pruning (OOM is the expensive failure the
reference tuner exists to avoid).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from .prune import prune_candidates
from .search import grid_candidates


@dataclass
class Candidate:
    dp: int = 1
    mp: int = 1
    pp: int = 1
    sep: int = 1                 # sequence/context-parallel (ring) degree
    ep: int = 1                  # expert-parallel degree (MoE models)
    sharding_stage: int = 0      # 0=none, 1/2=state/grad shard, 3=param
    micro_batch: int = 1
    estimated_step_ms: float = 0.0
    estimated_mem_gb: float = 0.0
    measured_step_ms: Optional[float] = None
    pruned_reason: Optional[str] = None

    @property
    def degree(self):
        return self.dp * self.mp * self.pp * self.sep * self.ep

    def hybrid_configs(self):
        return {"dp_degree": self.dp, "mp_degree": self.mp,
                "pp_degree": self.pp, "sep_degree": self.sep,
                "ep_degree": self.ep,
                "sharding_degree": self.dp if self.sharding_stage else 1}


@dataclass
class ModelSpec:
    """What the cost/memory model needs to know about the workload."""

    params: int                      # total parameter count
    num_layers: int
    hidden_size: int
    num_heads: int
    vocab_size: int
    seq_len: int
    global_batch: int
    param_bytes: int = 2             # bf16 params
    master_bytes: int = 12           # fp32 master + 2 adam moments
    use_recompute: bool = True
    num_experts: int = 0             # MoE expert count (0 = dense)
    expert_param_frac: float = 0.0   # fraction of params in expert FFNs
    # ISSUE 11: the sharded train steps store params as 1/N flat shards
    # (gather-on-use); `spec_of_model` opts in because that is what
    # `select_train_step` actually builds. False keeps the classic
    # stage-semantics memory model (stage 3 = param sharding) for
    # generic AutoTuner use.
    sharded_param_storage: bool = False


def estimate_memory_gb(spec: ModelSpec, c: Candidate) -> float:
    """Per-chip HBM estimate (the pruner's core).

    Replicated storage: params shard over mp*pp (+ dp when stage 3);
    sharded storage (ISSUE 11 default for the sharded steps) shards
    params over the FULL flattened degree like the optimizer state —
    gather-on-use keeps at most ~2 layer chunks of full params live,
    which the activation term's per-layer window already dwarfs.
    Optimizer state over mp*pp (* dp when stage>=1); ep shards the
    expert fraction of params/state 1/ep; activations over dp (batch)
    and pp (layers), ~2 bytes/elem with remat keeping ~4 tensors/layer
    live.
    """
    sharded_params = spec.sharded_param_storage and c.sharding_stage >= 1
    p_shard = (c.dp * c.mp * c.pp * c.ep if sharded_params
               else c.mp * c.pp * (c.dp if c.sharding_stage == 3 else 1))
    o_shard = c.mp * c.pp * c.ep * (c.dp if c.sharding_stage >= 1 else 1)
    dense_frac = 1.0 - spec.expert_param_frac
    # without sharded storage the expert stacks still replicate over dp
    # but shard 1/ep (the MoELayer EP slicing)
    exp_p_shard = p_shard if sharded_params else max(p_shard, 1) * c.ep
    param_gb = spec.params * spec.param_bytes * (
        dense_frac / p_shard
        + spec.expert_param_frac / exp_p_shard) / 1e9
    opt_gb = spec.params * spec.master_bytes / o_shard / 1e9
    mb = max(1, spec.global_batch // max(c.dp * c.ep, 1)
             // max(c.micro_batch, 1))
    live_per_layer = 4 if spec.use_recompute else 34
    # sep shards the sequence dim of every activation (ring attention
    # keeps attention memory O(seq/sep) too — meta_parallel/ring_attention)
    act_gb = (mb * (spec.seq_len // c.sep) * spec.hidden_size
              * (spec.num_layers // c.pp) * live_per_layer * 2 / c.mp) / 1e9
    logits_gb = mb * (spec.seq_len // c.sep) * spec.vocab_size * 4 \
        / c.mp / 1e9
    return param_gb + opt_gb + act_gb + logits_gb


def calibrate_backend(devices=None, probe_elems=262144, reps=5):
    """Measure the CURRENT backend's collective behavior with three
    micro-probes (r5, VERDICT r4 weak #5: the pp cost term needs a
    per-backend emulation constant — the virtual CPU mesh charges a
    shard_map ppermute ring tick orders of magnitude more than real ICI,
    so v5e constants misrank pp configs there):

      coll_lat_us — dispatch+sync latency of one jitted allreduce of a
                    tiny tensor on a 2-device mesh;
      ici_gbps    — effective allreduce bandwidth from a bigger probe;
      pp_tick_ms  — wall cost of ONE ppermute ring-scan tick (the
                    pipeline's unit of serialization), measured from a
                    jitted lax.scan of 8 ticks;
      peak_flops  — EFFECTIVE matmul throughput of one device (r6
                    planner promotion: on the emulated host mesh real
                    compute is ~4 orders below the v5e MXU constant, so
                    without this the compute term — and the pp BUBBLE
                    that multiplies it — vanish from every ranking and
                    pipeline configs rank absurdly fast).

    Returns a dict consumable by estimate_step_ms(backend=...) /
    AutoTuner(backend_constants=...). Costs ~1s on CPU, less on TPU.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    if devices is None:
        devices = jax.devices()
    devices = list(devices)[:2]

    def timed(fn, *args):
        out = fn(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / reps

    n = 512
    with jax.default_device(devices[0]):
        a = jnp.ones((n, n), jnp.float32)
        t_mm = timed(jax.jit(lambda x: (x @ x) @ x), a)
    peak_flops = float(min(max(2 * 2 * n ** 3 / max(t_mm, 1e-9), 1e9),
                           1e16))
    if len(devices) < 2:
        return {"coll_lat_us": 10.0, "ici_gbps": 400e9,
                "pp_tick_ms": 10.0 * 1e-3, "peak_flops": peak_flops}
    mesh = Mesh(np.asarray(devices), ("cal",))

    small = jnp.zeros((8, 16), jnp.float32)
    big = jnp.zeros((probe_elems,), jnp.float32)
    ar = jax.jit(jax.shard_map(
        lambda x: jax.lax.psum(x, "cal"), mesh=mesh, in_specs=P(),
        out_specs=P(), check_vma=False))
    t_small = timed(ar, small)
    t_big = timed(ar, big)
    # noise guard: on a fast interconnect t_big can land within jitter of
    # t_small — floor the delta at 5% of t_big and clamp the estimate to
    # a physical range so a noisy probe can never zero the dp comm term
    bw = 2 * big.nbytes / max(t_big - t_small, 0.05 * t_big, 1e-9)
    bw = min(max(bw, 1e6), 1e12)

    n_ticks = 8

    def ring(x):
        def tick(c, _):
            return jax.lax.ppermute(c, "cal", [(0, 1), (1, 0)]), None

        y, _ = jax.lax.scan(tick, x, None, length=n_ticks)
        return y

    rg = jax.jit(jax.shard_map(ring, mesh=mesh, in_specs=P("cal"),
                               out_specs=P("cal"), check_vma=False))
    t_ring = timed(rg, jnp.zeros((2, 64), jnp.float32))
    return {
        "coll_lat_us": t_small * 1e6,
        "ici_gbps": float(max(bw, 1e6)),
        "pp_tick_ms": t_ring / n_ticks * 1e3,
        "peak_flops": peak_flops,
    }


def estimate_step_ms(spec: ModelSpec, c: Candidate, *,
                     peak_flops=197e12, ici_gbps=400e9,
                     hbm_gbps=819e9, coll_lat_us=10.0,
                     backend=None) -> float:
    """Scaling-book style step-time decomposition (coarse, for RANKING --
    absolute numbers come from measured trials). `backend` (from
    calibrate_backend) overrides the collective constants with measured
    ones — mandatory for sane rankings on the virtual CPU mesh."""
    pp_tick_ms = coll_lat_us * 1e-3
    if backend is not None:
        coll_lat_us = float(backend.get("coll_lat_us", coll_lat_us))
        ici_gbps = float(backend.get("ici_gbps", ici_gbps))
        pp_tick_ms = float(backend.get("pp_tick_ms", pp_tick_ms))
        peak_flops = float(backend.get("peak_flops", peak_flops))
    tokens = spec.global_batch * spec.seq_len
    flops = 6 * spec.params * tokens * (4 / 3 if spec.use_recompute else 1)
    compute_ms = flops / (c.degree * peak_flops) * 1e3
    # TP: 2 allreduces of activations per layer (fwd+bwd doubles). The
    # latency term (fixed cost per collective, r4 planner validation —
    # without it small workloads rank comm-heavy configs FASTER) counts
    # 4 collectives/layer regardless of size.
    if c.mp > 1:
        act_bytes = (spec.global_batch // c.dp) * spec.seq_len \
            * spec.hidden_size * 2
        n_coll = 4 * spec.num_layers // c.pp
        tp_ms = (4 * act_bytes * (c.mp - 1) / c.mp / ici_gbps) \
            * spec.num_layers / c.pp * 1e3 \
            + n_coll * coll_lat_us * 1e-3
    else:
        tp_ms = 0.0
    # SEP/ring attention: k+v blocks rotate the full ring each layer —
    # per tick 2 tensors of [mb, seq/sep, hidden] bf16, (sep-1) ticks,
    # ~3x for the reverse-ring backward's extra dk/dv rotation
    if c.sep > 1:
        blk_bytes = (spec.global_batch // max(c.dp, 1)) \
            * (spec.seq_len // c.sep) * spec.hidden_size * 2
        sep_ms = (3 * 2 * blk_bytes * (c.sep - 1) / ici_gbps) \
            * spec.num_layers / c.pp * 1e3 \
            + 3 * (c.sep - 1) * spec.num_layers // c.pp \
            * coll_lat_us * 1e-3
    else:
        sep_ms = 0.0
    # PP bubble inflates compute by (pp-1)/micro; each ring tick also
    # pays the backend's per-tick cost (ppermute + the scan's
    # serialization unit — calibrated, since emulated meshes charge this
    # orders of magnitude above real ICI)
    bubble = (c.pp - 1) / max(c.micro_batch, 1)
    pp_lat_ms = ((c.pp + max(c.micro_batch, 1) - 1) * pp_tick_ms
                 if c.pp > 1 else 0.0)
    # DP/ZeRO grad sync: each replica allreduces only ITS param shard
    # (params / (mp*pp)) around the dp ring; one fused collective's
    # latency regardless of size
    if c.dp > 1:
        local_params = spec.params / (c.mp * c.pp)
        dp_ms = 2 * local_params * spec.param_bytes * (c.dp - 1) / c.dp \
            / ici_gbps * 1e3 + coll_lat_us * 1e-3
    else:
        dp_ms = 0.0
    # EP: capacity-padded dispatch+combine all_to_alls per MoE layer
    # (2 fwd + 2 bwd), each moving ~the local token activations once
    if c.ep > 1 and spec.num_experts:
        tok_bytes = (spec.global_batch // max(c.dp * c.ep, 1)) \
            * spec.seq_len * spec.hidden_size * 2
        ep_ms = (4 * tok_bytes * (c.ep - 1) / c.ep / ici_gbps) \
            * spec.num_layers / c.pp * 1e3 \
            + 4 * spec.num_layers // c.pp * coll_lat_us * 1e-3
    else:
        ep_ms = 0.0
    # Sharded param storage (ISSUE 11): the freed HBM is bought with
    # gather-on-use traffic — the fwd scan and the bwd recompute each
    # all_gather every param once, while the replicated layout's single
    # update-scan gather disappears: net +1 full-param gather per step
    # over the flattened axes. Overlappable (the double-buffered
    # prefetch slot), so charge half the wire time as exposed.
    N = c.dp * c.mp * c.pp * c.ep
    if spec.sharded_param_storage and c.sharding_stage >= 1 and N > 1:
        gather_ms = 0.5 * spec.params * spec.param_bytes * (N - 1) / N \
            / ici_gbps * 1e3
    else:
        gather_ms = 0.0
    # HBM floor: optimizer sweep
    hbm_ms = spec.params * spec.master_bytes / (
        c.mp * c.pp * c.ep
        * (c.dp if c.sharding_stage >= 1 else 1)) / hbm_gbps * 1e3
    return (compute_ms * (1 + bubble) + tp_ms + sep_ms + dp_ms + ep_ms
            + gather_ms + pp_lat_ms + hbm_ms)


class AutoTuner:
    """Reference tuner.py role: propose -> prune -> rank -> (measure).

    Args:
      spec: ModelSpec of the workload.
      n_devices: chips available.
      hbm_gb: per-chip HBM budget.
      runner: optional callable(Candidate) -> measured step ms; called by
        `measure(top_k)` on the best-ranked survivors (the reference
        launches real trials; here the caller decides how to run one).
    """

    def __init__(self, spec: ModelSpec, n_devices: int, hbm_gb: float = 16.0,
                 runner: Optional[Callable] = None,
                 sharding_stages=(0, 1, 3), max_micro=64,
                 enable_sep=False, backend_constants=None):
        self.spec = spec
        self.n_devices = n_devices
        self.hbm_gb = hbm_gb
        self.runner = runner
        self.sharding_stages = sharding_stages
        self.max_micro = max_micro
        self.enable_sep = enable_sep
        # calibrate_backend() output; None keeps the v5e constants
        self.backend_constants = backend_constants
        self.history: list[Candidate] = []

    def candidates(self) -> list[Candidate]:
        cands = grid_candidates(self.n_devices, self.sharding_stages,
                                self.max_micro, self.spec.global_batch,
                                enable_sep=self.enable_sep)
        cands = prune_candidates(cands, self.spec, self.hbm_gb)
        for c in cands:
            if c.pruned_reason is None:
                c.estimated_mem_gb = estimate_memory_gb(self.spec, c)
                c.estimated_step_ms = estimate_step_ms(
                    self.spec, c, backend=self.backend_constants)
        live = [c for c in cands if c.pruned_reason is None]
        live.sort(key=lambda c: c.estimated_step_ms)
        self.history = cands
        return live

    def search_once(self) -> Optional[Candidate]:
        """Best candidate by the cost model (reference search_once)."""
        live = self.candidates()
        return live[0] if live else None

    def measure(self, top_k: int = 3) -> Optional[Candidate]:
        """Run the runner on the top_k model-ranked candidates; returns the
        fastest measured one."""
        if self.runner is None:
            raise ValueError("no runner provided")
        best = None
        for c in self.candidates()[:top_k]:
            try:
                c.measured_step_ms = float(self.runner(c))
            except Exception as e:       # OOM'd trial = pruned, keep going
                c.pruned_reason = f"trial failed: {type(e).__name__}"
                continue
            if best is None or c.measured_step_ms < best.measured_step_ms:
                best = c
        return best
