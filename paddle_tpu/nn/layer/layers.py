"""Layer base class + Parameter.

Reference parity: python/paddle/nn/layer/layers.py (paddle.nn.Layer) and
EagerParamBase (python/paddle/base/framework.py). The parameter store is a
flat dict per layer, recursively composed — which doubles as the functional
pytree view used by the jit/pjit bridge (paddle_tpu.jit): state_dict() in,
updated state out.
"""
from __future__ import annotations

import itertools
from collections import OrderedDict

import numpy as np

from ...framework.tensor import Tensor
from ...framework.dtype import convert_dtype, get_default_dtype

_param_counter = itertools.count()


class Parameter(Tensor):
    """A trainable leaf tensor (reference: EagerParamBase)."""

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable)
        self.name = name or f"param_{next(_param_counter)}"
        self.persistable = True
        self.trainable = trainable

    @property
    def is_parameter(self):
        return True


class ParamAttr:
    """paddle.ParamAttr parity (python/paddle/base/param_attr.py)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if attr is False:
            return False
        # an initializer instance
        return ParamAttr(initializer=attr)


class Layer:
    """Base building block (paddle.nn.Layer parity)."""

    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._parameters = OrderedDict()
        self._sub_layers = OrderedDict()
        self._buffers = OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- attribute interception -----------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            for store in (layers, buffers):
                if store is not None and name in store:
                    del store[name]
            # a prior plain assignment (e.g. `self.bias = None`) lives in
            # the instance dict and would SHADOW the registered parameter
            self.__dict__.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for store in (params, buffers):
                if store is not None and name in store:
                    del store[name]
            self.__dict__.pop(name, None)
            layers[name] = value
        elif params is not None and name in params:
            if value is None:
                params[name] = None
            else:
                raise TypeError(f"cannot assign non-Parameter to parameter {name!r}")
        elif buffers is not None and name in buffers:
            buffers[name] = value if (value is None or isinstance(value, Tensor)) else Tensor(value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store_name in ("_parameters", "_sub_layers", "_buffers"):
            store = self.__dict__.get(store_name)
            if store is not None and name in store:
                return store[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store_name in ("_parameters", "_sub_layers", "_buffers"):
            store = self.__dict__.get(store_name)
            if store is not None and name in store:
                del store[name]
                return
        object.__delattr__(self, name)

    # -- parameter creation ---------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from ..initializer import Constant, XavierUniform

        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype or self._dtype or get_default_dtype()
        # precedence (reference set_global_initializer contract): a
        # user-specified attr initializer wins; otherwise an active
        # global initializer overrides even the layer's own default
        from ..initializer import get_global_initializer

        glob = get_global_initializer()
        init = attr.initializer
        if init is None and glob is not None:
            init = glob[1] if is_bias else glob[0]
        if init is None:
            init = default_initializer
        if init is None:
            init = Constant(0.0) if is_bias else XavierUniform()
        data = init(shape, dtype)
        p = Parameter(data, dtype=dtype, name=attr.name, trainable=attr.trainable)
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        p.is_distributed = False
        return p

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self.__dict__.pop(name, None)   # a prior plain attr would shadow
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self.__dict__.pop(str(name), None)
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self.__dict__.pop(name, None)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- traversal -------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p
            if not include_sublayers:
                break

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b
            if not include_sublayers:
                break

    def children(self):
        return [l for _, l in self.named_children()]

    def named_children(self):
        for name, layer in self._sub_layers.items():
            if layer is not None:
                yield name, layer

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from layer.named_sublayers(prefix=sub_prefix, include_self=True)

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # -- modes -----------------------------------------------------------
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    # -- state dict ------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True):
        dest = OrderedDict() if destination is None else destination
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[name] = p
        for name, layer in self.named_sublayers(include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                dest[f"{name}.{bname}" if name else bname] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, value in state_dict.items():
            if name not in own:
                unexpected.append(name)
                continue
            target = own[name]
            v = value if isinstance(value, Tensor) else Tensor(np.asarray(value))
            if list(v.shape) != list(target.shape):
                raise ValueError(
                    f"shape mismatch for {name}: loaded {v.shape} vs model {target.shape}"
                )
            target.set_value(v.astype(target.dtype))
        for name in own:
            if name not in state_dict:
                missing.append(name)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- dtype / device movement ----------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        def move(t):
            if t is None:
                return t
            out = t
            if dtype is not None and out.dtype.is_floating_point:
                out_data = out._data.astype(
                    __import__("paddle_tpu").framework.to_jax_dtype(dtype)
                )
                t._data = out_data
            if device is not None:
                import jax
                from ...framework.device import CPUPlace, TPUPlace

                place = device
                if isinstance(device, str):
                    place = CPUPlace() if device.startswith("cpu") else TPUPlace()
                t._data = jax.device_put(t._data, place.jax_device())
            return t

        for layer in self.sublayers(include_self=True):
            for p in layer._parameters.values():
                move(p)
            for b in layer._buffers.values():
                move(b)
        if dtype is not None:
            self._dtype = convert_dtype(dtype).name
        return self

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def astype(self, dtype):
        return self.to(dtype=dtype)

    # -- hooks ------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookHandle(self._forward_post_hooks)
        self._forward_post_hooks[handle.id] = hook
        return handle

    # -- call -------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            rep = repr(layer).split("\n")
            rep = [rep[0]] + ["  " + l for l in rep[1:]]
            lines.append(f"  ({name}): " + "\n".join(rep))
        main = f"{self.__class__.__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()


class _HookHandle:
    _counter = itertools.count()

    def __init__(self, store):
        self.id = next(_HookHandle._counter)
        self._store = store

    def remove(self):
        self._store.pop(self.id, None)
