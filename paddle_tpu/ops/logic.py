"""Comparison & logical ops (python/paddle/tensor/logic.py parity)."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor
from ._dispatch import binary, unary, ensure_tensor, nary


def equal(x, y, name=None):
    return binary(jnp.equal, x, y, "equal")


def not_equal(x, y, name=None):
    return binary(jnp.not_equal, x, y, "not_equal")


def less_than(x, y, name=None):
    return binary(jnp.less, x, y, "less_than")


def less_equal(x, y, name=None):
    return binary(jnp.less_equal, x, y, "less_equal")


def greater_than(x, y, name=None):
    return binary(jnp.greater, x, y, "greater_than")


def greater_equal(x, y, name=None):
    return binary(jnp.greater_equal, x, y, "greater_equal")


def logical_and(x, y, out=None, name=None):
    return binary(jnp.logical_and, x, y, "logical_and")


def logical_or(x, y, out=None, name=None):
    return binary(jnp.logical_or, x, y, "logical_or")


def logical_xor(x, y, out=None, name=None):
    return binary(jnp.logical_xor, x, y, "logical_xor")


def logical_not(x, out=None, name=None):
    return unary(jnp.logical_not, x, "logical_not")


def bitwise_and(x, y, name=None):
    return binary(jnp.bitwise_and, x, y, "bitwise_and")


def bitwise_or(x, y, name=None):
    return binary(jnp.bitwise_or, x, y, "bitwise_or")


def bitwise_xor(x, y, name=None):
    return binary(jnp.bitwise_xor, x, y, "bitwise_xor")


def bitwise_not(x, name=None):
    return unary(jnp.bitwise_not, x, "bitwise_not")


def equal_all(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    if x.shape != y.shape:
        return Tensor._wrap(jnp.asarray(False))
    return binary(lambda a, b: jnp.all(a == b), x, y, "equal_all")


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return binary(
        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        x, y, "allclose",
    )


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return binary(
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        x, y, "isclose",
    )


def where(condition, x=None, y=None, name=None):
    condition = ensure_tensor(condition)
    if x is None and y is None:
        return tuple(
            Tensor._wrap(i) for i in jnp.nonzero(condition._data)
        )
    return nary(
        lambda c, a, b: jnp.where(c.astype(bool), a, b),
        [condition, x, y],
        "where",
    )


def is_empty(x, name=None):
    return Tensor._wrap(jnp.asarray(ensure_tensor(x)._data.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
