"""Pipeline parallelism tests — the VERDICT r1 gap #2.

The contract: pp=2 / pp=4 training is step-for-step numerically equal to
single-device execution (reference test strategy: every strategy has a
numeric parity test against its unsharded twin, SURVEY.md §4).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
    pipeline_spmd, microbatch, unmicrobatch,
)
from paddle_tpu.models import (
    GPTConfig, GPTForCausalLM, GPTForCausalLMPipe, GPTPretrainingCriterion,
)


def _mesh(n, axis="pp"):
    return Mesh(np.array(jax.devices("cpu")[:n]), (axis,))


class TestPipelinePrimitive:
    @pytest.mark.parametrize("n_stages,n_micro", [
        (2, 2), pytest.param(4, 4, marks=pytest.mark.slow), (4, 2)])
    def test_matches_sequential(self, n_stages, n_micro):
        mesh = _mesh(n_stages)
        rng = np.random.default_rng(0)
        lps, h = 2, 16
        W = jnp.asarray(rng.standard_normal((n_stages, lps, h, h)) * 0.3,
                        jnp.float32)

        def block_fn(Ws, xmb):
            def body(c, w):
                return jnp.tanh(c @ w), None
            y, _ = jax.lax.scan(body, xmb, Ws)
            return y

        def piped(W, x):
            return unmicrobatch(pipeline_spmd(
                block_fn, W, microbatch(x, n_micro), mesh=mesh, axis="pp"))

        def seq(W, x):
            for i in range(n_stages * lps):
                x = jnp.tanh(x @ W.reshape(-1, h, h)[i])
            return x

        x = jnp.asarray(rng.standard_normal((n_micro * 2, h)), jnp.float32)
        np.testing.assert_allclose(piped(W, x), seq(W, x), atol=1e-6)
        g1 = jax.grad(lambda W, x: jnp.sum(jnp.sin(piped(W, x))), (0, 1))(W, x)
        g2 = jax.grad(lambda W, x: jnp.sum(jnp.sin(seq(W, x))), (0, 1))(W, x)
        np.testing.assert_allclose(g1[0], g2[0], atol=1e-5)
        np.testing.assert_allclose(g1[1], g2[1], atol=1e-5)

    def test_interleave_chunks(self):
        """num_chunks=2 VPP round-robin placement: chunk c on stage s is
        logical stage c*n_stages+s (reference pipeline_parallel.py:1138)."""
        mesh = _mesh(2)
        rng = np.random.default_rng(1)
        ns, nc, h = 2, 2, 8
        W = jnp.asarray(rng.standard_normal((ns, nc, 1, h, h)) * 0.3,
                        jnp.float32)

        def block_fn(Ws, xmb):
            y, _ = jax.lax.scan(lambda c, w: (jnp.tanh(c @ w), None), xmb, Ws)
            return y

        def piped(W, x):
            return unmicrobatch(pipeline_spmd(
                block_fn, W, microbatch(x, 2), mesh=mesh, axis="pp",
                num_chunks=nc))

        def seq(W, x):
            for c in range(nc):
                for s in range(ns):
                    x = jnp.tanh(x @ W[s, c, 0])
            return x

        x = jnp.asarray(rng.standard_normal((4, h)), jnp.float32)
        np.testing.assert_allclose(piped(W, x), seq(W, x), atol=1e-6)


def _tiny_cfg(**kw):
    base = dict(vocab_size=64, hidden_size=32, num_layers=4,
                num_attention_heads=4, max_position_embeddings=16,
                hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    base.update(kw)
    return GPTConfig(**base)


def _copy_plain_into_pipe(plain, pipe, num_stages, lps, num_chunks=1):
    sd = dict(plain.named_parameters())
    pipe.wte.weight._data = sd["gpt.wte.weight"]._data
    pipe.wpe.weight._data = sd["gpt.wpe.weight"]._data
    pipe.ln_f.weight._data = sd["gpt.ln_f.weight"]._data
    pipe.ln_f.bias._data = sd["gpt.ln_f.bias"]._data
    for flat, pname in pipe._stacked_names:
        stk = pipe._parameters[flat]
        if num_chunks == 1:
            vals = jnp.stack([
                jnp.stack([sd[f"gpt.blocks.{s * lps + i}.{pname}"]._data
                           for i in range(lps)])
                for s in range(num_stages)])
        else:
            vals = jnp.stack([
                jnp.stack([
                    jnp.stack([sd[
                        f"gpt.blocks.{(c * num_stages + s) * lps + i}.{pname}"
                    ]._data for i in range(lps)])
                    for c in range(num_chunks)])
                for s in range(num_stages)])
        stk._data = vals


class TestGPTPipeParity:
    @pytest.mark.slow
    def test_loss_and_grads_match_plain(self):
        cfg = _tiny_cfg()
        mesh = _mesh(2)
        plain = GPTForCausalLM(cfg)
        pipe = GPTForCausalLMPipe(cfg, num_stages=2, num_micro=2, mesh=mesh)
        _copy_plain_into_pipe(plain, pipe, 2, 2)

        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(rng.integers(0, 64, (4, 16)), dtype="int64")
        labels = paddle.to_tensor(rng.integers(0, 64, (4, 16)), dtype="int64")
        crit = GPTPretrainingCriterion()
        l_plain = crit(plain(ids), labels)
        l_pipe = crit(pipe(ids), labels)
        assert abs(float(l_plain) - float(l_pipe)) < 1e-5
        l_plain.backward()
        l_pipe.backward()
        sd = dict(plain.named_parameters())
        g_plain = sd["gpt.blocks.3.attn.qkv.weight"].grad._data
        g_pipe = pipe._parameters["blocks__attn__qkv__weight"].grad._data[1, 1]
        np.testing.assert_allclose(np.asarray(g_plain), np.asarray(g_pipe),
                                   atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(sd["gpt.wte.weight"].grad._data),
            np.asarray(pipe.wte.weight.grad._data), atol=1e-5)

    @pytest.mark.slow
    def test_pp4_loss_matches(self):
        cfg = _tiny_cfg()
        mesh = _mesh(4)
        plain = GPTForCausalLM(cfg)
        pipe = GPTForCausalLMPipe(cfg, num_stages=4, num_micro=4, mesh=mesh)
        _copy_plain_into_pipe(plain, pipe, 4, 1)
        rng = np.random.default_rng(2)
        ids = paddle.to_tensor(rng.integers(0, 64, (8, 16)), dtype="int64")
        labels = paddle.to_tensor(rng.integers(0, 64, (8, 16)), dtype="int64")
        crit = GPTPretrainingCriterion()
        assert abs(float(crit(plain(ids), labels)) -
                   float(crit(pipe(ids), labels))) < 1e-5

    @pytest.mark.skipif(
        paddle.jax_compat_legacy,
        reason="old XLA: PartitionId unsupported under SPMD partitioning "
               "(the pipeline shard_map path needs the new toolchain)")
    def test_train_step_pp_dp_mesh(self):
        """Full fused TrainStep over a dp×pp mesh: loss decreases and the
        jitted step does not retrace."""
        import paddle_tpu.optimizer as popt
        from paddle_tpu.jit import TrainStep
        from jax.sharding import NamedSharding, PartitionSpec as P
        from paddle_tpu.models import gpt_pipe_sharding_rules, match_sharding

        cfg = _tiny_cfg()
        mesh = Mesh(np.array(jax.devices("cpu")[:4]).reshape(2, 2),
                    ("dp", "pp"))
        pipe = GPTForCausalLMPipe(cfg, num_stages=2, num_micro=2, mesh=mesh)
        rules = gpt_pipe_sharding_rules(tp_axis=None)
        for name, p in pipe.named_parameters():
            spec = match_sharding(name, rules)
            axes = [a if (a and p._data.shape[i] % mesh.shape[a] == 0)
                    else None for i, a in enumerate(spec)] if spec else []
            p._data = jax.device_put(
                p._data, NamedSharding(mesh, P(*axes) if axes else P()))
        opt = popt.AdamW(learning_rate=1e-3, parameters=pipe.parameters())
        crit = GPTPretrainingCriterion()
        step = TrainStep(pipe, lambda m, i, l: crit(m(i), l), opt)
        rng = np.random.default_rng(3)
        ids = paddle.to_tensor(rng.integers(0, 64, (4, 16)), dtype="int64")
        ids._data = jax.device_put(ids._data, NamedSharding(mesh, P("dp")))
        labels = paddle.to_tensor(rng.integers(0, 64, (4, 16)), dtype="int64")
        labels._data = jax.device_put(labels._data,
                                      NamedSharding(mesh, P("dp")))
        losses = [float(step(ids, labels)) for _ in range(3)]
        assert losses[-1] < losses[0]
        assert np.all(np.isfinite(losses))


class TestHeteroPipeline:
    """pipeline_spmd_hetero (reference pp_layers.py LayerDesc
    segmentation): stages with different shapes/params — embedding on
    stage 0, head on the last stage — parity vs sequential execution,
    forward and grads."""

    def _stages(self, vocab=32, h=16, seq=8):
        import jax
        import jax.numpy as jnp

        rng = np.random.default_rng(0)

        def embed(params, ids):
            return params["table"][ids]           # [mb, s] -> [mb, s, h]

        def block(params, x):
            y = jnp.tanh(x @ params["w"] + params["b"])
            return x + y                          # [mb, s, h]

        def head(params, x):
            x = jnp.tanh(x @ params["w"] + params["b"])
            return x @ params["proj"]             # -> [mb, s, vocab]

        p_embed = {"table": jnp.asarray(
            rng.standard_normal((vocab, h)), jnp.float32)}
        p_block = {"w": jnp.asarray(rng.standard_normal((h, h)) * 0.1,
                                    jnp.float32),
                   "b": jnp.zeros((h,), jnp.float32)}
        p_head = {"w": jnp.asarray(rng.standard_normal((h, h)) * 0.1,
                                   jnp.float32),
                  "b": jnp.zeros((h,), jnp.float32),
                  "proj": jnp.asarray(rng.standard_normal((h, vocab)) * 0.1,
                                      jnp.float32)}
        fns = [embed, block, block, head]
        params = [p_embed, p_block, p_block, p_head]
        return fns, params

    def test_matches_sequential(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import pipeline_spmd_hetero, microbatch

        mesh = Mesh(np.asarray(jax.devices("cpu")[:4]), ("pp",))
        fns, params = self._stages()
        rng = np.random.default_rng(1)
        ids = jnp.asarray(rng.integers(0, 32, (8, 8)), jnp.int32)
        xm = microbatch(ids, 4)

        out = pipeline_spmd_hetero(fns, params, xm, mesh=mesh)
        # sequential reference
        want = []
        for m in range(4):
            h = xm[m]
            for f, p in zip(fns, params):
                h = f(p, h)
            want.append(h)
        want = jnp.stack(want)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=1e-5)

    def test_grads_match_sequential(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import pipeline_spmd_hetero, microbatch

        mesh = Mesh(np.asarray(jax.devices("cpu")[:4]), ("pp",))
        fns, params = self._stages()
        rng = np.random.default_rng(2)
        ids = jnp.asarray(rng.integers(0, 32, (4, 8)), jnp.int32)
        xm = microbatch(ids, 2)

        def loss_pipe(ps):
            out = pipeline_spmd_hetero(fns, ps, xm, mesh=mesh)
            return jnp.sum(jnp.sin(out))

        def loss_seq(ps):
            tot = 0.0
            for m in range(2):
                h = xm[m]
                for f, p in zip(fns, ps):
                    h = f(p, h)
                tot = tot + jnp.sum(jnp.sin(h))
            return tot

        gp = jax.grad(loss_pipe)(params)
        gs = jax.grad(loss_seq)(params)
        flat_p = jax.tree_util.tree_leaves(gp)
        flat_s = jax.tree_util.tree_leaves(gs)
        for a, b in zip(flat_p, flat_s):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)


class TestZeroBubblePipeline:
    """dW-deferred hand-written ring VJP (docs/pipeline_schedules.md r4):
    exact gradient parity with the AD-derived pipeline."""

    def test_matches_ad_pipeline(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import zb_linear_pipeline, pipeline_spmd

        mesh = Mesh(np.asarray(jax.devices("cpu")[:4]), ("pp",))
        rng = np.random.default_rng(0)
        S, M, B, D = 4, 4, 8, 32
        w = jnp.asarray(rng.standard_normal((S, D, D)) * 0.3, jnp.float32)
        x = jnp.asarray(rng.standard_normal((M, B, D)), jnp.float32)

        def block(wl, xb):
            return jnp.tanh(xb @ wl)

        np.testing.assert_allclose(
            np.asarray(zb_linear_pipeline(w, x, mesh=mesh)),
            np.asarray(pipeline_spmd(block, w, x, mesh=mesh)), atol=1e-5)

        g_ref = jax.grad(lambda w, x: jnp.sum(jnp.sin(
            pipeline_spmd(block, w, x, mesh=mesh))), (0, 1))(w, x)
        g_zb = jax.grad(lambda w, x: jnp.sum(jnp.sin(
            zb_linear_pipeline(w, x, mesh=mesh))), (0, 1))(w, x)
        for a, b in zip(g_zb, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4)


class TestZeroBubbleGPT:
    """Round-5 generalization (VERDICT r4 weak #3): the dW-deferred ring
    on the REAL transformer block — pipeline_spmd_zb(block_fn) with the
    GPTBlock body, gradient parity vs the AD-derived ring, both fwd and
    all param/input grads."""

    def _gpt_block_fn(self, h=16, heads=2):
        cfg = _tiny_cfg(hidden_size=h, num_attention_heads=heads)
        import paddle_tpu as paddle
        paddle.seed(0)
        from paddle_tpu.models.gpt import GPTBlock
        from paddle_tpu.framework.autograd import no_grad
        from paddle_tpu.framework.tensor import Tensor

        template = GPTBlock(cfg)
        leaves = [p for _, p in template.named_parameters()]

        def block_fn(leaf_list, xmb):
            with no_grad():
                saved = [p._data for p in leaves]
                for p, d in zip(leaves, leaf_list):
                    p._data = d
                try:
                    return template._inner(Tensor._wrap(xmb))._data
                finally:
                    for p, d in zip(leaves, saved):
                        p._data = d

        return template, block_fn

    @pytest.mark.slow  # ~15-23s multi-device parity; the dryrun
    # gate (zero-bubble pipe phase) covers this path in-budget
    def test_gpt_block_parity_pp4(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import pipeline_spmd, pipeline_spmd_zb

        S, M, B, h, seq = 4, 6, 2, 16, 8
        template, block_fn = self._gpt_block_fn(h=h)
        rng = np.random.default_rng(1)
        stacked = [jnp.asarray(
            rng.standard_normal((S,) + tuple(p.shape)) * 0.2, jnp.float32)
            for _, p in template.named_parameters()]
        x = jnp.asarray(rng.standard_normal((M, B, seq, h)), jnp.float32)
        mesh = Mesh(np.asarray(jax.devices("cpu")[:S]), ("pp",))

        out_ad = pipeline_spmd(block_fn, stacked, x, mesh=mesh)
        out_zb = pipeline_spmd_zb(block_fn, stacked, x, mesh=mesh)
        np.testing.assert_allclose(np.asarray(out_zb), np.asarray(out_ad),
                                   atol=1e-5)

        def loss_ad(p, xx):
            return jnp.sum(jnp.sin(pipeline_spmd(block_fn, p, xx,
                                                 mesh=mesh)))

        def loss_zb(p, xx):
            return jnp.sum(jnp.sin(pipeline_spmd_zb(block_fn, p, xx,
                                                    mesh=mesh)))

        g_ad = jax.grad(loss_ad, (0, 1))(stacked, x)
        g_zb = jax.grad(loss_zb, (0, 1))(stacked, x)
        for a, b in zip(jax.tree.leaves(g_zb), jax.tree.leaves(g_ad)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4)

    @pytest.mark.slow  # ~15-23s multi-device parity; the dryrun
    # gate (zero-bubble pipe phase) covers this path in-budget
    def test_dw_chunk_variants_agree(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import pipeline_spmd_zb

        S, M, B, h, seq = 2, 3, 2, 16, 4
        template, block_fn = self._gpt_block_fn(h=h)
        rng = np.random.default_rng(2)
        stacked = [jnp.asarray(
            rng.standard_normal((S,) + tuple(p.shape)) * 0.2, jnp.float32)
            for _, p in template.named_parameters()]
        x = jnp.asarray(rng.standard_normal((M, B, seq, h)), jnp.float32)
        mesh = Mesh(np.asarray(jax.devices("cpu")[:S]), ("pp",))

        def g(chunk):
            return jax.grad(lambda p: jnp.sum(pipeline_spmd_zb(
                block_fn, p, x, mesh=mesh, dw_chunk=chunk)))(stacked)

        for a, b in zip(jax.tree.leaves(g(1)), jax.tree.leaves(g(4))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)


class TestHeteroParamResidency:
    """r5 fix of VERDICT r4 weak #2: per-device resident param bytes in
    the hetero pipeline = the LARGEST SINGLE STAGE's total (the
    single-program-SPMD floor), not the old per-slot elementwise-max
    union that let a [vocab, hidden] embedding stage inflate every
    device's every slot. vocab >> hidden makes the difference stark."""

    def test_per_device_bytes_is_max_stage_total(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import _pack_stage_segments

        vocab, h = 4096, 16          # vocab >> hidden
        rng = np.random.default_rng(0)
        emb = {"table": jnp.asarray(rng.standard_normal((vocab, h)),
                                    jnp.float32)}
        blk = {"w1": jnp.asarray(rng.standard_normal((h, 4 * h)),
                                 jnp.float32),
               "w2": jnp.asarray(rng.standard_normal((4 * h, h)),
                                 jnp.float32),
               "b": jnp.zeros((h,), jnp.float32)}
        head = {"proj": jnp.asarray(rng.standard_normal((h, vocab)),
                                    jnp.float32)}
        stages = [emb, blk, dict(blk), head]
        mesh = Mesh(np.asarray(jax.devices("cpu")[:4]), ("pp",))
        flat = [jax.tree_util.tree_flatten(p) for p in stages]
        all_dtypes, seg_len, stacked = _pack_stage_segments(
            flat, mesh=mesh, axis="pp")

        stage_totals = [sum(int(np.prod(l.shape)) for l in leaves)
                        for leaves, _ in flat]
        max_total = max(stage_totals)
        # packed per-device elements == max stage total exactly
        per_device = sum(seg_len[dt] for dt in all_dtypes)
        assert per_device == max_total, (per_device, max_total)
        # each stacked array's per-device shard is [1, seg_len]
        for stk in stacked:
            shard = stk.addressable_shards[0]
            assert shard.data.shape[0] == 1
        # and the old union scheme would have been ~3x worse here: slot 0
        # union = max(vocab*h, h*4h, h*vocab) on EVERY device, slot 1
        # adds 4h*h, ... — at minimum the two vocab-sized shapes both
        # land in the union while only ONE can be a real stage's max
        union_lower_bound = vocab * h + 4 * h * h
        assert per_device < union_lower_bound

    def test_hetero_pipeline_still_correct_vocab_gg_hidden(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import pipeline_spmd_hetero

        vocab, h, seq = 512, 8, 4
        rng = np.random.default_rng(1)
        p_emb = {"table": jnp.asarray(
            rng.standard_normal((vocab, h)) * 0.1, jnp.float32)}
        p_blk = {"w": jnp.asarray(rng.standard_normal((h, h)) * 0.3,
                                  jnp.float32)}
        p_head = {"proj": jnp.asarray(
            rng.standard_normal((h, vocab)) * 0.1, jnp.float32)}

        def emb(p, ids):
            return p["table"][ids]

        def blk(p, x):
            return jnp.tanh(x @ p["w"])

        def head(p, x):
            return x @ p["proj"]

        fns = [emb, blk, blk, head]
        params = [p_emb, p_blk, dict(p_blk), p_head]
        ids = jnp.asarray(rng.integers(0, vocab, (6, 2, seq)), jnp.int32)
        mesh = Mesh(np.asarray(jax.devices("cpu")[:4]), ("pp",))
        got = pipeline_spmd_hetero(fns, params, ids, mesh=mesh)

        def seq_ref(x):
            y = emb(p_emb, x)
            y = blk(p_blk, y)
            y = blk(p_blk, y)
            return head(p_head, y)

        want = jax.vmap(seq_ref)(ids)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5)


class TestZeroBubbleModelPath:
    """use_zero_bubble through the full GPTForCausalLMPipe forward: the
    stacked [n_stages, layers_per_stage] leaves, _block_fn's inner scan,
    and the apply_op wrapper around the custom_vjp — loss AND grads must
    match the AD-ring model (r5 review finding: the direct-block test
    could not see these layers)."""

    @pytest.mark.slow  # ~15-23s multi-device parity; the dryrun
    # gate (zero-bubble pipe phase) covers this path in-budget
    def test_model_loss_and_grads_match_ad_ring(self):
        cfg = _tiny_cfg()
        mesh = _mesh(2)
        paddle.seed(0)
        ad = GPTForCausalLMPipe(cfg, num_stages=2, num_micro=2, mesh=mesh)
        paddle.seed(0)
        zb = GPTForCausalLMPipe(cfg, num_stages=2, num_micro=2, mesh=mesh,
                                use_zero_bubble=True)
        for (n1, p1), (n2, p2) in zip(ad.named_parameters(),
                                      zb.named_parameters()):
            assert n1 == n2
            p2._data = p1._data

        rng = np.random.default_rng(3)
        ids = paddle.to_tensor(rng.integers(0, 64, (4, 16)), dtype="int64")
        labels = paddle.to_tensor(rng.integers(0, 64, (4, 16)),
                                  dtype="int64")
        crit = GPTPretrainingCriterion()
        l_ad = crit(ad(ids), labels)
        l_zb = crit(zb(ids), labels)
        assert abs(float(l_ad) - float(l_zb)) < 1e-5
        l_ad.backward()
        l_zb.backward()
        for (n, pa), (_, pz) in zip(ad.named_parameters(),
                                    zb.named_parameters()):
            assert (pa.grad is None) == (pz.grad is None), n
            if pa.grad is not None:
                np.testing.assert_allclose(
                    np.asarray(pa.grad._data), np.asarray(pz.grad._data),
                    atol=2e-4, err_msg=n)

    def test_rejects_dropout(self):
        cfg = _tiny_cfg(hidden_dropout_prob=0.1)
        mesh = _mesh(2)
        import pytest as _pytest
        with _pytest.raises(ValueError, match="dropout"):
            GPTForCausalLMPipe(cfg, num_stages=2, num_micro=2, mesh=mesh,
                               use_zero_bubble=True)


class TestVPPTrainParity:
    """VPP (num_chunks=2, the interleave schedule) under the FULL train
    path: loss AND parameter grads match the plain single-device model
    carrying the same weights (r5 — VERDICT r4 weak #6 named VPP as
    never parity-exercised beyond a forward test)."""

    @pytest.mark.slow  # ~15-23s multi-device parity; the dryrun
    # gate (zero-bubble pipe phase) covers this path in-budget
    def test_chunks2_loss_and_grads_match_plain(self):
        cfg = _tiny_cfg()                    # 4 layers
        mesh = _mesh(2)
        paddle.seed(0)
        plain = GPTForCausalLM(cfg)
        pipe = GPTForCausalLMPipe(cfg, num_stages=2, num_micro=2,
                                  num_chunks=2, mesh=mesh)
        _copy_plain_into_pipe(plain, pipe, 2, 1, num_chunks=2)

        rng = np.random.default_rng(5)
        ids = paddle.to_tensor(rng.integers(0, 64, (4, 16)),
                               dtype="int64")
        labels = paddle.to_tensor(rng.integers(0, 64, (4, 16)),
                                  dtype="int64")
        crit = GPTPretrainingCriterion()
        l_plain = crit(plain(ids), labels)
        l_pipe = crit(pipe(ids), labels)
        assert abs(float(l_plain) - float(l_pipe)) < 1e-5
        l_plain.backward()
        l_pipe.backward()
        sd = dict(plain.named_parameters())
        # VPP placement: chunk c on stage s holds layer c*n_stages + s;
        # check one early and one late layer's qkv grad
        stk = pipe._parameters["blocks__attn__qkv__weight"].grad._data
        np.testing.assert_allclose(
            np.asarray(sd["gpt.blocks.0.attn.qkv.weight"].grad._data),
            np.asarray(stk[0, 0, 0]), atol=1e-5)     # stage0 chunk0
        np.testing.assert_allclose(
            np.asarray(sd["gpt.blocks.3.attn.qkv.weight"].grad._data),
            np.asarray(stk[1, 1, 0]), atol=1e-5)     # stage1 chunk1
        np.testing.assert_allclose(
            np.asarray(sd["gpt.wte.weight"].grad._data),
            np.asarray(pipe.wte.weight.grad._data), atol=1e-5)
