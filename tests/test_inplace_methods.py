"""Auto-bound in-place Tensor-method semantics (framework/
tensor_methods.py generated variants): each must (a) return self,
(b) leave the buffer equal to the out-of-place op, (c) rebind IN PLACE
so aliases observe the update."""
import numpy as np
import pytest

import paddle_tpu as paddle

UNARY = ["abs_", "ceil_", "floor_", "round_", "exp_", "log_", "sqrt_",
         "tanh_", "sigmoid_", "relu_", "erfinv_", "trunc_", "frac_",
         "log1p_", "reciprocal_", "rsqrt_"]


@pytest.mark.parametrize("name", UNARY)
def test_unary_inplace_matches_outofplace(name):
    t = paddle.to_tensor(np.array([0.3, 0.7, 0.9], np.float32))
    if not hasattr(t, name):
        pytest.skip(f"{name} not bound")
    base = getattr(t, name[:-1])()
    holder = [t]                # a real alias container (optimizer-list
    ret = getattr(t, name)()    # shape): must observe the mutation
    assert ret is t
    np.testing.assert_allclose(holder[0].numpy(), base.numpy(),
                               rtol=1e-6)
    assert holder[0] is ret


BINARY = ["add_", "subtract_", "multiply_", "divide_", "pow_",
          "remainder_", "floor_divide_", "maximum_" ]


@pytest.mark.parametrize("name", BINARY)
def test_binary_inplace_matches_outofplace(name):
    t = paddle.to_tensor(np.array([2.0, 5.0, 9.0], np.float32))
    o = paddle.to_tensor(np.array([2.0, 2.0, 4.0], np.float32))
    if not hasattr(t, name):
        pytest.skip(f"{name} not bound")
    base = getattr(t, name[:-1])(o)
    ret = getattr(t, name)(o)
    assert ret is t
    np.testing.assert_allclose(t.numpy(), base.numpy(), rtol=1e-6)


def test_structural_inplace():
    t = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    t.transpose_([1, 0])
    assert list(t.shape) == [3, 2]
    t.flatten_()
    assert list(t.shape) == [6]
    u = paddle.to_tensor(np.ones((3, 3), np.float32))
    u.tril_()
    assert u.numpy()[0, 2] == 0.0
    u.zero_()
    assert float(u.sum()) == 0.0


def test_cast_inplace_changes_dtype():
    t = paddle.to_tensor(np.array([1.5, 2.5], np.float32))
    t.cast_("float64")
    assert "float64" in str(t.dtype)


def test_random_inplace_fill_shapes():
    t = paddle.zeros([64])
    t.uniform_(min=-2.0, max=-1.0)
    arr = t.numpy()
    assert (arr >= -2.0).all() and (arr <= -1.0).all()
    b = paddle.zeros([1000])
    b.bernoulli_(p=0.3)
    assert 0.15 < float(b.mean()) < 0.45
