"""MoE layer with expert parallelism.

Reference parity: python/paddle/incubate/distributed/models/moe/moe_layer.py
(MoELayer :263, global_scatter :119, global_gather :140) with gshard/switch
gates (gate/).

TPU-first: the reference routes tokens with index-list global_scatter/
global_gather collectives (NCCL alltoall of ragged buffers). Here routing is
the GShard einsum formulation — dense [T,E,C] dispatch/combine masks, expert
params STACKED on a leading E dim sharded over the ``ep`` mesh axis, and a
vmap over experts; XLA GSPMD lowers the dispatch/combine einsums to the
all-to-alls on ICI. Static shapes (capacity) keep it jit-compilable; drops
are mask zeros, not ragged buffers.

Real expert parallelism (ISSUE 9): when the forward traces inside a
`shard_map` that binds the ``ep`` axis AND the bound expert stacks are the
rank's 1/ep slice (the dp×ep scan train step's layout —
jit/sharded_scan.py `_setup_ep`), the dispatch/combine become EXPLICIT
`jax.lax.all_to_all`s: the [E, C, H] capacity-padded dispatch buffer
splits its expert dim over ep and concatenates capacity, each rank runs
its E/ep local experts over the ep·C tokens it received, and the inverse
all_to_all brings expert outputs home. Capacity padding is what makes the
equal-split wire format legal for ragged per-expert token counts — the
same trick `global_scatter`/`global_gather` use for ragged count vectors.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..... import nn
from .....framework.tensor import Tensor
from .....framework.autograd import apply_op, no_grad
from .....nn.layer.layers import Parameter
from .gate import NaiveGate

__all__ = ["MoELayer", "ExpertFFN", "global_scatter", "global_gather"]


class ExpertFFN(nn.Layer):
    """Default expert: fc1 -> gelu -> fc2 (the reference examples' expert)."""

    def __init__(self, d_model, d_hidden):
        super().__init__()
        self.fc1 = nn.Linear(d_model, d_hidden)
        self.fc2 = nn.Linear(d_hidden, d_model)

    def forward(self, x):
        return self.fc2(nn.functional.gelu(self.fc1(x)))


class MoELayer(nn.Layer):
    """Mixture-of-experts over an expert-parallel mesh axis.

    Args:
      d_model: token feature size.
      experts: list of identically-structured expert Layers (their initial
        params are stacked onto a leading num_experts dim).
      gate: "gshard" (top-2) | "switch" (top-1) | a NaiveGate instance.
      capacity_factor: per-expert slots = ceil(cf * T / E). float("inf")
        disables dropping (capacity = T).
      axis: expert-parallel mesh axis name; stacked params are sharded over
        it when the ambient mesh has the axis.

    After forward, ``self.l_aux`` holds the load-balancing loss Tensor
    (add it to the training loss, reference MoELayer semantics).
    """

    def __init__(self, d_model, experts, gate="gshard",
                 capacity_factor=1.25, axis="ep", mesh=None, group=None,
                 ep_degree=None):
        super().__init__()
        self.d_model = int(d_model)
        self.num_experts = len(experts)
        self.capacity_factor = capacity_factor
        self.gate = gate if isinstance(gate, NaiveGate) else NaiveGate(gate)
        self._axis = axis
        self._mesh = group.mesh if group is not None else mesh
        # ep_degree: declared expert-parallel degree (validated here so a
        # bad layout fails at construction, not at trace time); None =
        # whatever the ambient mesh's ep axis provides
        if ep_degree is not None:
            ep_degree = int(ep_degree)
            if ep_degree < 1 or self.num_experts % ep_degree:
                raise ValueError(
                    f"num_experts {self.num_experts} not divisible by "
                    f"ep_degree {ep_degree}")
        self.ep_degree = ep_degree
        self.gate_weight = self.create_parameter(
            [self.d_model, self.num_experts])

        template = experts[0]
        object.__setattr__(self, "_template", template)
        names = [n for n, _ in template.named_parameters()]
        self._stacked_names = []
        for pname in names:
            stacked = jnp.stack([
                dict(e.named_parameters())[pname]._data for e in experts])
            flat = "experts__" + pname.replace(".", "__")
            self.add_parameter(flat, Parameter(stacked))
            self._stacked_names.append((flat, pname))
        self.l_aux = None
        self._shard_params()

    def _resolve_mesh(self):
        mesh = self._mesh
        if mesh is None:
            from .....distributed import env as denv

            if denv.is_initialized():
                mesh = denv.get_mesh()
        if mesh is not None and self._axis in mesh.axis_names \
                and mesh.shape[self._axis] > 1:
            return mesh
        return None

    def _shard_params(self):
        mesh = self._resolve_mesh()
        if mesh is None:
            return
        for flat, _ in self._stacked_names:
            p = self._parameters[flat]
            if p._data.shape[0] % mesh.shape[self._axis] == 0:
                spec = P(self._axis, *([None] * (p._data.ndim - 1)))
                p._data = jax.device_put(p._data,
                                         NamedSharding(mesh, spec))

    def _capacity(self, num_tokens):
        if math.isinf(self.capacity_factor):
            return int(num_tokens)
        return max(1, int(math.ceil(
            self.capacity_factor * num_tokens / self.num_experts)))

    def forward(self, x):
        orig_shape = x.shape
        hidden = orig_shape[-1]
        if hidden != self.d_model:
            raise ValueError(f"expected feature dim {self.d_model}, "
                             f"got {hidden}")
        num_tokens = 1
        for s in orig_shape[:-1]:
            num_tokens *= s
        capacity = self._capacity(num_tokens)
        gate_fn = self.gate
        mesh = self._resolve_mesh()
        axis = self._axis
        template = self._template
        leaves = [p for _, p in template.named_parameters()]
        stacked = [self._parameters[flat] for flat, _ in self._stacked_names]

        def expert_apply(layer_leaves, xe):
            with no_grad():
                saved = [p._data for p in leaves]
                for p, d in zip(leaves, layer_leaves):
                    p._data = d
                try:
                    out = template(Tensor._wrap(xe))._data
                finally:
                    for p, d in zip(leaves, saved):
                        p._data = d
            return out

        num_experts = self.num_experts

        def moe_fn(xa, wg, *stacked_leaves):
            xt = xa.reshape(num_tokens, hidden)
            logits = (xt.astype(jnp.float32)
                      @ wg.astype(jnp.float32))
            combine, dispatch, aux = gate_fn(logits, capacity)
            combine = combine.astype(xt.dtype)
            expert_in = jnp.einsum(
                "tec,th->ech", dispatch.astype(xt.dtype), xt)
            e_loc = int(stacked_leaves[0].shape[0])
            if e_loc != num_experts:
                # REAL expert parallelism: the bound stacks are this
                # rank's 1/ep expert slice inside a shard_map binding the
                # ep axis (the dp×ep scan step). Ship each expert's
                # capacity-padded token block to its owner (split the
                # expert dim, concatenate capacity), run the local
                # experts over the ep·C tokens received, and all_to_all
                # the outputs home. Shapes are static — capacity padding
                # is what makes the equal-split wire format legal.
                from .....distributed.collective import _axis_bound

                if not _axis_bound(axis):
                    raise RuntimeError(
                        f"MoELayer bound {e_loc}/{num_experts} expert "
                        f"slices but mesh axis {axis!r} is not bound in "
                        "this trace — expert-parallel dispatch needs the "
                        "shard_map context that sliced the experts")
                recv = jax.lax.all_to_all(
                    expert_in, axis, split_axis=0, concat_axis=1,
                    tiled=True)                   # [E/ep, ep*C, H]
                out = jax.vmap(expert_apply)(list(stacked_leaves), recv)
                expert_out = jax.lax.all_to_all(
                    out, axis, split_axis=1, concat_axis=0,
                    tiled=True)                   # [E, C, H]
            else:
                if mesh is not None:
                    from .....distributed.env import pin_sharding

                    spec = P(axis, *([None] * (expert_in.ndim - 1)))
                    expert_in = pin_sharding(expert_in,
                                             NamedSharding(mesh, spec))
                expert_out = jax.vmap(expert_apply)(list(stacked_leaves),
                                                    expert_in)
            y = jnp.einsum("tec,ech->th", combine, expert_out)
            return y.reshape(orig_shape), aux.astype(jnp.float32)

        y, aux = apply_op(moe_fn, [x, self.gate_weight] + stacked,
                          name="moe")
        self.l_aux = aux
        return y


def _default_group():
    """World group when the distributed env is up, else None (count checks
    that need a group are skipped outside a mesh)."""
    from .....distributed import env as denv

    if not denv.is_initialized():
        return None
    from .....distributed.collective import get_group

    return get_group()


def _validated_counts(local_count, global_count, name, x=None, group=None):
    """The reference kernels move count-shaped ragged buffers
    (distributed/utils/moe_utils.py global_scatter/global_gather). The XLA
    all_to_all wire is equal-split, so the counts are VERIFIED rather than
    silently ignored, then routed: uniform counts describe exactly the
    equal-split exchange (fast path); ragged counts run through the
    capacity-padded equal-split exchange (`_ragged_exchange` — pad every
    bucket to the max count, all_to_all the padded blocks, compact). The
    remaining errors mark genuinely unsupported shapes: traced counts
    (the layout must be host-known to build the pad/compact maps),
    local/global count vectors that disagree (the single-controller
    global view runs every rank's identical program, so the receive
    layout IS derived from the send layout), mismatched totals, and
    count vectors that don't tile over the group.

    Returns (lc, gc) as host numpy arrays (or None)."""
    import numpy as np

    counts = []
    for c in (local_count, global_count):
        if c is None:
            counts.append(None)
            continue
        data = c._data if isinstance(c, Tensor) else c
        if isinstance(data, jax.core.Tracer):
            raise NotImplementedError(
                f"{name} with traced counts cannot drive the host-built "
                "pad/compact maps; use MoELayer's dense capacity "
                "dispatch inside jit")
        counts.append(np.asarray(data))
    lc, gc = counts
    if lc is not None and gc is not None and lc.sum() != gc.sum():
        raise ValueError(
            f"{name}: local_count total ({int(lc.sum())}) != global_count "
            f"total ({int(gc.sum())}) — the exchange would lose tokens")
    if lc is not None and gc is not None and (
            lc.size != gc.size or not np.array_equal(lc, gc)):
        raise ValueError(
            f"{name}: local_count {lc.tolist()} and global_count "
            f"{gc.tolist()} disagree. In the single-controller global "
            "view every rank runs the same program over the same count "
            "vector, so the receive layout is derived from the send "
            "layout — per-rank-distinct count vectors are not "
            "representable here (run the reference per-rank API under "
            "multi-process SPMD for that)")
    # counts must actually describe the exchange: length a multiple of
    # nranks (n_expert * world entries) and totals covering x's rows
    # (global leading dim = nranks * per-rank rows)
    if group is not None and lc is not None:
        nranks = group.nranks
        if lc.size % nranks:
            raise ValueError(
                f"{name}: counts length {lc.size} is not a multiple of "
                f"the group's nranks ({nranks})")
        if x is not None:
            rows = (x._data if isinstance(x, Tensor)
                    else jnp.asarray(x)).shape[0]
            if int(lc.sum()) * nranks != rows:
                raise ValueError(
                    f"{name}: counts route {int(lc.sum())} rows/rank x "
                    f"{nranks} ranks but x has {rows} rows")
    return lc, gc


def _ragged_exchange(x, counts, group, inverse=False):
    """Capacity-padded equal-split exchange of ragged per-expert buckets
    (single-controller global view).

    Layout contract (destination-major, the reference moe_utils layout):
    rank r's section of `x` holds, for each bucket b = d*n_e + e,
    ``counts[b]`` rows destined to rank d's local expert e
    (``inverse=False``); the result is source-major — rank r's section
    holds, for each source s and local expert e, the ``counts[r*n_e+e]``
    rows s sent it. ``inverse=True`` applies the exact inverse map (the
    gather direction). The wire carries ONE equal-split all_to_all of
    [nranks · n_expert · capacity] blocks, capacity = max(counts); pad
    rows are zeros and never reach the output.
    """
    import numpy as np

    from .....distributed.collective import alltoall_single

    data = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    W = group.nranks
    counts = np.asarray(counts, np.int64)
    B = counts.size
    n_e = B // W
    S = int(counts.sum())
    cap = max(1, int(counts.max()))
    feat = data.shape[1:]
    off = np.zeros(B, np.int64)
    off[1:] = np.cumsum(counts)[:-1]
    grp_sum = counts.reshape(W, n_e).sum(axis=1)          # per-rank-group
    grp_off = np.zeros((W, n_e), np.int64)
    grp_off[:, 1:] = np.cumsum(counts.reshape(W, n_e), axis=1)[:, :-1]
    # scattered-layout section offsets (sections are W*sum(group_r) rows)
    sec = np.zeros(W + 1, np.int64)
    sec[1:] = np.cumsum(W * grp_sum)

    # pack map: padded[r, d, e, c] <- x row (or -1 = zero pad).
    pack = np.full((W, W, n_e, cap), -1, np.int64)
    # unpack map: out_row <- padded-recv flat index (r, s, e, c)
    if inverse:
        total_out = W * S
    else:
        total_out = int(sec[-1])
    unpack = np.zeros(total_out, np.int64)
    for r in range(W):
        for d in range(W):
            for e in range(n_e):
                if inverse:
                    cnt = int(counts[r * n_e + e])
                    src = (sec[r] + d * grp_sum[r] + grp_off[r, e]
                           + np.arange(cnt))
                else:
                    cnt = int(counts[d * n_e + e])
                    src = r * S + off[d * n_e + e] + np.arange(cnt)
                pack[r, d, e, :cnt] = src
                # receive side of block (r<-s=d): where its rows land
                if inverse:
                    # gather: rows return to destination-major order
                    cnt_in = int(counts[d * n_e + e])
                    dst = r * S + off[d * n_e + e] + np.arange(cnt_in)
                    flat = (((r * W + d) * n_e + e) * cap
                            + np.arange(cnt_in))
                else:
                    cnt_in = int(counts[r * n_e + e])
                    dst = (sec[r] + d * grp_sum[r] + grp_off[r, e]
                           + np.arange(cnt_in))
                    flat = (((r * W + d) * n_e + e) * cap
                            + np.arange(cnt_in))
                unpack[dst] = flat

    pack_flat = pack.reshape(-1)
    mask = jnp.asarray((pack_flat >= 0).reshape(-1, *([1] * len(feat))),
                       data.dtype)
    pack_idx = jnp.asarray(np.maximum(pack_flat, 0))
    unpack_idx = jnp.asarray(unpack)

    def pad_fn(d):
        return jnp.take(d, pack_idx, axis=0) * mask

    padded = apply_op(pad_fn, [x if isinstance(x, Tensor)
                               else Tensor._wrap(data)], name="moe_pad")
    # shard the rank-major padded buffer over the group axis and run the
    # REAL equal-split collective
    if len(group.axes) == 1:
        spec = P(group.axes[0], *([None] * len(feat)))
        padded._data = jax.device_put(
            padded._data, NamedSharding(group.mesh, spec))
    exchanged = alltoall_single(None, padded, group=group)

    def compact_fn(d):
        return jnp.take(d, unpack_idx, axis=0)

    return apply_op(compact_fn, [exchanged], name="moe_compact")


def global_scatter(x, local_count, global_count, group=None):
    """Reference moe_layer.py:119 — alltoall token push. Counts are
    validated, never silently ignored: uniform counts ride the direct
    equal-split all_to_all; ragged per-expert counts ride the
    capacity-padded equal-split exchange (`_ragged_exchange`)."""
    from .....distributed.collective import alltoall_single

    group = group or _default_group()
    lc, _ = _validated_counts(local_count, global_count,
                              "global_scatter", x=x, group=group)
    if lc is not None and len(set(lc.tolist())) > 1:
        if group is None:
            raise ValueError(
                "global_scatter with ragged counts needs a group/mesh "
                "(the exchange layout depends on nranks)")
        return _ragged_exchange(x, lc, group, inverse=False)
    out = Tensor(jnp.zeros_like(x._data if isinstance(x, Tensor)
                                else jnp.asarray(x)))
    alltoall_single(out, x, group=group)
    return out


def global_gather(x, local_count, global_count, group=None):
    """Reference moe_layer.py:140 — inverse alltoall pull (the exact
    inverse of `global_scatter`, incl. the ragged capacity-padded
    path)."""
    from .....distributed.collective import alltoall_single

    group = group or _default_group()
    lc, _ = _validated_counts(local_count, global_count,
                              "global_gather", x=x, group=group)
    if lc is not None and len(set(lc.tolist())) > 1:
        if group is None:
            raise ValueError(
                "global_gather with ragged counts needs a group/mesh "
                "(the exchange layout depends on nranks)")
        return _ragged_exchange(x, lc, group, inverse=True)
    out = Tensor(jnp.zeros_like(x._data if isinstance(x, Tensor)
                                else jnp.asarray(x)))
    alltoall_single(out, x, group=group)
    return out
