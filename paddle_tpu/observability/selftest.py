"""Hermetic observability selftest (ISSUE 12 acceptance lane).

Run as ``python -m paddle_tpu.observability.selftest`` in a clean
JAX_PLATFORMS=cpu subprocess with 8 virtual host devices (bench.py
run_selftest wires it through the same env-strip recipe as the other
lanes; ``python bench.py --observability`` is the CLI) and prints ONE
JSON line for BENCH_r*.json:

* **registry overhead** — the measured cost of everything the telemetry
  layer adds to an instrumented train step (sentinel signature check,
  timeline record + chrome counter, histogram observes) is <= 1% of the
  measured step time;
* **retrace sentinel** — on ALL THREE train-step paths (`TrainStep`,
  `FusedScanTrainStep`, `ShardedFusedScanTrainStep` on the 8-device
  host mesh) a deliberately injected labels-dtype flip is attributed to
  the exact argument leaf, and strict mode raises `RetraceError`
  BEFORE the bad dispatch; clean runs stay at ONE signature with zero
  unexpected events (strict active throughout, never tripping);
* **zero added retraces / host transfers** — the instrumented steps
  hold exactly one compiled executable after N steps and their lowered
  HLO contains no host-transfer ops (the PR-4 probe pattern: telemetry
  must never touch the compiled program);
* **timeline JSONL schema** — records round-trip through the file sink
  byte-exactly, with the required ts/lane/step keys;
* **Prometheus exposition** — ``registry().expose()`` parses as valid
  text-format lines with TYPE headers and summary quantiles, including
  sanitized names and spec-conformant non-finite values;
* **serve-loop tracing overhead (ISSUE 13)** — the per-step work the
  request-tracing layer adds to a serving engine (span begin/ends for
  a full slot batch, SLO observes, the exemplar threshold check, the
  dispatch-time observe) is measured against a representative engine's
  decode step, with the debug HTTP server live, and must stay <= 1%.
"""
from __future__ import annotations

import json
import time

import numpy as np

TINY = dict(vocab_size=96, hidden_size=32, num_layers=4,
            num_attention_heads=2, max_position_embeddings=16,
            hidden_dropout_prob=0.0, attention_dropout_prob=0.0)

HOST_TRANSFER_OPS = ("infeed", "outfeed", "send(", "recv(",
                     "host_callback")


def _steps(n_devices=8, seed=0):
    """One instance of each train-step path on a tiny GPT + its batch."""
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as popt
    from paddle_tpu.distributed import env as denv
    from paddle_tpu.jit import (
        FusedScanTrainStep, ShardedFusedScanTrainStep, TrainStep,
    )
    from paddle_tpu.models import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    crit = GPTPretrainingCriterion()
    rng = np.random.default_rng(seed)
    ids = paddle.to_tensor(
        rng.integers(0, TINY["vocab_size"], (n_devices, 16)),
        dtype="int64")
    labels = paddle.to_tensor(
        rng.integers(0, TINY["vocab_size"], (n_devices, 16)),
        dtype="int64")

    def build(kind):
        cfg = GPTConfig(**TINY, scan_layers=(kind != "eager"))
        paddle.seed(seed)
        model = GPTForCausalLM(cfg)
        opt = popt.AdamW(learning_rate=1e-3,
                         parameters=model.parameters())
        if kind == "eager":
            return TrainStep(model, lambda m, a, b: crit(m(a), b), opt)
        if kind == "fused":
            return FusedScanTrainStep(model, opt, criterion=crit)
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.devices("cpu")[:n_devices]),
                    ("sharding",))
        denv.set_mesh(mesh)
        return ShardedFusedScanTrainStep(model, opt, criterion=crit,
                                         mesh=mesh, axis="sharding")

    return build, ids, labels


def run_probe(n_devices=8):
    import jax
    import paddle_tpu as paddle  # noqa: F401 — jax compat shims
    from paddle_tpu import observability as obs

    devs = jax.devices("cpu")
    if len(devs) < n_devices:
        return {"observability":
                {"check": f"FAIL: {len(devs)} cpu devices"}}
    obs.set_strict_retrace(True)   # active for the WHOLE lane
    rec, fails = {}, []

    def check(name, fn):
        try:
            fn()
            rec[name] = "pass"
        except Exception as e:  # noqa: BLE001 — recorded, not raised
            rec[name] = f"FAIL: {type(e).__name__}: {e}"[:300]
            fails.append(name)

    build, ids, labels = _steps(n_devices)

    # -- retrace sentinel: attribution + strict + zero-added probes ----
    def sentinel_path(kind):
        import jax.numpy as jnp

        step = build(kind)
        for _ in range(3):
            step(ids, labels)
        st = step.retrace_stats()
        assert st["signatures"] == 1, st       # clean run: one trace
        assert st["unexpected"] == 0, st
        if hasattr(step._jitted, "_cache_size"):
            assert step._jitted._cache_size() == 1   # no added retrace
        # telemetry must never touch the compiled program: no host
        # transfer op in the lowered HLO (PR-4 probe pattern)
        state = step._extract_state()
        lr = jnp.float32(1e-3)
        args = ((state, lr, [ids._data, labels._data])
                if kind == "eager"
                else (state, lr, ids._data, labels._data, None))
        guard = getattr(step, "_step_guard", None)
        import contextlib

        with (guard() if guard else contextlib.nullcontext()):
            text = step._jitted.lower(*args).as_text()
        for op in HOST_TRANSFER_OPS:
            assert op not in text, f"host transfer {op!r} in {kind} HLO"
        # inject the dtype flip: strict mode must raise BEFORE dispatch
        # and the event must NAME the offending leaf
        bad = labels.astype("int32")
        try:
            step(ids, bad)
            raise AssertionError(
                f"{kind}: injected dtype flip not caught")
        except obs.RetraceError as e:
            msg = str(e)
        assert "labels" in msg or "batch[1]" in msg, msg
        assert "dtype" in msg and "int32" in msg, msg
        st = step.retrace_stats()
        assert st["unexpected"] == 1, st
        ev = st["events"][-1]
        assert any(("labels" in c or "batch[1]" in c)
                   and "dtype" in c for c in ev["changes"]), ev
        # the raise happened before the bad dispatch: the step still
        # works and still holds ONE executable
        step(ids, labels)
        if hasattr(step._jitted, "_cache_size"):
            assert step._jitted._cache_size() == 1
        rec[f"sentinel_{kind}_event"] = ev["changes"][:3]

    check("retrace_sentinel_eager", lambda: sentinel_path("eager"))
    check("retrace_sentinel_fused", lambda: sentinel_path("fused"))
    check("retrace_sentinel_sharded", lambda: sentinel_path("sharded"))

    # -- registry/telemetry overhead <= 1% of step time ----------------
    def overhead():
        import jax.numpy as jnp
        import paddle_tpu as paddle
        import paddle_tpu.optimizer as popt
        from paddle_tpu.jit import FusedScanTrainStep
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        # a representative (not toy) step: the bound is a RATIO, so the
        # denominator must look like a real train step, and the
        # numerator is timed on this step's own full state tree
        cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=4,
                        num_attention_heads=4,
                        max_position_embeddings=64,
                        hidden_dropout_prob=0.0,
                        attention_dropout_prob=0.0, scan_layers=True)
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
        opt = popt.AdamW(learning_rate=1e-3,
                         parameters=model.parameters())
        step = FusedScanTrainStep(model, opt)
        rng = np.random.default_rng(1)
        ids = paddle.to_tensor(rng.integers(0, 256, (8, 64)),
                               dtype="int64")
        labels = paddle.to_tensor(rng.integers(0, 256, (8, 64)),
                                  dtype="int64")
        step(ids, labels)                      # compile outside timing
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            loss = step(ids, labels)
            jax.block_until_ready(loss._data)
            times.append(time.perf_counter() - t0)
        step_ms = min(times) * 1e3
        # the per-step telemetry work an instrumented step performs:
        # the sentinel signature check over the full state tree, a
        # timeline record through a sink, and the producer histogram
        # observes — timed directly on the same live objects
        state = step._extract_state()
        lr = jnp.float32(1e-3)
        tl = obs.StepTimeline(sinks=[lambda r: None], lane="overhead")
        reg = obs.registry()
        h1 = reg.histogram("input.stall_ms")
        h2 = reg.histogram("input.h2d_ms")
        reps = 50
        t0 = time.perf_counter()
        for i in range(reps):
            step._sentinel.observe(
                (state, lr, ids._data, labels._data, None),
                names=("state", "lr", "ids", "labels", "segment_ids"))
            tl.record(step=i, host_ms=step_ms, loss_scale=1.0)
            h1.observe(0.01)
            h2.observe(0.5)
        telemetry_ms = (time.perf_counter() - t0) / reps * 1e3
        ratio = telemetry_ms / step_ms
        rec["overhead"] = {
            "step_ms": round(step_ms, 3),
            "telemetry_ms_per_step": round(telemetry_ms, 4),
            "ratio": round(ratio, 5),
        }
        assert ratio <= 0.01, rec["overhead"]

    check("registry_overhead", overhead)

    # -- serving: tracing + SLO + debug server <= 1% of serve loop -----
    def tracing_serve_overhead():
        import paddle_tpu as paddle
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.serving import ServingEngine

        # representative (not toy) serving model — the bound is a
        # RATIO, so the denominator must look like a step a production
        # engine would run (h256/8L is still ~1000x under a real
        # serving model; the ratio only gets MORE comfortable there)
        cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=8,
                        num_attention_heads=8,
                        max_position_embeddings=128,
                        hidden_dropout_prob=0.0,
                        attention_dropout_prob=0.0)
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
        model.eval()
        slots = 8
        # decode_burst=4 is the bench-lane serving configuration
        # (multi-step scheduling); the per-step tracing work is
        # per-BURST, so this is the ratio production pays
        eng = ServingEngine(model, max_slots=slots, max_len=96,
                            page_size=16, chunk_size=32,
                            decode_burst=4,
                            slos=[("ttft", "ttft_s", 0.25),
                                  ("itl", "itl_s", 0.05)])
        port = eng.start_debug_server()       # live during measurement
        assert port
        rng = np.random.default_rng(3)
        for i in range(slots):
            eng.submit(rng.integers(1, 256, (24,)), 64, seed=i)
        # drive until every slot is decode-active, compile included
        while len(eng.scheduler.decode_slots()) < slots:
            eng.step()
        times = []
        for _ in range(8):
            t0 = time.perf_counter()
            eng.step()
            times.append(time.perf_counter() - t0)
        step_ms = min(times) * 1e3
        # drain the async tail of the last dispatch before timing the
        # host-side tracing work — leftover XLA pool threads contend
        # for this container's capped cores and would inflate the
        # numerator ~40x
        jax.block_until_ready(eng._buffers)
        time.sleep(0.05)
        # the instrumentation one steady decode step adds, timed on the
        # SAME live objects: a decode_burst + stream_deliver span pair
        # per slot, the retired-flush sweep, the dispatch-time observe,
        # plus a retire's SLO feeds and exemplar threshold check (an
        # overestimate — retires are per request, not per step)
        tracer = eng.tracer
        roots = [eng.tracer.begin("request", track=f"ov{i}")
                 for i in range(slots)]
        reps = 50

        def trial():
            t0 = time.perf_counter()
            for _ in range(reps):
                spans = [tracer.begin("decode_burst", parent=r, slot=i,
                                      k=1, batch=slots)
                         for i, r in enumerate(roots)]
                for sp in spans:
                    tracer.end(sp)
                spans = [tracer.begin("stream_deliver", parent=r)
                         for r in roots]
                for sp in spans:
                    tracer.end(sp, tokens=1)
                eng._flush_retired()
                eng.decode_step._dispatch_hist.observe(step_ms)
                eng.slo.observe_metric("ttft_s", 0.01)
                eng.slo.observe_metric("itl_s", 0.001)
                eng._exemplar_thresholds()
            return (time.perf_counter() - t0) / reps * 1e3

        tracing_ms = min(trial() for _ in range(3))
        for r in roots:
            tracer.end(r)
        eng.stop_debug_server()
        ratio = tracing_ms / step_ms
        rec["serve_tracing_overhead"] = {
            "serve_step_ms": round(step_ms, 3),
            "tracing_ms_per_step": round(tracing_ms, 4),
            "ratio": round(ratio, 5),
            "slots": slots,
        }
        assert ratio <= 0.01, rec["serve_tracing_overhead"]

    check("tracing_serve_overhead", tracing_serve_overhead)

    # -- timeline JSONL schema round-trip ------------------------------
    def timeline_roundtrip():
        import os
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "tl.jsonl")
            tl = obs.StepTimeline(sinks=[obs.JsonlSink(path)],
                                  lane="train")
            want = []
            for i in range(5):
                want.append(tl.record(
                    step=i, host_ms=1.5 * i, stall_ms=0.25,
                    grad_norm=0.5, loss_scale=2.0 ** 10,
                    guard_skips=0, compile_events=0,
                    comm_bytes=1024, note="schema"))
            tl.close()
            got = obs.read_jsonl(path)
            assert got == want, (got, want)
            for r in got:
                assert isinstance(r["ts"], float) and r["lane"] == \
                    "train" and isinstance(r["step"], int), r
            # numeric fields mirrored into registry histograms
            h = obs.registry().get("timeline.train.host_ms")
            assert h is not None and h.count >= 5

    check("timeline_jsonl_roundtrip", timeline_roundtrip)

    # -- Prometheus exposition format ----------------------------------
    def prometheus():
        import re

        sample = re.compile(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{quantile="[0-9.]+"\})? '
            r"[^ ]+$")
        values = re.compile(
            r"^(NaN|[+-]Inf|[-+]?[0-9.eE+-]+)$")

        def check_text(text):
            assert text.endswith("\n")
            lines = [ln for ln in text.splitlines() if ln]
            assert any(ln.startswith("# TYPE ") for ln in lines), \
                "no TYPE headers"
            for ln in lines:
                if ln.startswith("#"):
                    continue
                assert sample.match(ln), f"bad exposition line: {ln!r}"
                assert values.match(ln.split()[-1]), f"bad value: {ln!r}"
                assert " inf" not in ln and " nan" not in ln, ln
            return lines

        lines = check_text(obs.registry().expose())
        # the summary form carries quantiles + sum/count
        assert any('quantile="0.99"' in ln for ln in lines)
        assert any(ln.split()[0].endswith("_count") for ln in lines
                   if not ln.startswith("#"))
        # adversarial instruments (names that need sanitizing, values
        # that need the spec's non-finite tokens — ISSUE 13 satellite)
        # go on a PRIVATE registry: registration is permanent, and the
        # global scrape must not carry junk series after this lane
        g = obs.MetricsRegistry()
        g.counter("ok.counter").inc()
        g.histogram("ok.hist").observe(1.0)
        g.gauge("bad name!{} (weird)").set(float("inf"))
        g.gauge("0leading.digit").set(float("-inf"))
        g.gauge("nan.gauge").set(float("nan"))
        lines = check_text(g.expose())
        assert any(ln.split()[-1] == "+Inf" for ln in lines)
        assert any(ln.split()[-1] == "-Inf" for ln in lines)
        assert any(ln.split()[-1] == "NaN" for ln in lines)

    check("prometheus_exposition", prometheus)

    # strict mode never tripped on the clean portions of this lane
    summary = obs.retrace_summary()
    rec["retrace_summary"] = {
        "total_unexpected": summary["total_unexpected"],
        "strict": obs.strict_retrace(),
    }
    rec["check"] = ("pass" if not fails
                    else "FAIL: " + ", ".join(fails))
    return {"observability": rec}


if __name__ == "__main__":
    print(json.dumps(run_probe()))
