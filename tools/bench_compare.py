"""BENCH round-over-round regression gate (ISSUE 13 satellite).

Diffs two bench records per metric with per-metric-family tolerance
thresholds and emits a pass/regress verdict table. Handles both raw
``bench.py`` JSON results and the driver's ``BENCH_r*.json`` wrappers
({"n", "cmd", "rc", "tail", "parsed"} — the result JSON is recovered
from ``parsed`` or scraped out of the stdout ``tail``, which may be
truncated at the FRONT, so extraction looks for the last parseable
object).

Usage::

    python tools/bench_compare.py                  # two newest BENCH_r*
    python tools/bench_compare.py OLD.json NEW.json
    BENCH_COMPARE=1 python bench.py                # in-run gate: the
        # fresh result is compared against the newest BENCH_r*.json
        # and the verdict lands in the record ("bench_compare" key)

Exit code: 0 pass / 2 regress / 0 with status "no_data" when fewer
than two comparable records exist (a missing history must not fail a
fresh checkout).

Metric families and default tolerances (relative):

    tok_s      -5%   higher is better (tokens/s, images/s)
    mfu        -5%   higher is better
    goodput    -5%   higher is better (fraction)
    ttft      +25%   lower is better  (latency lanes are CPU-noisy)
    itl       +25%   lower is better
    stall     +100%  lower is better  (sub-ms noise; abs floor below)
    mem        +5%   lower is better  (compiled-step peak bytes —
                     growth fails the gate like a tok/s regression,
                     ISSUE 14; AOT buffer-assignment numbers are
                     deterministic, so 5% is generous)
    finite     ABSOLUTE: any finite_frac below 1.0 regresses — a
                     training run that produced even one non-finite
                     step is broken regardless of the previous round
                     (ISSUE 15)
    gradnorm   INFORMATIONAL ONLY: grad-norm drift rows render with an
                     "info" verdict and NEVER gate — norms legitimately
                     move with model/config/step-count changes
                     (ISSUE 15)
    spec_yield -5%   higher is better (speculative tokens-per-dispatch:
                     the structural yield of the spec step, gated as a
                     lower bound like a throughput metric — ISSUE 16)
    spec_accept INFORMATIONAL ONLY: accept rate is a property of the
                     draft/model pair and legitimately moves with
                     config changes (ISSUE 16)
    cold_start +30%  lower is better (trace+compile-or-deserialize to
                     first step/token, milliseconds — the persistent
                     AOT executable cache's headline metric, ISSUE 17;
                     250ms absolute floor absorbs toy-model jitter)

Latency/stall/mem metrics additionally carry an ABSOLUTE floor: when
both sides sit under it, the row is informational (sub-floor jitter
cannot regress the gate — for mem, toy-model selftest peaks of a few
MB must not gate while the flagship GB-scale peaks do).
"""
from __future__ import annotations

import glob
import json
import os
import re
import sys

__all__ = ["load_record", "extract_metrics", "compare",
           "compare_latest", "render_table", "DEFAULT_TOLERANCES"]

# family -> (relative tolerance, higher_is_better, absolute floor)
DEFAULT_TOLERANCES = {
    "tok_s":   (0.05, True, 0.0),
    "mfu":     (0.05, True, 0.0),
    "goodput": (0.05, True, 0.0),
    "ttft":    (0.25, False, 2e-3),     # seconds
    "itl":     (0.25, False, 1e-3),     # seconds
    "stall":   (1.00, False, 0.5),      # milliseconds
    "mem":     (0.05, False, 32 * 1024 * 1024),   # bytes (peak)
    # numerics family (ISSUE 15): finite_frac is an ABSOLUTE gate
    # (must stay 1.0), grad-norm drift is informational-only — both
    # special-cased in compare(), the tuples here only register the
    # families
    "finite":  (0.0, True, 0.0),
    "gradnorm": (0.0, True, 0.0),
    # speculative decoding (ISSUE 16): tokens-per-dispatch is the
    # structural yield of the spec step (deterministic at a fixed
    # draft/model pair) — a drop means accepted spans shrank, gate it
    # like a throughput metric. Accept rate is a property of the
    # draft/model PAIR, legitimately moves with config — report only.
    "spec_yield": (0.05, True, 0.0),
    "spec_accept": (0.0, True, 0.0),
    # cold start (ISSUE 17): compile-or-deserialize to first step, ms.
    # Wide relative band (compile wall is scheduler-noisy) + an
    # absolute floor so toy selftest programs never gate
    "cold_start": (0.30, False, 250.0),
    # serving fleet (ISSUE 18): aggregate multi-replica tok/s gates
    # like any throughput; fleet TTFT percentiles are merged-sample
    # (union of replica windows), latency band + floor as ttft
    "fleet_tok_s": (0.05, True, 0.0),
    "fleet_ttft": (0.25, False, 2e-3),   # seconds
    # KV capacity (ISSUE 20): resident-slots-at-equal-HBM ratios from
    # pool_stats' packed-bytes math (int8 vs bf16, int4 vs int8/bf16).
    # Deterministic geometry arithmetic at a fixed config — any drop
    # means the packing itself regressed, so gate tight, higher-better
    "kv_capacity": (0.05, True, 0.0),
    # self-healing fleet (ISSUE 19): mean-time-to-recovery in ms
    # (replica death -> first post-death token; trainer crash ->
    # first post-restore step). Wide band + absolute floor: recovery
    # wall on the CPU selftest is re-prefill/compile dominated and
    # scheduler-noisy, but a multi-x blowup past the floor means the
    # re-dispatch path itself regressed
    "mttr":    (0.50, False, 250.0),     # milliseconds
}

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def bench_records(root="."):
    """(round, path) for every BENCH_r*.json under root, ascending."""
    out = []
    for p in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = _ROUND_RE.search(os.path.basename(p))
        if m:
            out.append((int(m.group(1)), p))
    return sorted(out)


def _scrape_tail(tail):
    """Last parseable JSON object in a (possibly front-truncated)
    stdout tail."""
    dec = json.JSONDecoder()
    best, best_len = None, 0
    for m in re.finditer(r'\{"', tail):
        try:
            obj, end = dec.raw_decode(tail[m.start():])
        except json.JSONDecodeError:
            continue
        if not isinstance(obj, dict) or not (
                "metric" in obj or "value" in obj or "selftest" in obj):
            continue
        # the OUTERMOST result is wanted, not a nested {"metric": ...}
        # block — prefer the longest parsed span
        if end > best_len:
            best, best_len = obj, end
    return best


def load_record(path):
    """The bench RESULT dict from either a raw bench.py JSON line or a
    driver wrapper; None when nothing parseable is inside."""
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(rec, dict):
        return None
    if "metric" in rec or "selftest" in rec:
        return rec
    parsed = rec.get("parsed")
    if isinstance(parsed, dict):
        return parsed
    tail = rec.get("tail")
    if isinstance(tail, str):
        return _scrape_tail(tail)
    return None


def _family(key):
    k = key.lower()
    # fleet rollups match BEFORE the generic tok_s/ttft families so
    # the multi-replica lanes carry their own tolerance rows
    if "fleet_tok_s" in k:
        return "fleet_tok_s"
    if "fleet_ttft" in k:
        return "fleet_ttft"
    if "mttr" in k:
        return "mttr"
    if "finite_frac" in k:
        return "finite"
    if "grad_norm" in k:
        return "gradnorm"
    if "slots_ratio" in k or "kv_capacity" in k:
        return "kv_capacity"
    if "tokens_per_dispatch" in k:
        return "spec_yield"
    if "accept_rate" in k:
        return "spec_accept"
    if "peak_bytes" in k:
        return "mem"
    if ("cold_start" in k or "warmup_ms" in k
            or "first_train_step_ms" in k or "first_decode_ms" in k):
        return "cold_start"
    if "goodput_frac" in k:
        return "goodput"
    if "ttft" in k:
        return "ttft"
    if "itl" in k:
        return "itl"
    if "stall" in k:
        return "stall"
    if k.endswith("mfu") or "mfu" in k.rsplit(".", 1)[-1]:
        return "mfu"
    if ("tok_s" in k or "tokens_per_sec" in k or "images_per_sec" in k
            or k.endswith("_s_chip") or "speedup" in k):
        return "tok_s"
    return None


_SKIP_KEYS = {"config", "provenance", "vs_baseline", "vs_round3",
              "timeline", "recorded_at", "compute_path_hash", "cmd",
              "tail", "window_note", "bench_compare", "error",
              "budget_s", "elapsed_s",
              # pinned historical constant (identical every round —
              # comparing it only pads the table)
              "r4_unrolled_reference"}


def extract_metrics(rec) -> dict:
    """Flatten a bench result into {dotted.path: float} for every
    comparable metric (tok/s, MFU, TTFT/ITL, stall, goodput). The
    top-level {"metric", "value"} pair keys as the metric's own name so
    rounds with different primaries still line up per model."""
    out = {}

    def walk(node, path):
        if isinstance(node, dict):
            name = node.get("metric")
            val = node.get("value")
            if isinstance(name, str) and isinstance(val, (int, float)) \
                    and not isinstance(val, bool):
                # first wins: a nested attachment repeating the name
                # (an embedded reference block) must not overwrite the
                # outer live value
                out.setdefault(name, float(val))
                if isinstance(node.get("mfu"), (int, float)):
                    out.setdefault(f"{name}.mfu", float(node["mfu"]))
            for k, v in node.items():
                if k in _SKIP_KEYS or k in ("metric", "value", "mfu"):
                    continue
                walk(v, f"{path}.{k}" if path else k)
            return
        if isinstance(node, (list, tuple)):
            return                      # no positional metrics
        if isinstance(node, bool) or not isinstance(node, (int, float)):
            return
        if _family(path.rsplit(".", 1)[-1]) is not None:
            f = float(node)
            if f == f and abs(f) != float("inf"):
                out.setdefault(path, f)

    walk(rec, "")
    return out


def compare(old_rec, new_rec, tolerances=None) -> dict:
    """Per-metric verdicts between two bench results. A row regresses
    when it moves beyond its family tolerance in the BAD direction
    (and, for latency families, above the absolute floor)."""
    tol = dict(DEFAULT_TOLERANCES)
    tol.update(tolerances or {})
    old_m = extract_metrics(old_rec or {})
    new_m = extract_metrics(new_rec or {})
    rows = []
    for key in sorted(set(old_m) & set(new_m)):
        fam = _family(key.rsplit(".", 1)[-1]) or _family(key)
        if fam is None or fam not in tol:
            continue
        rel_tol, higher_better, floor = tol[fam]
        old, new = old_m[key], new_m[key]
        # a zero baseline has no relative delta — delta_pct must stay
        # JSON-clean (json.dumps would emit the non-spec `Infinity`
        # and corrupt the whole BENCH record for strict parsers)
        delta = (new - old) / abs(old) if old else None
        verdict = "ok"
        if fam == "finite":
            # absolute: 1.0 means every step was finite; anything less
            # regresses no matter what the previous round recorded
            verdict = ("regress" if new < 1.0
                       else ("improved" if old < 1.0 else "ok"))
        elif fam in ("gradnorm", "spec_accept"):
            # drift is reported, never gated (accept rate moves with
            # the draft/model pair, grad norms with model/config)
            verdict = "info"
        elif max(abs(old), abs(new)) < floor:
            verdict = "sub_floor"
        elif old == 0:
            # relative tolerances are meaningless against 0 — report,
            # never regress, on a freshly-appearing metric value
            verdict = "new_baseline" if new != 0 else "ok"
        elif higher_better:
            if new < old * (1 - rel_tol):
                verdict = "regress"
            elif new > old * (1 + rel_tol):
                verdict = "improved"
        else:
            if new > old * (1 + rel_tol):
                verdict = "regress"
            elif new < old * (1 - rel_tol):
                verdict = "improved"
        rows.append({"metric": key, "family": fam, "old": old,
                     "new": new,
                     "delta_pct": (None if delta is None
                                   else round(delta * 100, 2)),
                     "tol_pct": round(rel_tol * 100, 1),
                     "verdict": verdict})
    # an ABSOLUTE gate must not degrade to "pass" by vanishing: a
    # finite_frac the baseline recorded but the candidate lacks (the
    # monitor errored, or never folded a step) is itself a regression
    # — exactly the broken-monitor case the gate exists to catch.
    # Other families legitimately come and go with lane configs.
    for key in sorted(set(old_m) - set(new_m)):
        fam = _family(key.rsplit(".", 1)[-1]) or _family(key)
        if fam == "finite":
            rows.append({"metric": key, "family": fam,
                         "old": old_m[key], "new": None,
                         "delta_pct": None, "tol_pct": 0.0,
                         "verdict": "regress",
                         "note": "absolute gate metric missing from "
                                 "candidate record"})
    regressions = [r["metric"] for r in rows if r["verdict"] == "regress"]
    status = ("no_data" if not rows
              else "regress" if regressions else "pass")
    return {"status": status, "compared": len(rows),
            "regressions": regressions, "rows": rows}


def render_table(result) -> str:
    lines = [f"{'metric':<58}{'old':>12}{'new':>12}{'Δ%':>8}"
             f"{'tol%':>6}  verdict"]
    for r in result["rows"]:
        dp = ("     —" if r["delta_pct"] is None
              else f"{r['delta_pct']:>8.2f}")
        new = ("           —" if r["new"] is None
               else f"{r['new']:>12.4g}")
        lines.append(
            f"{r['metric'][:58]:<58}{r['old']:>12.4g}{new}"
            f"{dp}{r['tol_pct']:>6.1f}  "
            f"{r['verdict']}")
    lines.append(f"status: {result['status']} "
                 f"({result['compared']} metrics compared"
                 + (f", regressed: {', '.join(result['regressions'])}"
                    if result["regressions"] else "") + ")")
    return "\n".join(lines)


def compare_latest(root=".", current=None, tolerances=None) -> dict:
    """Gate entry: compare ``current`` (an in-flight bench result)
    against the newest BENCH_r*.json — or, with no ``current``, the two
    newest records against each other."""
    recs = bench_records(root)
    if current is not None:
        if not recs:
            return {"status": "no_data", "compared": 0,
                    "regressions": [], "rows": [],
                    "note": "no BENCH_r*.json history to compare against"}
        n, path = recs[-1]
        base = load_record(path)
        res = compare(base, current, tolerances=tolerances)
        res["baseline"] = os.path.basename(path)
        return res
    if len(recs) < 2:
        return {"status": "no_data", "compared": 0, "regressions": [],
                "rows": [], "note": "need two BENCH_r*.json records"}
    (_, old_p), (_, new_p) = recs[-2], recs[-1]
    res = compare(load_record(old_p), load_record(new_p),
                  tolerances=tolerances)
    res["baseline"] = os.path.basename(old_p)
    res["candidate"] = os.path.basename(new_p)
    return res


def main(argv):
    if len(argv) == 2:
        res = compare(load_record(argv[0]), load_record(argv[1]))
        res["baseline"], res["candidate"] = argv
    elif len(argv) == 0:
        res = compare_latest(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))) or ".")
    else:
        print(__doc__)
        return 1
    print(render_table(res), file=sys.stderr)
    print(json.dumps(res))
    return 2 if res["status"] == "regress" else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
