"""Loss functionals (python/paddle/nn/functional/loss.py parity;
reference kernels cross_entropy (softmax_with_cross_entropy), bce, mse...).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...ops._dispatch import unary, binary, nary, ensure_tensor


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    """softmax_with_cross_entropy parity. Computed in fp32 via log_softmax
    (numerically-stable fused form — XLA fuses the exp/sum/sub chain)."""
    input = ensure_tensor(input)
    label = ensure_tensor(label)

    def f(logits, lbl, *maybe_w):
        x32 = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(x32, axis=axis) if use_softmax else jnp.log(jnp.maximum(x32, 1e-30))
        if soft_label or (lbl.ndim == logits.ndim and lbl.shape[axis] == logits.shape[axis] and jnp.issubdtype(lbl.dtype, jnp.floating)):
            soft = lbl.astype(jnp.float32)
            if label_smoothing > 0:
                k = logits.shape[axis]
                soft = soft * (1 - label_smoothing) + label_smoothing / k
            loss = -jnp.sum(soft * logp, axis=axis)
        else:
            idx = lbl.astype(jnp.int32)
            if idx.ndim == logits.ndim:
                idx = jnp.squeeze(idx, axis=axis)
            k = logits.shape[axis]
            safe_idx = jnp.where(idx == ignore_index, 0, idx)
            picked = jnp.take_along_axis(
                jnp.moveaxis(logp, axis, -1),
                safe_idx[..., None],
                axis=-1,
            )[..., 0]
            if label_smoothing > 0:
                smooth_term = jnp.mean(logp, axis=axis)
                picked = (1 - label_smoothing) * picked + label_smoothing * smooth_term
            loss = -picked
            valid = idx != ignore_index
            loss = jnp.where(valid, loss, 0.0)
            if maybe_w:
                w = maybe_w[0].astype(jnp.float32)[safe_idx]
                loss = loss * jnp.where(valid, w, 0.0)
                if reduction == "mean":
                    denom = jnp.sum(jnp.where(valid, w, 0.0))
                    return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
            if reduction == "mean":
                denom = jnp.sum(valid.astype(jnp.float32))
                return jnp.sum(loss) / jnp.maximum(denom, 1.0)
        return _reduce(loss, reduction)

    inputs = [input, label]
    if weight is not None:
        inputs.append(ensure_tensor(weight))
    return nary(f, inputs, "cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    loss = loss.unsqueeze(axis)
    if return_softmax:
        from .activation import softmax

        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    return _nll(input, label, weight, ignore_index, reduction)


def _nll(input, label, weight, ignore_index, reduction):
    input = ensure_tensor(input)
    label = ensure_tensor(label)

    def f(logp, lbl, *maybe_w):
        idx = lbl.astype(jnp.int32)
        safe_idx = jnp.where(idx == ignore_index, 0, idx)
        picked = jnp.take_along_axis(logp, safe_idx[..., None], axis=-1)[..., 0]
        loss = -picked
        valid = idx != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if maybe_w:
            w = maybe_w[0][safe_idx]
            loss = loss * jnp.where(valid, w, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, w, 0.0)), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
        return _reduce(loss, reduction)

    inputs = [input, label]
    if weight is not None:
        inputs.append(ensure_tensor(weight))
    return nary(f, inputs, "nll_loss")


def mse_loss(input, label, reduction="mean", name=None):
    return binary(lambda a, b: _reduce(jnp.square(a - b), reduction),
                  ensure_tensor(input), ensure_tensor(label), "mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return binary(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                  ensure_tensor(input), ensure_tensor(label), "l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return binary(f, ensure_tensor(input), ensure_tensor(label), "smooth_l1_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, y, *maybe_w):
        p32 = jnp.clip(p.astype(jnp.float32), 1e-12, 1 - 1e-7)
        loss = -(y * jnp.log(p32) + (1 - y) * jnp.log(1 - p32))
        if maybe_w:
            loss = loss * maybe_w[0]
        return _reduce(loss, reduction)

    inputs = [ensure_tensor(input), ensure_tensor(label)]
    if weight is not None:
        inputs.append(ensure_tensor(weight))
    return nary(f, inputs, "bce")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def f(z, y, *rest):
        z32 = z.astype(jnp.float32)
        y32 = y.astype(jnp.float32)
        i = 0
        pw = None
        w = None
        if weight is not None:
            w = rest[i]; i += 1
        if pos_weight is not None:
            pw = rest[i]; i += 1
        # log(1+exp(-|z|)) stable form
        max_val = jnp.maximum(-z32, 0)
        if pw is not None:
            log_w = (pw - 1) * y32 + 1
            loss = (1 - y32) * z32 + log_w * (jnp.log(jnp.exp(-max_val) + jnp.exp(-z32 - max_val)) + max_val)
        else:
            loss = (1 - y32) * z32 + max_val + jnp.log(jnp.exp(-max_val) + jnp.exp(-z32 - max_val))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    inputs = [ensure_tensor(logit), ensure_tensor(label)]
    if weight is not None:
        inputs.append(ensure_tensor(weight))
    if pos_weight is not None:
        inputs.append(ensure_tensor(pos_weight))
    return nary(f, inputs, "bce_with_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def f(lp, y):
        if log_target:
            loss = jnp.exp(y) * (y - lp)
        else:
            loss = y * (jnp.log(jnp.maximum(y, 1e-12)) - lp)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)

    return binary(f, ensure_tensor(input), ensure_tensor(label), "kl_div")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def f(x, y):
        loss = jnp.where(y == 1, x, jnp.maximum(0.0, margin - x))
        return _reduce(loss, reduction)

    return binary(f, ensure_tensor(input), ensure_tensor(label), "hinge_embedding")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return nary(
        lambda x1, x2, y: _reduce(jnp.maximum(0.0, -y * (x1 - x2) + margin), reduction),
        [ensure_tensor(input), ensure_tensor(other), ensure_tensor(label)],
        "margin_ranking",
    )


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / (
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12
        )
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return nary(f, [ensure_tensor(input1), ensure_tensor(input2), ensure_tensor(label)],
                "cosine_embedding")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2, eps=1e-6,
                        swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        dp = jnp.power(jnp.sum(jnp.power(jnp.abs(a - pos) + eps, p), axis=-1), 1 / p)
        dn = jnp.power(jnp.sum(jnp.power(jnp.abs(a - neg) + eps, p), axis=-1), 1 / p)
        if swap:
            dn2 = jnp.power(jnp.sum(jnp.power(jnp.abs(pos - neg) + eps, p), axis=-1), 1 / p)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return nary(f, [ensure_tensor(input), ensure_tensor(positive), ensure_tensor(negative)],
                "triplet_margin")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def f(z, y, *maybe_norm):
        p = jax.nn.sigmoid(z.astype(jnp.float32))
        ce = binary_ce_logits_raw(z.astype(jnp.float32), y.astype(jnp.float32))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if maybe_norm:
            loss = loss / maybe_norm[0]
        return _reduce(loss, reduction)

    inputs = [ensure_tensor(logit), ensure_tensor(label)]
    if normalizer is not None:
        inputs.append(ensure_tensor(normalizer))
    return nary(f, inputs, "sigmoid_focal")


def binary_ce_logits_raw(z, y):
    max_val = jnp.maximum(-z, 0)
    return (1 - y) * z + max_val + jnp.log(jnp.exp(-max_val) + jnp.exp(-z - max_val))


def square_error_cost(input, label):
    return binary(lambda a, b: jnp.square(a - b), ensure_tensor(input), ensure_tensor(label),
                  "square_error_cost")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    raise NotImplementedError("ctc_loss lands with the audio op pack")
