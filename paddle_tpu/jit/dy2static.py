"""dy2static — AST control-flow conversion for `to_static`.

Reference parity: python/paddle/jit/dy2static/ (entry jit/api.py:195; the
control-flow converters live in convert_operators.py). The reference
rewrites Python `if`/`while`/`for` whose conditions depend on tensor
values into `paddle.static.nn.cond`/`while_loop` ops; here the same AST
rewrite targets `jax.lax.cond`/`jax.lax.while_loop`, so data-dependent
control flow compiles into the XLA program instead of failing in the
`jax.jit` trace (TPU-first: compiler-friendly control flow, no Python
branching inside jit).

Shape of the rewrite (mirroring dy2static's convert_ifelse contract):

    if cond:            def _t(ctx):                # true branch
        x = x + 1           x, = ctx
    else:                   x = x + 1
        x = x - 1           return (x,)
                        def _f(ctx): ...            # false branch
                        (x,) = _jst.convert_ifelse(cond, _t, _f, (x,))

The carried names are the union of names assigned in either branch (the
reference computes the same "modified vars" set). `while` carries the
names assigned in the body plus those read by the condition; `for i in
range(...)` lowers to the while form. Conditions' `and`/`or`/`not`
convert to lazy logical helpers (convert_logical_and/or/not parity).

Conversion limits (converted statements containing these stay plain
Python, which still traces fine for non-tensor conditions; a tensor
condition then falls back to EAGER execution with a warning — the
documented dy2static fallback contract):
  * return/break/continue/yield inside a converted branch or loop body
  * names assigned in only one branch and unbound before the `if`
"""
from __future__ import annotations

import ast
import functools
import sys
import inspect
import textwrap
import warnings

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor

# reserved name injected into the target module globals for the rewritten
# code to reach the runtime converters (collision-safe, dunder-style)
_JST = "__paddle_tpu_jst__"

__all__ = ["convert_function", "convert_ifelse", "convert_while",
           "logical_and", "logical_or", "logical_not", "ConversionError"]


class ConversionError(RuntimeError):
    pass


class Unsupported(RuntimeError):
    """Raised mid-trace when a converted statement cannot be staged (e.g.
    a name assigned in only one branch and unbound before the `if`);
    StaticFunction catches it and falls back to eager."""


class _Undef:
    """UndefinedVar parity (reference dy2static/utils.py): placeholder for
    carried names with no binding before the converted statement. Any use
    raises UnboundLocalError (python would raise NameError at the read
    site; the converted form binds the name to this sentinel instead, so
    the sentinel must be loud rather than silently truthy)."""

    __slots__ = ()

    def __repr__(self):
        return "<undefined>"

    def _raise(self, *a, **k):
        raise UnboundLocalError(
            "variable referenced before assignment (it was assigned in "
            "only one branch of converted control flow — dy2static "
            "UndefinedVar)")

    __bool__ = __len__ = __iter__ = __index__ = __int__ = __float__ = \
        __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = \
        __truediv__ = __rtruediv__ = __lt__ = __le__ = __gt__ = __ge__ = \
        __call__ = __getitem__ = _raise


_UNDEF = _Undef()

# aliases reachable from generated code via the injected _JST module ref
UNDEF = _UNDEF


def ret_value(v):
    """Final-return helper for flag-lowered functions: UNDEF means no
    valued `return` ever executed (python returns None)."""
    return None if v is _UNDEF else v


def _load(fn):
    """Load a carried name tolerating unboundness (generated code passes
    `_jst._load(lambda: name)`)."""
    try:
        return fn()
    except (NameError, UnboundLocalError):
        return _UNDEF


# ---------------------------------------------------------------------------
# runtime converters (the `_jst` namespace the rewritten code calls)
# ---------------------------------------------------------------------------

def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _is_tensorish(x):
    return isinstance(x, (Tensor, jax.Array)) or isinstance(
        x, jax.core.Tracer)


def _ctx_to_jax(ctx):
    """Carried-state pytree → jax arrays (python scalars become weakly
    typed jax scalars so the carry has stable avals across iterations)."""
    out = []
    for v in ctx:
        v = _unwrap(v)
        if isinstance(v, (bool, int, float)):
            v = jnp.asarray(v)
        out.append(v)
    return tuple(out)


def _ctx_wrap(ctx):
    """jax arrays → Tensors for the branch/body code (which runs paddle
    ops); non-arrays pass through."""
    return tuple(Tensor._wrap(v) if isinstance(v, (jax.Array,))
                 or isinstance(v, jax.core.Tracer) else v for v in ctx)


def _fill_ph_slots(ctx, ph, probe_fns):
    """Zero-fill UNDEF carried slots in `ph` (the flag-lowering's
    return-value slots) from the aval another branch/body produces for
    them. Sound because the generated gates guarantee such a slot is only
    consumed when its flag is set — i.e. on the path that assigned it."""
    undef_ph = [i for i in ph if ctx[i] is _UNDEF]
    if not undef_ph:
        return ctx
    defined = [i for i, v in enumerate(ctx) if v is not _UNDEF]
    init = _ctx_to_jax([ctx[i] for i in defined])
    fills = {}
    for fn in probe_fns:
        rec = {}

        def probe(c, fn=fn, rec=rec):
            full = list(ctx)
            w = _ctx_wrap(c)
            for j, i in enumerate(defined):
                full[i] = w[j]
            out = fn(tuple(full))
            rec["undef"] = [v is _UNDEF for v in out]
            return _ctx_to_jax([jnp.zeros(()) if v is _UNDEF else v
                                for v in out])

        shp = jax.eval_shape(probe, init)
        for i in undef_ph:
            if i not in fills and not rec["undef"][i]:
                fills[i] = jnp.zeros(shp[i].shape, shp[i].dtype)
    ctx = list(ctx)
    for i in undef_ph:
        # never assigned by any branch: a scalar placeholder keeps the
        # carry total; the gates make it unreadable
        ctx[i] = fills.get(i, jnp.zeros(()))
    return tuple(ctx)


def convert_ifelse(pred, true_fn, false_fn, ctx, ph=()):
    """Reference convert_operators.convert_ifelse: tensor predicate →
    lax.cond over the carried names; python predicate → plain branch.

    Carried slots holding _UNDEF (no binding before the `if`) are fed to
    the branch code as-is; both branches must then assign them — a branch
    returning _UNDEF for such a slot cannot be staged (Unsupported),
    EXCEPT slots in `ph` (flag-lowered return values), which zero-fill
    from the assigning branch's aval (_fill_ph_slots)."""
    p = _unwrap(pred)
    if isinstance(p, jax.core.Tracer):
        if ph:
            ctx = _fill_ph_slots(ctx, ph, (true_fn, false_fn))
        defined = [i for i, v in enumerate(ctx) if v is not _UNDEF]
        init = _ctx_to_jax([ctx[i] for i in defined])

        def _run(branch_fn, c):
            full = list(ctx)
            w = _ctx_wrap(c)
            for j, i in enumerate(defined):
                full[i] = w[j]
            out = branch_fn(tuple(full))
            for v in out:
                if v is _UNDEF:
                    raise Unsupported(
                        "a name assigned in only one branch of a "
                        "tensor-dependent `if` has no binding before it")
            return _ctx_to_jax(out)

        out = jax.lax.cond(jnp.reshape(p, ()).astype(bool),
                           lambda c: _run(true_fn, c),
                           lambda c: _run(false_fn, c), init)
        return _ctx_wrap(out)
    if isinstance(p, jax.Array):
        p = bool(p)  # concrete tensor: eager semantics
    return true_fn(ctx) if p else false_fn(ctx)


def convert_while(cond_fn, body_fn, ctx, ph=()):
    """Reference convert_operators.convert_while_loop: tensor condition →
    lax.while_loop; python condition → plain loop."""
    first = cond_fn(ctx)
    p = _unwrap(first)
    if isinstance(p, jax.core.Tracer):
        if ph:
            ctx = _fill_ph_slots(ctx, ph, (body_fn,))
        if any(v is _UNDEF for v in ctx):
            raise Unsupported(
                "a name assigned inside a tensor-dependent `while` has no "
                "binding before the loop (zero-iteration value unknown)")
        init = _ctx_to_jax(ctx)

        def _cond(c):
            return jnp.reshape(_unwrap(cond_fn(_ctx_wrap(c))), ()).astype(
                bool)

        def _body(c):
            return _ctx_to_jax(body_fn(_ctx_wrap(c)))

        # stabilize the carry: one body pass may promote dtypes (e.g.
        # python-int counter -> weak i32 vs strong i64); while_loop needs
        # identical avals, so seed with the body's output structure
        stable = jax.eval_shape(_body, init)
        init = tuple(jnp.asarray(v, dtype=s.dtype)
                     for v, s in zip(init, stable))
        out = jax.lax.while_loop(_cond, _body, init)
        return _ctx_wrap(out)
    while bool(p):
        ctx = body_fn(ctx)
        p = _unwrap(cond_fn(ctx))
    return ctx


def logical_and(lhs_fn, rhs_fn):
    """Short-circuit-preserving `and` (convert_logical_and parity)."""
    lhs = lhs_fn()
    l = _unwrap(lhs)
    if not (isinstance(l, jax.core.Tracer) or isinstance(l, jax.Array)):
        return lhs and rhs_fn()
    r = _unwrap(rhs_fn())
    return Tensor._wrap(jnp.logical_and(jnp.asarray(l, bool),
                                        jnp.asarray(r, bool)))


def logical_or(lhs_fn, rhs_fn):
    lhs = lhs_fn()
    l = _unwrap(lhs)
    if not (isinstance(l, jax.core.Tracer) or isinstance(l, jax.Array)):
        return lhs or rhs_fn()
    r = _unwrap(rhs_fn())
    return Tensor._wrap(jnp.logical_or(jnp.asarray(l, bool),
                                       jnp.asarray(r, bool)))


def logical_not(x):
    v = _unwrap(x)
    if isinstance(v, jax.core.Tracer) or isinstance(v, jax.Array):
        return Tensor._wrap(jnp.logical_not(jnp.asarray(v, bool)))
    return not x


# ---------------------------------------------------------------------------
# AST pass
# ---------------------------------------------------------------------------

_BLOCKERS = (ast.Return, ast.Break, ast.Continue, ast.Yield, ast.YieldFrom,
             ast.Global, ast.Nonlocal)


def _has_blocker(nodes):
    def check(n):
        if isinstance(n, _BLOCKERS):
            return True
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return False    # own scope: a return/yield there is local
        return any(check(c) for c in ast.iter_child_nodes(n))

    return any(check(n) for n in nodes)


class _AssignedNames(ast.NodeVisitor):
    """Names (re)bound by a statement list — the carried-state set (the
    reference's "modified vars in the block" analysis)."""

    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.names.add(node.id)

    def visit_FunctionDef(self, node):
        self.names.add(node.name)   # binds the name; don't descend

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    @classmethod
    def of(cls, nodes):
        v = cls()
        for n in nodes:
            v.visit(n)
        return v.names


class _ReadNames(ast.NodeVisitor):
    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.names.add(node.id)

    @classmethod
    def of(cls, node):
        v = cls()
        v.visit(node)
        return v.names


class _CondExprTransformer(ast.NodeTransformer):
    """Inside converted conditions only: and/or/not → lazy helpers."""

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = ("logical_and" if isinstance(node.op, ast.And)
              else "logical_or")
        expr = node.values[0]
        for rhs in node.values[1:]:
            expr = ast.Call(
                func=ast.Attribute(value=ast.Name(_JST, ast.Load()),
                                   attr=fn, ctx=ast.Load()),
                args=[ast.Lambda(args=_EMPTY_ARGS, body=expr),
                      ast.Lambda(args=_EMPTY_ARGS, body=rhs)],
                keywords=[])
        return expr

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(
                func=ast.Attribute(value=ast.Name(_JST, ast.Load()),
                                   attr="logical_not", ctx=ast.Load()),
                args=[node.operand], keywords=[])
        return node


_EMPTY_ARGS = ast.arguments(posonlyargs=[], args=[], vararg=None,
                            kwonlyargs=[], kw_defaults=[], kwarg=None,
                            defaults=[])


def _ctx_tuple(names, ctx):
    return ast.Tuple([ast.Name(n, ctx()) for n in names], ctx())


def _ctx_load_guarded(names):
    """( _jst._load(lambda: a), _jst._load(lambda: b) ) — tolerates names
    with no binding before the converted statement (UndefinedVar parity)."""
    elems = [
        ast.Call(
            func=ast.Attribute(value=ast.Name(_JST, ast.Load()),
                               attr="_load", ctx=ast.Load()),
            args=[ast.Lambda(args=_EMPTY_ARGS,
                             body=ast.Name(n, ast.Load()))],
            keywords=[])
        for n in names]
    return ast.Tuple(elems, ast.Load())


# ---------------------------------------------------------------------------
# return/break/continue lowering (r5, VERDICT r4 next #6) — the flag-variable
# rewriting of the reference's return_transformer.py /
# break_continue_transformer.py, adapted to the carried-names design:
#
#   return X   ->  __d2sf_rv = X; __d2sf_ret = True     (+ block gating)
#   break      ->  __d2sf_brkN = True                   (+ loop-test and)
#   continue   ->  __d2sf_contN = True                  (+ body gating)
#
# Statements after a flag-setter in the same block are wrapped in
# `if not (flags...):` — after the main transformer runs, those gates
# become lax.cond when the flags are traced, which is exactly how an
# early return inside a tensor `if` stages. The return-value slot
# (__d2sf_rv) starts as the UNDEF sentinel; convert_ifelse/convert_while
# zero-fill it from the other branch's aval (the `ph` parameter) — sound
# because the gates guarantee it is only consumed when the flag is set.
#
# Eligibility (conservative): the function's LAST statement is a plain
# `return`, and returns/breaks/continues appear inside if/while/for
# bodies. Functions mixing valued returns with an implicit fall-off-None
# are left to the eager-fallback path (their two return structures can't
# stage into one program).
#
# Loop-var fidelity (ADVICE r5): a gated `for` still runs its iterator
# to completion, so the loop target would end at the LAST iterated value
# instead of the break-time value. Each stop-flagged `for` therefore
# snapshots its target(s) at the top of the gated body (__d2sf_lvN_*)
# and restores them after the loop — post-loop reads now match eager
# Python. The snapshot slots ride the same `ph` zero-fill as __d2sf_rv
# (they too are only MEANINGFULLY consumed on paths that assigned them;
# a zero-trip tensor loop reads back the zero-fill, where eager Python
# would raise NameError on an unbound loop var — loud either way for
# the python-loop path, which restores the UNDEF sentinel).
# ---------------------------------------------------------------------------

_RET, _RV = "__d2sf_ret", "__d2sf_rv"
_LV = "__d2sf_lv"


def _loop_target_names(target):
    """The plain Names a for-loop target binds, or None when the
    target is fancier (starred/attribute/subscript)."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, ast.Tuple) and all(
            isinstance(e, ast.Name) for e in target.elts):
        return [e.id for e in target.elts]
    return None


def _load_call(name):
    """`_jst._load(lambda: name)` — reads tolerating unboundness."""
    return ast.Call(
        func=ast.Attribute(value=ast.Name(_JST, ast.Load()),
                           attr="_load", ctx=ast.Load()),
        args=[ast.Lambda(args=_EMPTY_ARGS,
                         body=ast.Name(name, ast.Load()))],
        keywords=[])


def _assign(name, value_node):
    return ast.Assign(targets=[ast.Name(name, ast.Store())],
                      value=value_node)


def _not_flags(flags):
    """`not (f1 or f2 or ...)` — lowered lazily by _CondExprTransformer
    when the main pass converts the gate's `if`."""
    ors = ast.BoolOp(op=ast.Or(),
                     values=[ast.Name(f, ast.Load()) for f in flags]) \
        if len(flags) > 1 else ast.Name(flags[0], ast.Load())
    return ast.UnaryOp(op=ast.Not(), operand=ors)


class _FlagLower:
    """Bottom-up statement rewriter eliminating Return/Break/Continue in
    favor of carried flag variables (see section comment)."""

    def __init__(self):
        self.n = 0
        self.lowered = 0

    def run(self, fdef):
        body = fdef.body
        if not body or not isinstance(body[-1], ast.Return):
            return fdef
        if not self._has_lowerable(body):
            return fdef
        # bare `return` and valued `return` cannot mix: the bare path
        # would surface a zero-filled placeholder instead of None (r5
        # review repro). All-bare is fine (rv stays UNDEF -> None).
        has_val, has_bare = False, False
        for s in body:
            for sub in self._walk_own_scope(s):
                if isinstance(sub, ast.Return):
                    if sub.value is None:
                        has_bare = True
                    else:
                        has_val = True
        if has_val and has_bare:
            return fdef
        new, sets = self._block(body, loop=None)
        inits = [
            _assign(_RET, ast.Constant(False)),
            _assign(_RV, ast.Attribute(
                value=ast.Name(_JST, ast.Load()), attr="UNDEF",
                ctx=ast.Load())),
        ]
        tail = [ast.Return(ast.Call(
            func=ast.Attribute(value=ast.Name(_JST, ast.Load()),
                               attr="ret_value", ctx=ast.Load()),
            args=[ast.Name(_RV, ast.Load())], keywords=[]))]
        fdef.body = inits + new + tail
        return fdef

    @staticmethod
    def _walk_own_scope(node):
        """ast.walk that does not descend into nested function scopes."""
        stack = [node]
        while stack:
            n = stack.pop()
            yield n
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                stack.extend(ast.iter_child_nodes(n))

    def _has_lowerable(self, stmts):
        for s in stmts:
            for sub in self._walk_own_scope(s):
                if isinstance(sub, (ast.If, ast.While, ast.For)):
                    for inner in self._walk_own_scope(sub):
                        if isinstance(inner, (ast.Return, ast.Break,
                                              ast.Continue)):
                            return True
        return False

    def _block(self, stmts, loop):
        """Returns (new_stmts, flags-this-block-may-set)."""
        out = []
        acc = set()
        for idx, s in enumerate(stmts):
            lowered, sets = self._stmt(s, loop)
            out.extend(lowered)
            acc |= sets
            rest = stmts[idx + 1:]
            if sets and rest:
                rest_new, rest_sets = self._block(rest, loop)
                out.append(ast.If(test=_not_flags(sorted(sets)),
                                  body=rest_new, orelse=[]))
                return out, acc | rest_sets
        return out, acc

    def _stmt(self, s, loop):
        if isinstance(s, ast.Return):
            self.lowered += 1
            new = [_assign(_RET, ast.Constant(True))]
            if s.value is not None:
                new.insert(0, _assign(_RV, s.value))
            return new, {_RET}
        if isinstance(s, ast.Break):
            self.lowered += 1
            return [_assign(loop[0], ast.Constant(True))], {loop[0]}
        if isinstance(s, ast.Continue):
            self.lowered += 1
            return [_assign(loop[1], ast.Constant(True))], {loop[1]}
        if isinstance(s, ast.If):
            body, bsets = self._block(s.body, loop)
            orelse, osets = (self._block(s.orelse, loop)
                             if s.orelse else ([], set()))
            return [ast.If(test=s.test, body=body, orelse=orelse)], \
                bsets | osets
        if isinstance(s, (ast.While, ast.For)):
            return self._loop(s, loop)
        return [s], set()

    def _loop(self, s, outer_loop):
        self.n += 1
        n = self.n   # _block below recurses and bumps self.n for
        brk = f"__d2sf_brk{n}"      # nested loops: every name of THIS
        cont = f"__d2sf_cont{n}"    # loop must use the entry value
        body, sets = self._block(s.body, loop=(brk, cont))
        pre, inner_stop = [], []
        if brk in sets:
            pre.append(_assign(brk, ast.Constant(False)))
            inner_stop.append(brk)
        if cont in sets:
            # reset at each iteration top AND bind before the loop (the
            # carried-ctx capture needs a pre-loop binding)
            body = [_assign(cont, ast.Constant(False))] + body
            pre.append(_assign(cont, ast.Constant(False)))
        escape = {_RET} if _RET in sets else set()
        if escape:
            inner_stop.append(_RET)
        # loop `else` runs iff the loop was NOT broken out of: with break
        # lowered to a flag the loop always "completes", so the else
        # block becomes a flag-gated statement AFTER the loop — emitted
        # as plain statements (NOT as the loop's orelse: the main
        # transformer never descends into a loop's orelse, so a gate
        # left there would stay a python `if` over a traced flag — r5
        # review repro)
        post = []
        if s.orelse:
            orelse, osets = self._block(s.orelse, outer_loop)
            escape |= osets
            post = ([ast.If(test=_not_flags(sorted(inner_stop)),
                            body=orelse, orelse=[])]
                    if inner_stop else orelse)
        if isinstance(s, ast.While):
            test = s.test
            if inner_stop:
                test = ast.BoolOp(op=ast.And(), values=[
                    s.test, _not_flags(inner_stop)])
            return pre + [ast.While(test=test, body=body,
                                    orelse=[])] + post, escape
        # for: gate the body on the stop flags instead of cutting the
        # iteration; snapshot the loop target(s) at the top of the gated
        # body and restore after the loop, so post-loop reads see the
        # break-time value like eager Python (section comment)
        if inner_stop:
            names = _loop_target_names(s.target)
            if names:
                snaps = [f"{_LV}{n}_{j}" for j in range(len(names))]
                pre += [_assign(sn, _load_call(n))
                        for n, sn in zip(names, snaps)]
                body = [_assign(sn, ast.Name(n, ast.Load()))
                        for n, sn in zip(names, snaps)] + body
                post = [_assign(n, ast.Name(sn, ast.Load()))
                        for n, sn in zip(names, snaps)] + post
            body = [ast.If(test=_not_flags(inner_stop), body=body,
                           orelse=[])]
        return pre + [ast.For(target=s.target, iter=s.iter, body=body,
                              orelse=[])] + post, escape


def _make_branch_fn(name, carried, body):
    """def <name>(__ctx): (a, b) = __ctx; BODY; return (a, b)"""
    stmts = []
    if carried:
        stmts.append(ast.Assign(
            targets=[_ctx_tuple(carried, ast.Store)],
            value=ast.Name("__ctx", ast.Load())))
    stmts.extend(body)
    stmts.append(ast.Return(_ctx_tuple(carried, ast.Load)))
    args = ast.arguments(
        posonlyargs=[], args=[ast.arg("__ctx")], vararg=None,
        kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])
    return ast.FunctionDef(name=name, args=args, body=stmts,
                           decorator_list=[], returns=None)


class ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.count = 0
        self.converted = 0

    def _uid(self):
        self.count += 1
        return self.count

    # nested defs/lambdas keep their own semantics — only the decorated
    # function's own statements convert (decorate inner fns separately,
    # the reference's convert_call recursion is out of scope)
    def _visit_stmts(self, stmts):
        out = []
        for s in stmts:
            r = self.visit(s)
            out.extend(r if isinstance(r, list) else [r])
        return out

    def visit_FunctionDef(self, node):
        return node  # don't descend into nested defs

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return node

    def visit_If(self, node):
        body = self._visit_stmts(node.body)
        orelse = self._visit_stmts(node.orelse)
        if _has_blocker(body) or _has_blocker(orelse):
            return ast.If(test=node.test, body=body, orelse=orelse)
        carried = sorted(n for n in (_AssignedNames.of(body)
                                     | _AssignedNames.of(orelse))
                         if not n.startswith("__dy2st_"))
        i = self._uid()
        self.converted += 1
        test = _CondExprTransformer().visit(node.test)
        tname, fname = f"__dy2st_true_{i}", f"__dy2st_false_{i}"
        tfn = _make_branch_fn(tname, carried, body)
        ffn = _make_branch_fn(
            fname, carried, orelse or [ast.Pass()])
        ph = tuple(j for j, n in enumerate(carried)
                   if n == _RV or n.startswith(_LV))
        call = ast.Call(
            func=ast.Attribute(value=ast.Name(_JST, ast.Load()),
                               attr="convert_ifelse", ctx=ast.Load()),
            args=[test, ast.Name(tname, ast.Load()),
                  ast.Name(fname, ast.Load()),
                  _ctx_load_guarded(carried)],
            keywords=[ast.keyword(arg="ph", value=ast.Constant(ph))]
            if ph else [])
        assign = (ast.Assign(targets=[_ctx_tuple(carried, ast.Store)],
                             value=call)
                  if carried else ast.Expr(call))
        return [tfn, ffn, assign]

    def visit_While(self, node):
        body = self._visit_stmts(node.body)
        if _has_blocker(body) or node.orelse:
            return ast.While(test=node.test, body=body, orelse=node.orelse)
        # names the loop rebinds; everything else (loop-invariant reads in
        # the test or body) resolves through the generated closures
        carried = sorted(n for n in _AssignedNames.of(body)
                         if not n.startswith("__dy2st_"))
        i = self._uid()
        self.converted += 1
        test = _CondExprTransformer().visit(node.test)
        cname, bname = f"__dy2st_cond_{i}", f"__dy2st_body_{i}"
        cfn = _make_branch_fn(cname, carried, [])
        cfn.body[-1] = ast.Return(test)  # return COND instead of ctx
        bfn = _make_branch_fn(bname, carried, body)
        ph = tuple(j for j, n in enumerate(carried)
                   if n == _RV or n.startswith(_LV))
        call = ast.Call(
            func=ast.Attribute(value=ast.Name(_JST, ast.Load()),
                               attr="convert_while", ctx=ast.Load()),
            args=[ast.Name(cname, ast.Load()), ast.Name(bname, ast.Load()),
                  _ctx_load_guarded(carried)],
            keywords=[ast.keyword(arg="ph", value=ast.Constant(ph))]
            if ph else [])
        assign = (ast.Assign(targets=[_ctx_tuple(carried, ast.Store)],
                             value=call)
                  if carried else ast.Expr(call))
        return [cfn, bfn, assign]

    def visit_For(self, node):
        """`for i in range(...)` → while form (reference converts for-range
        through the same while machinery); other iterables untouched."""
        body = self._visit_stmts(node.body)
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and not node.iter.keywords
                    and 1 <= len(node.iter.args) <= 3
                    and isinstance(node.target, ast.Name))
        if (not is_range or _has_blocker(body) or node.orelse):
            return ast.For(target=node.target, iter=node.iter, body=body,
                           orelse=node.orelse)
        i = self._uid()
        a = node.iter.args
        start = a[0] if len(a) >= 2 else ast.Constant(0)
        stop = a[1] if len(a) >= 2 else a[0]
        stp = a[2] if len(a) == 3 else ast.Constant(1)
        var = node.target.id
        # an internal counter (__d2sv_ prefix: carried, unlike __dy2st_
        # helper defs) drives the iteration; the user's loop var is
        # assigned AT THE TOP of each iteration, so after the loop it
        # holds the last iterated value (python for-range semantics), not
        # `stop`. Known deviation: a zero-trip range leaves the var bound
        # to `start` where python leaves it unbound.
        it_n = f"__d2sv_it_{i}"
        stop_n, step_n = f"__dy2st_stop_{i}", f"__dy2st_step_{i}"
        pre = [
            ast.Assign(targets=[ast.Name(it_n, ast.Store())], value=start),
            ast.Assign(targets=[ast.Name(var, ast.Store())],
                       value=ast.Name(it_n, ast.Load())),
            ast.Assign(targets=[ast.Name(stop_n, ast.Store())], value=stop),
            ast.Assign(targets=[ast.Name(step_n, ast.Store())], value=stp),
        ]
        # while it < stop (step > 0 assumed for tensor bounds; negative
        # python steps still work via the python-loop path of
        # convert_while because the cond stays concrete then)
        test = ast.Compare(left=ast.Name(it_n, ast.Load()),
                           ops=[ast.Lt()],
                           comparators=[ast.Name(stop_n, ast.Load())])
        bind = ast.Assign(targets=[ast.Name(var, ast.Store())],
                          value=ast.Name(it_n, ast.Load()))
        incr = ast.AugAssign(target=ast.Name(it_n, ast.Store()),
                             op=ast.Add(),
                             value=ast.Name(step_n, ast.Load()))
        wnode = ast.While(test=test, body=[bind] + body + [incr], orelse=[])
        return pre + self.visit_While(wnode)


# ---------------------------------------------------------------------------
# function conversion
# ---------------------------------------------------------------------------

def convert_function(fn):
    """AST-convert `fn` (plain function or bound method). Returns
    (converted_callable, n_converted_statements); raises ConversionError
    when the source can't be rewritten (caller falls back to `fn`)."""
    import types as _types

    target = fn.__func__ if inspect.ismethod(fn) else fn
    if getattr(target, "_paddle_tpu_not_to_static", False):
        raise ConversionError("marked @not_to_static")
    try:
        src = textwrap.dedent(inspect.getsource(target))
    except (OSError, TypeError) as e:
        raise ConversionError(f"source unavailable: {e}") from e
    try:
        tree = ast.parse(src)
    except SyntaxError as e:  # e.g. decorated lambda fragments
        raise ConversionError(f"unparsable source: {e}") from e
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        raise ConversionError("not a function definition")
    fdef.decorator_list = []

    # flag-lowering pre-pass: return/break/continue in convertible
    # control flow become carried flags (see _FlagLower), so the main
    # transformer below no longer bails on them
    _FlagLower().run(fdef)
    tr = ControlFlowTransformer()
    fdef.body = tr._visit_stmts(fdef.body)
    if tr.converted == 0:
        return fn, 0  # nothing to do — keep the original (zero overhead)
    # mangle the def name so exec-ing into the LIVE module globals (needed
    # so later rebinding of module globals stays visible, matching eager
    # semantics) cannot clobber the original function's binding
    if getattr(sys.modules[__name__], "_code_level", None) is not None:
        ast.fix_missing_locations(fdef)
        stream = (sys.stdout
                  if getattr(sys.modules[__name__], "_code_to_stdout",
                             False) else sys.stderr)
        print(ast.unparse(fdef), file=stream)
    mangled = f"__dy2st_fn_{fdef.name}"
    fdef.name = mangled
    ast.fix_missing_locations(tree)

    has_closure = bool(target.__closure__)
    if has_closure:
        # re-exec'd code has no cells; snapshot free vars into a copy of
        # globals (documented deviation: later cell mutation is invisible)
        glb = dict(target.__globals__)
        for name, cell in zip(target.__code__.co_freevars,
                              target.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError as e:
                raise ConversionError(
                    f"unfilled closure cell {name!r}") from e
    else:
        glb = target.__globals__    # live view — rebinding stays visible
    from . import dy2static as _jst_mod

    glb[_JST] = _jst_mod
    code = compile(tree, filename=f"<dy2static {target.__name__}>",
                   mode="exec")
    exec(code, glb)
    conv = glb.pop(mangled)
    conv = functools.wraps(target)(conv)
    conv._dy2static_converted = tr.converted
    if inspect.ismethod(fn):
        conv = _types.MethodType(conv, fn.__self__)
    return conv, tr.converted
