"""Data parallelism + parallel env bring-up.

Reference parity: paddle.DataParallel (python/paddle/distributed/parallel.py:219)
with the EagerReducer bucketed-allreduce machinery
(paddle/fluid/distributed/collective/reducer.cc:484), and init_parallel_env
(parallel.py:978).

TPU-first: under GSPMD there is no reducer — the wrapper shards the batch
over the "dp" mesh axis and keeps params replicated; XLA's partitioner then
emits exactly one fused gradient all-reduce per backward (the hand-built
bucketing the reference needs is what the compiler does natively). The
no_sync/gradient-accumulation API is preserved.
"""
from __future__ import annotations

import contextlib

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework.tensor import Tensor
from ..framework.autograd import apply_op
from ..nn.layer.layers import Layer
from . import env
from .collective import Group
from .env import init_parallel_env  # noqa: F401  (public API re-export)


def _shard_batch(t: Tensor, mesh, axis_name: str) -> Tensor:
    if axis_name not in mesh.axis_names or mesh.shape[axis_name] == 1:
        return t
    if t.ndim == 0 or t.shape[0] % mesh.shape[axis_name] != 0:
        return t
    spec = P(axis_name, *([None] * (t.ndim - 1)))
    sharding = NamedSharding(mesh, spec)
    return apply_op(lambda x: jax.device_put(x, sharding), [t],
                    name="shard_batch")


class DataParallel(Layer):
    """Reference parallel.py:219. Batch-shards inputs on the dp axis; params
    stay replicated; gradient sync is XLA's partitioner (no reducer)."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group: Group = None):
        super().__init__()
        self._layers = layers
        self._group = group
        self._axis = (group.axes[0] if group is not None else "dp")
        self.find_unused_parameters = find_unused_parameters
        self._grad_need_sync = True
        # reference EagerReducer group size (MB): used by the EXPLICIT
        # sync path (apply_collective_grads over partial-tagged grads) —
        # one bucketed all-reduce per ~this many MB instead of one per
        # parameter. The GSPMD path needs no reducer at all (see class
        # docstring).
        self._comm_buffer_mb = int(comm_buffer_size)

    @property
    def group(self):
        return self._group

    def forward(self, *inputs, **kwargs):
        mesh = (self._group.mesh if self._group is not None
                else env.get_mesh())
        new_inputs = tuple(
            _shard_batch(x, mesh, self._axis) if isinstance(x, Tensor) else x
            for x in inputs
        )
        new_kwargs = {
            k: _shard_batch(v, mesh, self._axis) if isinstance(v, Tensor) else v
            for k, v in kwargs.items()
        }
        return self._layers(*new_inputs, **new_kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        """Gradient-accumulation guard (reference parallel.py no_sync).

        Under GSPMD the dp gradient reduction happens INSIDE each compiled
        backward (the loss reduces over the globally-sharded batch), so
        there is no standalone all-reduce this context could elide: jax's
        `unreduced` partial placement, which would express a deferred
        reduction, exists only in the Explicit-sharding mode, not the Auto
        mode this framework compiles with. Eagerly this guard is therefore
        semantically complete but saves no communication. For efficient
        accumulation use ``TrainStep(..., accumulate_steps=N)`` — the
        micro-batch loop compiles into ONE program where XLA schedules and
        fuses the reductions.
        """
        self._grad_need_sync = False
        try:
            yield
        finally:
            self._grad_need_sync = True

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        """Explicit gradient sync (reference parallel.py
        apply_collective_grads). Under GSPMD the dp reduction already
        happened inside the compiled backward, so only grads explicitly
        tagged partial (``grad._is_partial_grad = True`` by a per-rank
        producer, the hybrid_parallel_util contract) are reduced — as ONE
        bucketed all-reduce per `comm_buffer_size` MB (quantized payloads
        per FLAGS_comm_quant), not one collective per parameter."""
        if not self._grad_need_sync:
            return
        grads = [p.grad for p in self.parameters()
                 if getattr(p, "grad", None) is not None
                 and getattr(p.grad, "_is_partial_grad", False)]
        if not grads:
            return
        from ..utils import flags as _flags
        from .collective import new_group
        from .comm_bucketer import bucketed_all_reduce

        group = self._group
        if group is None:
            # reduce over the DP axis only — the world group on a hybrid
            # mesh (dp×mp, ...) would sum unrelated model-parallel slices
            mesh = env.get_mesh()
            if self._axis not in mesh.axis_names:
                raise ValueError(
                    f"DataParallel grad sync: axis {self._axis!r} not in "
                    f"mesh {mesh.axis_names}; pass group= explicitly — "
                    "falling back to the world group would sum across "
                    "non-data axes and corrupt gradients")
            group = new_group(axes=[self._axis], mesh=mesh)
        # FLAGS_comm_bucket_mb=0 is the documented per-parameter escape
        # hatch; bucket_mb=0 makes every tensor its own bucket
        mb = (self._comm_buffer_mb
              if int(_flags.get_flag("FLAGS_comm_bucket_mb") or 0) > 0
              else 0)
        bucketed_all_reduce(grads, group=group, bucket_mb=mb)
        for g in grads:
            g._is_partial_grad = False

    # delegate everything else to the wrapped layer
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)


def get_rank(group=None):
    return env.get_rank()


def get_world_size(group=None):
    return env.get_world_size()
