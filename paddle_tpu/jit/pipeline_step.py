"""Pipeline-parallel fused-scan train step: the ppermute ring schedule
over the layer-chunk scan structure, with the sharded weight update.

`ShardedFusedScanTrainStep` splits the GRADIENT/OPTIMIZER work over the
mesh; this step additionally splits the LAYERS. The model's layer chunks
(`layer_chunk` layers each, C chunks total) are round-robined over the
``pp`` mesh axis as VIRTUAL STAGES — chunk ``c`` lives on stage
``c % pp`` in ring pass ``c // pp`` — the VPP placement of
docs/pipeline_schedules.md, realized on the compiled ppermute ring of
`fleet/meta_parallel/spmd_pipeline.py`:

  forward:   microbatch the local (dp-shard) batch into M pieces; for
             each of the V = C/pp ring passes, run ``pp + M - 1`` scan
             ticks — every stage applies ITS chunk of the pass to the
             micro-batch it holds and ppermutes the activation to the
             next stage. Stage 0 injects fresh micro-batches and
             collects finished ones; warmup/steady/cooldown fall out of
             the ring (bubble fraction (pp-1)/(pp+M-1) per pass).
  head:      the collected hiddens re-assemble to the full local batch
             (one psum over pp) and the LM-head loss is the same
             masked-mean the dp-only step computes — so micro-batch
             accumulation is exact by construction: the gradient IS the
             gradient of the one global mean, the `TrainStep
             (accum_steps=k)` contract without a separate accumulator.
  backward:  jax AD of the ring — the reverse ring, 1F1B's backward —
             yields each rank's OWN chunks' grads ([V, K, ...] per
             leaf, 1/pp of the layers: the pipeline-parallel memory
             contract). Each chunk's bucket-packed grad then
             reduce-scatters over the flattened (dp, pp) axes exactly
             like the base step's in-scan scatter: the pp leg of the
             sum SELECTS the owner stage (others contribute zeros), the
             dp leg is the data-parallel reduction, and the optimizer
             shards stay 1/(dp·pp) flat buckets. The update scan,
             fused global-norm clip, and non-finite guard are inherited
             unchanged.

Per-rank loss/grads carry the uniform ×pp joint-vjp replication factor
(every pp rank computes the identical loss); the base step's
1/(dp·pp) normalization divides it back out — the same algebra the
dp×mp leg uses (see jit/sharded_scan.py).

Dropout (ISSUE 11 satellite): legal inside the ring via a
per-(micro, stage) PRNG offset scheme extending the base per-layer
formula — a tick computing chunk ``c`` (= layer ``c*K``) on the
micro-batch ``m`` that entered the ring ``stage`` ticks ago draws at

    offset = ((step*(L+1) + layer) * (dp*M) + (dp_rank*M + m)) * 8

i.e. the (dp_rank, micro) pair takes the rank slot of the base scheme
(micro-batches are disjoint row sets of the local batch, exactly like
dp shards are of the global batch), so masks are distinct per
(step, layer, dp_rank, micro) and collision-free against the
embedding-dropout slot (layer = L). Warmup/cooldown ticks compute on
garbage lanes with clipped micro indices; their outputs are never
collected, so their masks are irrelevant.

Under ``param_storage='sharded'`` (ISSUE 11 tentpole) the replicated
per-leaf stacks are gone: each rank's OWN chunks are all-gathered from
the 1/N flat bucket shards before the ring (one uniform collective per
(pass, owner-stage) pair with a static chunk index — every rank
contributes its shard slice, the owner keeps the result), so per-rank
full-param residency stays 1/pp of the layers while steady-state
storage drops to 1/N; the update writes shards back with no gather.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .fused_scan_step import _RNG_SLOTS
from .sharded_scan import (
    ShardedFusedScanTrainStep, pack_flat, scatter_flat,
)


class PipelineScanTrainStep(ShardedFusedScanTrainStep):
    """Hybrid (dp, pp) train step for a scan_layers GPT model.

    Usage::

        mesh = dist.env.build_mesh({"dp": 2, "pp": 2})
        dist.env.set_mesh(mesh)
        step = PipelineScanTrainStep(model, opt, mesh=mesh,
                                     num_micro=4)
        loss = step(ids, labels)     # ids [global_batch, seq]

    The ``pp`` axis must divide C = num_layers / layer_chunk (virtual
    stages round-robin exactly); ``num_micro`` must divide the local
    (per-dp-rank) batch. `schedule_stats()` reports the analytic bubble
    ratio of the configured schedule.
    """

    # a dp1×pp1 mesh is a legitimate REFERENCE configuration (the ring
    # degenerates to the sequential microbatch-accumulation loop — the
    # "accumulated single-stage grads" side of the bit-identity test)
    _allow_degree_one = True

    def __init__(self, model, optimizer, criterion=None, pp_axis=None,
                 num_micro=2, mesh=None, axis=None, **kw):
        # consumed by the _extra_reduction_axes hook during super init
        self._pp_axis_arg = pp_axis
        self._num_micro = int(num_micro)
        super().__init__(model, optimizer, criterion=criterion,
                         mesh=mesh, axis=axis, **kw)
        if self._pp_axis is None:
            raise ValueError(
                "PipelineScanTrainStep needs a 'pp' mesh axis (the ring "
                "ppermutes over it; degree 1 is allowed as the "
                "sequential-accumulation reference); use "
                "ShardedFusedScanTrainStep on a dp-only mesh")
        C = self.model.config.num_layers // self._layer_chunk
        if C % self._pp_degree:
            raise ValueError(
                f"chunk count {C} (= num_layers/layer_chunk) not "
                f"divisible by pp degree {self._pp_degree}: the "
                "round-robin virtual-stage placement needs C % pp == 0")
        if self._num_micro < 1:
            raise ValueError("num_micro must be >= 1")
        # dropout: the (dp_rank, micro) pair takes the rank slot of the
        # per-layer offset scheme — masks distinct per micro-batch and
        # identical wherever the same (step, layer, rows) recur
        self._rng_nranks = self._batch_degree * self._num_micro
        if self._aux_active:
            raise ValueError(
                "MoE blocks under pipeline parallelism are not "
                "supported: the ring schedule does not thread the "
                "per-chunk aux-loss output (and expert all_to_alls "
                "inside ring ticks are unvalidated) — train MoE models "
                "on a dp or dp×ep mesh (ShardedFusedScanTrainStep)")
        # observability (ISSUE 12): the analytic schedule accounting is
        # static — publish it once so the bubble fraction rides every
        # registry snapshot / Prometheus scrape
        from ..observability import registry as _oreg

        stats = self.schedule_stats()
        reg = _oreg()
        reg.gauge("pipeline.bubble_fraction").set(stats["bubble_ratio"])
        reg.gauge("pipeline.num_micro").set(stats["num_micro"])
        reg.gauge("pipeline.degree").set(stats["pp"])

    def _rng_rank(self):
        # the micro index is added per tick (see the ring body); this
        # contributes the dp part of the (dp_rank*M + m) slot
        return super()._rng_rank() * self._num_micro

    def _extra_reduction_axes(self, mesh):
        pp_axis = self._pp_axis_arg
        if pp_axis is None:
            pp_axis = "pp" if "pp" in mesh.axis_names else None
        elif pp_axis not in mesh.axis_names:
            pp_axis = None
        self._pp_axis = pp_axis
        self._pp_degree = int(mesh.shape[pp_axis]) if pp_axis else 1
        return (pp_axis,) if pp_axis else ()

    def schedule_stats(self):
        """Analytic schedule accounting (the bubble-ratio probe): the
        ring runs V serial passes of pp + M - 1 ticks; a stage computes
        usefully on M of each pass's ticks."""
        pp, M = self._pp_degree, self._num_micro
        C = self.model.config.num_layers // self._layer_chunk
        V = C // pp
        ticks = V * (pp + M - 1)
        return {
            "pp": pp, "num_micro": M, "layer_chunks": C,
            "virtual_stages_per_rank": V,
            "ring_ticks": ticks,
            "useful_ticks_per_stage": V * M,
            "bubble_ratio": (pp - 1) / (pp + M - 1),
        }

    def _own_chunks(self, state):
        """Per-leaf [V, K, ...] stacks of THIS stage's chunks.

        Replicated storage: a jnp.take of the replicated stacks.
        Sharded storage: for each (pass, owner) pair, all-gather the
        statically-indexed chunk from the flat bucket shards (uniform
        over the mesh — every rank contributes its slice) and keep it
        where this rank IS the owner stage; non-trainable leaves ride
        the replicated stacks as before."""
        s = state["s"]
        K = self._layer_chunk
        C = self.model.config.num_layers // K
        pp = self._pp_degree
        V = C // pp
        stage = lax.axis_index(self._pp_axis)
        own_idx = stage + pp * jnp.arange(V)   # round-robin ownership
        if self._param_storage != "sharded":
            sp_c = tuple(a.reshape((C, K) + tuple(a.shape[1:]))
                         for a in s["p"])
            return tuple(jnp.take(a, own_idx, axis=0) for a in sp_c)
        fp_c = [a.reshape((C, K, -1)) for a in s["fp"]]
        t_pos = {j: tj for tj, (j, _) in enumerate(self._s_train)}
        per_v = []
        for v in range(V):
            sel = None
            for owner in range(pp):
                full = self._gather_stacked_chunk(
                    fp_c, jnp.int32(pp * v + owner))
                if sel is None:
                    sel = tuple(
                        jnp.where(stage == owner, d, jnp.zeros_like(d))
                        for d in full)
                else:
                    sel = tuple(jnp.where(stage == owner, d, acc)
                                for d, acc in zip(full, sel))
            per_v.append(sel)
        own = []
        for j in range(len(self._s_params)):
            if j in self._s_trainable_idx:
                tj = t_pos[j]
                own.append(jnp.stack([per_v[v][tj] for v in range(V)]))
            else:
                d_c = s["p"][j].reshape((C, K)
                                        + tuple(s["p"][j].shape[1:]))
                own.append(jnp.take(d_c, own_idx, axis=0))
        return tuple(own)

    # -- the ring forward/backward (replaces the base backward scan) ----
    def _grads(self, state, ids, labels, t32, ct):
        from .nonfinite_guard import all_finite

        s, o = state["s"], state["o"]
        axes, N = self._axes, self._degree
        K = self._layer_chunk
        n_layers = self.model.config.num_layers
        C = n_layers // K
        pp, M = self._pp_degree, self._num_micro
        V = C // pp
        quant = self._comm_quant
        s_assign, o_assign = self._s_assign, self._o_assign
        clip_norm = self._clip_global
        guard = self._guard
        nm = self._numerics is not None
        rank = self._flat_rank()
        chunk_apply = self._chunk_apply
        pp_axis = self._pp_axis
        stage = lax.axis_index(pp_axis)
        perm = [(i, (i + 1) % pp) for i in range(pp)]
        b, seq = ids.shape              # LOCAL (dp-shard) batch rows
        if b % M:
            raise ValueError(
                f"local batch {b} not divisible by num_micro {M}")
        mb = b // M
        pos = jnp.arange(seq, dtype=ids.dtype)[None, :]

        sharded_storage = self._param_storage == "sharded"
        own0 = self._own_chunks(state)
        o_p0 = (self._gather_outer_full(o) if sharded_storage
                else o["p"])

        def one_pass(p_v, xs, xs_fin, v, acc):
            """One ring pass: every micro-batch through this pass's pp
            stages. xs [M, mb, seq, h]; collected outputs land on stage
            0 (the ring wraps the last stage back there). ``v`` indexes
            the pass for the dropout offsets: this stage's chunk is
            stage + pp*v, and the micro on this stage at tick t entered
            the ring `stage` ticks ago. ``acc`` threads the per-chunk
            activation-stats accumulators ([C] each, or None): a valid
            tick's output charges the LOGICAL chunk id stage + pp*v —
            the virtual-stage placement mapped back to layer ids
            (ISSUE 15); warmup/cooldown garbage lanes are masked out.
            ``xs_fin`` [M] carries each waiting micro's finiteness flag
            (fp32 0/1): output flags derive from the square-sum and
            ppermute alongside the activations, so health costs no
            per-tick isfinite pass (the fused/sharded one-pass design,
            carried around the ring)."""
            chunk_idx = stage + pp * v
            rng_base = (self._rng_chunk_base(t32, chunk_idx)
                        if self._dropout_active else None)

            def tick(carry, t):
                st, st_fin, outs, outs_fin, a = carry
                take = jnp.clip(t, 0, M - 1)
                fresh = lax.dynamic_index_in_dim(xs, take, 0,
                                                 keepdims=False)
                inp = jnp.where(stage == 0, fresh, st)
                rng0 = None
                if rng_base is not None:
                    m = jnp.clip(t - stage, 0, M - 1)
                    rng0 = rng_base + m * _RNG_SLOTS
                y = chunk_apply(p_v, inp, rng0)
                passed_fin = None
                if a is not None:
                    # stats never feed the loss: stop_gradient keeps
                    # the ring's vjp structure untouched. Output
                    # finiteness derives from the fp32 square-sum
                    # (one pass; see fused_scan_step._act_stats); the
                    # INPUT flag rode the ring with the activation
                    y_s = lax.stop_gradient(y)
                    valid = (t >= stage) & (t - stage <= M - 1)
                    vf = valid.astype(jnp.float32)
                    oh = (jnp.arange(C) == chunk_idx).astype(
                        jnp.float32) * vf
                    y_sq = jnp.sum(jnp.square(
                        y_s.astype(jnp.float32)))
                    y_fin = jnp.isfinite(y_sq)
                    in_fin = jnp.where(
                        stage == 0,
                        lax.dynamic_index_in_dim(xs_fin, take, 0,
                                                 keepdims=False),
                        st_fin)
                    origin = (in_fin > 0.5) & ~y_fin
                    # selection, not oh*y_sq: 0 × NaN would smear a
                    # broken chunk's NaN over every other row
                    a = (a[0] + jnp.where(oh > 0, oh * y_sq, 0.0),
                         a[1] + oh * jnp.float32(y_s.size),
                         a[2] + oh * origin.astype(jnp.float32))
                    passed_fin = lax.ppermute(
                        y_fin.astype(jnp.float32), pp_axis, perm)
                passed = lax.ppermute(y, pp_axis, perm)
                done = t - (pp - 1)
                slot = jnp.clip(done, 0, M - 1)
                outs = lax.cond(
                    done >= 0,
                    lambda o_: lax.dynamic_update_index_in_dim(
                        o_, passed, slot, 0),
                    lambda o_: o_, outs)
                if a is not None:
                    outs_fin = lax.cond(
                        done >= 0,
                        lambda o_: lax.dynamic_update_index_in_dim(
                            o_, passed_fin, slot, 0),
                        lambda o_: o_, outs_fin)
                return (passed, passed_fin, outs, outs_fin, a), None

            fin0 = (jnp.float32(1.0) if nm else None)
            outs_fin0 = (jnp.ones((M,), jnp.float32) if nm else None)
            (_, _, outs, outs_fin, acc), _ = lax.scan(
                tick, (jnp.zeros_like(xs[0]), fin0, jnp.zeros_like(xs),
                       outs_fin0, acc),
                jnp.arange(pp + M - 1))
            return outs, outs_fin, acc

        def fwd_loss(own_p, o_p):
            # embedding is pointwise over tokens: embed the full local
            # batch once, then view as micro-batches (the embedding
            # dropout slot is layer L of the base scheme, micro 0 —
            # unique, since blocks only use layers < L)
            x0 = self._embed_fn(
                o_p, ids, pos,
                rng_off=(self._rng_base(t32, n_layers)
                         if self._dropout_active else None))
            xs = x0.reshape((M, mb) + tuple(x0.shape[1:]))
            acc = ((jnp.zeros((C,), jnp.float32),) * 3 if nm else None)
            # per-micro finiteness of the embedded batch: the ONE
            # explicit isfinite pass (chunk outputs derive theirs from
            # the square-sums around the ring)
            xs_fin = (jnp.isfinite(lax.stop_gradient(x0))
                      .reshape(M, -1).all(axis=1).astype(jnp.float32)
                      if nm else None)
            for v in range(V):
                p_v = tuple(a[v] for a in own_p)
                xs, xs_fin, acc = one_pass(p_v, xs, xs_fin, v, acc)
                # between passes only stage 0's collected buffer is
                # meaningful — and only stage 0 reads it (fresh inject)
            # replicate the finished hiddens to every pp rank for the
            # head (outer params are replicated; each rank computes the
            # identical loss — the uniform ×pp joint factor)
            y = lax.psum(jnp.where(stage == 0, xs, jnp.zeros_like(xs)),
                         pp_axis)
            yb = y.reshape((b,) + tuple(y.shape[2:]))
            return self._head_fn(o_p, yb, labels), acc

        loss, vjpf, act_acc = jax.vjp(fwd_loss, own0, o_p0,
                                      has_aux=True)
        d_own, d_o = vjpf(ct.astype(loss.dtype))

        # ---- per-chunk scatter over (dp..., pp): the pp leg of the sum
        # selects the owner stage, the dp leg reduces data-parallel;
        # only 1/pp of the layers' grads ever exist on a rank (d_own)
        # and only the 1/N flat shards survive this loop
        sq = jnp.float32(0.0)
        fin = jnp.bool_(True)
        c_sq = [jnp.float32(0.0)] * C
        c_fin = [jnp.bool_(True)] * C
        G = []
        for bkt in s_assign.buckets:
            rows = []
            for c in range(C):
                v, owner = c // pp, c % pp
                flat = pack_flat(lambda j, v=v: d_own[j][v], bkt,
                                 lead=(K,))
                contrib = jnp.where(stage == owner, flat,
                                    jnp.zeros_like(flat))
                gs = scatter_flat(contrib, axes, N, quant)   # [K, F/N]
                # clip carry + per-chunk monitor row share one shard
                # reduction (ISSUE 15 dedup, as in the base step)
                if clip_norm is not None or nm:
                    nc = self._shard_of(self._s_hp[bkt.index][3], rank,
                                        bkt.numel // N)
                    ct_b, mt_b = self._clip_monitor_sq(
                        gs, nc, clip_norm is not None, nm)
                    if ct_b is not None:
                        sq = sq + ct_b
                    if nm:
                        c_sq[c] = c_sq[c] + mt_b
                if guard is not None:
                    # exact isfinite for the guard's skip decision
                    b_fin = all_finite([gs])
                    c_fin[c] = c_fin[c] & b_fin
                    fin = fin & b_fin
                rows.append(gs)
            G.append(jnp.stack(rows))                        # [C, K, F/N]
        G = tuple(G)

        # ---- outer grads (embed cotangents are zero off stage 0, head
        # cotangents live on every rank — the ×pp factor is uniform,
        # see the module docstring)
        o_gs = []
        o_sq = jnp.float32(0.0)
        o_fin = jnp.bool_(True)
        for bkt in o_assign.buckets:
            flat = pack_flat(
                lambda j: d_o[j].astype(jnp.float32), bkt)
            gs = scatter_flat(flat, axes, N, quant)          # [F/N]
            if clip_norm is not None or nm:
                nc = self._shard_of(self._o_hp[bkt.index][3], rank,
                                    bkt.numel // N)
                ct_b, mt_b = self._clip_monitor_sq(
                    gs, nc, clip_norm is not None, nm)
                if ct_b is not None:
                    sq = sq + ct_b
                if nm:
                    o_sq = o_sq + mt_b
            if guard is not None:
                b_fin = all_finite([gs])
                o_fin = o_fin & b_fin
                fin = fin & b_fin
            o_gs.append(gs)
        nrows = None
        if nm:
            if guard is None:
                # finiteness derives from the sq-norms (no extra pass)
                c_fin = [jnp.isfinite(c_sq[c]) for c in range(C)]
                o_fin = jnp.isfinite(o_sq)
            # the backward-origin column stays zero here (the whole-
            # ring vjp has no per-chunk incoming cotangent to compare
            # against) — provenance relies on the activation origin
            # (forward) and the per-chunk grad finite flags
            nrows = {
                "grad": jnp.stack(
                    [jnp.stack([c_sq[c],
                                (~c_fin[c]).astype(jnp.float32),
                                jnp.float32(0.0)])
                     for c in range(C)]),
                "act": jnp.stack(act_acc, axis=1),      # [C, 3]
                "outer": jnp.stack([
                    o_sq, (~o_fin).astype(jnp.float32)]),
            }
        return loss, G, o_gs, sq, fin, nrows
