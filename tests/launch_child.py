"""Child script for the 2-launcher E2E test: joins the cluster through the
launcher's env contract (init_parallel_env -> jax.distributed.initialize),
all-reduces across the two processes, prints the proof line."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1").strip()

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import distributed as dist
from paddle_tpu.distributed import env as denv

denv.init_parallel_env()
assert jax.process_count() == 2, jax.process_count()
rank = int(os.environ["PADDLE_TRAINER_ID"])
mesh = denv.get_mesh()

# dp-sharded global vector [1, 2]: each host owns one element
full = np.asarray([1.0, 2.0], np.float32)
arr = jax.make_array_from_callback(
    full.shape, NamedSharding(mesh, P("dp")), lambda idx: full[idx])
t = paddle.Tensor._wrap(arr)
dist.all_reduce(t)   # psum over dp -> every shard holds 3
local = np.asarray(t._data.addressable_shards[0].data)
assert float(local[0]) == 3.0, local
print(f"LAUNCH-OK rank={rank} sum={float(local[0])}", flush=True)
