"""Automatic mixed precision.

Reference parity: python/paddle/amp/ (auto_cast :1012, amp_guard :457,
GradScaler grad_scaler.py:645, lists amp_lists.py) + the C++ state machine
paddle/fluid/imperative/amp_auto_cast.cc. TPU-first: bf16 is the primary AMP
dtype (native MXU input type, no loss scaling required); fp16 is supported
with the reference's dynamic loss scaling.
"""
from .auto_cast import (  # noqa: F401
    auto_cast,
    amp_guard,
    amp_state,
    decorate,
    amp_decorate,
    is_auto_cast_enabled,
    get_amp_dtype,
    get_amp_level,
    white_list,
    black_list,
)
from .grad_scaler import GradScaler, AmpScaler, OptimizerState  # noqa: F401
from . import debugging  # noqa: F401


def is_float16_supported(device=None):
    """reference amp/__init__ is_float16_supported: TPUs compute in
    bf16/fp32; fp16 storage works but matmul units prefer bf16."""
    import jax

    return jax.default_backend() in ("tpu", "gpu")


def is_bfloat16_supported(device=None):
    return True        # bf16 is the TPU-native half precision
