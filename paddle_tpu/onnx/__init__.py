"""paddle.onnx parity surface: export() requires the onnx package, which
this image does not ship; jit.save (StableHLO round-trip) is the
serialization path on TPU."""
__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise RuntimeError(
        "paddle.onnx.export needs the 'onnx' package (not available in "
        "this environment). TPU deployment path: paddle.jit.save(layer, "
        "path) -> compiled servable via paddle.jit.load")
