"""Hermetic parity selftest for the HYBRID (dp×mp / dp×pp) train steps.

Run under a cpu-forced env (bench.py's stripped subprocess /
tools/cpu_env.sh) with an 8-virtual-device host platform:

    python -m paddle_tpu.jit.hybrid_selftest

Asserts, on one process, the ISSUE 8 acceptance triangle with
ClipGradByGlobalNorm active:

    dp-only ShardedFusedScanTrainStep (8-rank mesh)
        ==  dp4×mp2 (Megatron column/row block slicing, in-block mp
            psums, vocab-parallel sharded fused CE, grads scattered
            over the flattened dp×mp product)
        ==  dp2×pp2 (ring pipeline: layer chunks round-robined over pp,
            micro-batch accumulation, grads scattered over dp×pp)

loss trajectories within the sharded_scan_selftest tolerances, final
params within rel tol, ONE compiled executable per mesh signature
(compile-count probes), and the planner (`pick_layout`) returning a
pruning-clean layout for the 8-device host. Prints ONE JSON line so the
record lands verbatim in BENCH_r*.json.
"""
from __future__ import annotations

import json

import numpy as np

TOL = {
    "loss_abs": 5e-4,
    "param_rtol": 5e-3,
    "param_atol": 5e-5,
}

TINY = dict(vocab_size=96, hidden_size=32, num_layers=4,
            num_attention_heads=2, max_position_embeddings=16,
            hidden_dropout_prob=0.0, attention_dropout_prob=0.0)


def hybrid_probe(n_devices=8, steps=4, lr=1e-2, clip_norm=0.05, seed=0):
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as popt
    from paddle_tpu.distributed import env as denv
    from paddle_tpu.jit import (
        PipelineScanTrainStep, ShardedFusedScanTrainStep,
    )
    from paddle_tpu.models import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    devs = jax.devices("cpu")[:n_devices]
    if len(devs) < n_devices:
        return {"check": f"FAIL: {len(devs)} cpu devices < {n_devices}"}
    # ISSUE 12: the retrace sentinel runs STRICT for the whole lane —
    # any unexpected recompile on any hybrid step path is a hard FAIL,
    # proving the old hand-written compile-count probes are subsumed
    from .. import observability as obs

    obs.set_strict_retrace(True)
    crit = GPTPretrainingCriterion()
    rng = np.random.default_rng(seed)
    ids = paddle.to_tensor(
        rng.integers(0, TINY["vocab_size"], (n_devices, 16)),
        dtype="int64")
    labels = paddle.to_tensor(
        rng.integers(0, TINY["vocab_size"], (n_devices, 16)),
        dtype="int64")

    def build(mesh, cls, **kw):
        cfg = GPTConfig(**TINY, scan_layers=True)
        paddle.seed(seed)
        model = GPTForCausalLM(cfg)
        opt = popt.AdamW(learning_rate=lr,
                         parameters=model.parameters(),
                         grad_clip=nn.ClipGradByGlobalNorm(clip_norm))
        denv.set_mesh(mesh)
        step = cls(model, opt, criterion=crit, mesh=mesh, **kw)
        losses = [float(step(ids, labels)) for _ in range(steps)]
        return losses, model, step

    from jax.sharding import Mesh

    mesh_dp = Mesh(np.asarray(devs), ("sharding",))
    ref, m_ref, s_ref = build(mesh_dp, ShardedFusedScanTrainStep,
                              axis="sharding")
    mesh_mp = Mesh(np.asarray(devs).reshape(n_devices // 2, 2),
                   ("dp", "mp"))
    mp, m_mp, s_mp = build(mesh_mp, ShardedFusedScanTrainStep,
                           axis="dp", mp_axis="mp")
    mesh_pp = denv.build_mesh({"dp": 2, "pp": 2}, devices=devs[:4])
    pp, m_pp, s_pp = build(mesh_pp, PipelineScanTrainStep, num_micro=2)

    def ldiff(a, b):
        return float(np.max(np.abs(np.asarray(a) - np.asarray(b))))

    def pdiff(m1, m2):
        worst = 0.0
        for (_, p1), (_, p2) in zip(m1.named_parameters(),
                                    m2.named_parameters()):
            a = np.asarray(p1._data, np.float32)
            b = np.asarray(p2._data, np.float32)
            denom = TOL["param_rtol"] * np.abs(a) + TOL["param_atol"]
            worst = max(worst, float(np.max(np.abs(a - b) / denom)))
        return worst

    d_mp, d_pp = ldiff(ref, mp), ldiff(ref, pp)
    p_mp, p_pp = pdiff(m_ref, m_mp), pdiff(m_ref, m_pp)
    compiles = {"dp4xmp2": s_mp._jitted._cache_size(),
                "dp2xpp2": s_pp._jitted._cache_size()}

    # planner: a pruning-clean layout for this host
    from ..distributed.auto_tuner import pick_layout, spec_of_model
    from ..distributed.auto_tuner.prune import prune_candidates

    cfg = GPTConfig(**TINY, scan_layers=True)
    spec = spec_of_model(cfg, global_batch=n_devices, seq_len=16)
    try:
        dec = pick_layout(spec, n_devices,
                          backend={"coll_lat_us": 300.0,
                                   "ici_gbps": 2e9,
                                   "pp_tick_ms": 0.2,
                                   "peak_flops": 2e11}, env={})
        cand = dec["candidate"]
        planner_ok = (cand.degree == n_devices
                      and prune_candidates([cand], spec, 16.0)[0]
                      .pruned_reason is None)
        planner_pick = dec["mesh_degrees"]
    except Exception as e:
        planner_ok, planner_pick = False, f"{type(e).__name__}: {e}"

    bubble = s_pp.schedule_stats()
    ok = (d_mp < TOL["loss_abs"] and d_pp < TOL["loss_abs"]
          and p_mp < 1.0 and p_pp < 1.0
          and compiles["dp4xmp2"] == 1 and compiles["dp2xpp2"] == 1
          and planner_ok)
    return {
        "check": "pass" if ok else
        f"FAIL: mp={d_mp:.2e} pp={d_pp:.2e} p_mp={p_mp:.2f} "
        f"p_pp={p_pp:.2f} compiles={compiles} planner={planner_ok}",
        "n_devices": n_devices, "steps": steps,
        "max_abs_loss_diff_dp4xmp2_vs_dp8": round(d_mp, 9),
        "max_abs_loss_diff_dp2xpp2_vs_dp8": round(d_pp, 9),
        "param_tol_violation_dp4xmp2": round(p_mp, 4),
        "param_tol_violation_dp2xpp2": round(p_pp, 4),
        "compile_count_per_signature": compiles,
        "pipeline_schedule": bubble,
        "planner_pick": planner_pick,
        "retrace_sentinel": {
            "strict": obs.strict_retrace(),
            "total_unexpected":
                obs.retrace_summary()["total_unexpected"],
            "dp4xmp2_signatures":
                s_mp.retrace_stats()["signatures"],
            "dp2xpp2_signatures":
                s_pp.retrace_stats()["signatures"],
        },
        "tolerances": TOL,
    }


def _main():
    denv_ok = True
    try:
        out = {"hybrid_parallel": hybrid_probe()}
    except Exception as e:
        denv_ok = False
        out = {"hybrid_parallel": {
            "check": f"FAIL: {type(e).__name__}: {e}"[:300]}}
    print(json.dumps(out))
    return 0 if denv_ok else 1


if __name__ == "__main__":
    raise SystemExit(_main())
