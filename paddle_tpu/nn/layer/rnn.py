"""Recurrent layers (python/paddle/nn/layer/rnn.py parity).

TPU-first: the time loop is a `jax.lax.scan` inside one recorded op — a single
compiled XLA while-loop instead of the reference's per-step kernel launches
(paddle/phi/kernels/gpu/rnn_kernel.cu).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...ops._dispatch import nary, ensure_tensor
from .layers import Layer
from ..initializer import Uniform


def _lstm_step(carry, x_t, wi, wh, bi, bh):
    h, c = carry
    gates = x_t @ wi.T + h @ wh.T
    if bi is not None:
        gates = gates + bi + bh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return (h_new, c_new), h_new


def _gru_step(carry, x_t, wi, wh, bi, bh):
    h = carry
    gi = x_t @ wi.T + (bi if bi is not None else 0)
    gh = h @ wh.T + (bh if bh is not None else 0)
    ir, iz, ic = jnp.split(gi, 3, axis=-1)
    hr, hz, hc = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(ir + hr)
    z = jax.nn.sigmoid(iz + hz)
    n = jnp.tanh(ic + r * hc)
    h_new = (1 - z) * n + z * h
    return h_new, h_new


def _rnn_step(carry, x_t, wi, wh, bi, bh, act):
    h = carry
    out = x_t @ wi.T + h @ wh.T
    if bi is not None:
        out = out + bi + bh
    h_new = jnp.tanh(out) if act == "tanh" else jax.nn.relu(out)
    return h_new, h_new


class RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, activation="tanh"):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.num_directions = 2 if direction in ("bidirect", "bidirectional") else 1
        gate_mult = {"LSTM": 4, "GRU": 3, "RNN": 1}[mode]

        std = 1.0 / math.sqrt(hidden_size)
        self._all_weights = []
        for layer in range(num_layers):
            for direction_i in range(self.num_directions):
                in_size = input_size if layer == 0 else hidden_size * self.num_directions
                suffix = "_reverse" if direction_i else ""
                wi = self.create_parameter(
                    [gate_mult * hidden_size, in_size], attr=weight_ih_attr,
                    default_initializer=Uniform(-std, std))
                wh = self.create_parameter(
                    [gate_mult * hidden_size, hidden_size], attr=weight_hh_attr,
                    default_initializer=Uniform(-std, std))
                bi = self.create_parameter(
                    [gate_mult * hidden_size], attr=bias_ih_attr, is_bias=True,
                    default_initializer=Uniform(-std, std))
                bh = self.create_parameter(
                    [gate_mult * hidden_size], attr=bias_hh_attr, is_bias=True,
                    default_initializer=Uniform(-std, std))
                names = [f"weight_ih_l{layer}{suffix}", f"weight_hh_l{layer}{suffix}",
                         f"bias_ih_l{layer}{suffix}", f"bias_hh_l{layer}{suffix}"]
                for n, p in zip(names, (wi, wh, bi, bh)):
                    self.add_parameter(n, p)
                self._all_weights.append(names)

    def _run_layer(self, x, wi, wh, bi, bh, init, reverse=False):
        # x: [seq, batch, in]; returns outputs [seq, batch, hidden], final state
        step = {"LSTM": _lstm_step, "GRU": _gru_step, "RNN": _rnn_step}[self.mode]

        def scan_fn(carry, x_t):
            if self.mode == "RNN":
                return step(carry, x_t, wi, wh, bi, bh, self.activation)
            return step(carry, x_t, wi, wh, bi, bh)

        xs = jnp.flip(x, 0) if reverse else x
        final, ys = jax.lax.scan(scan_fn, init, xs)
        if reverse:
            ys = jnp.flip(ys, 0)
        return ys, final

    def forward(self, inputs, initial_states=None, sequence_length=None):
        inputs = ensure_tensor(inputs)
        batch_axis = 1 if self.time_major else 0
        batch = inputs.shape[batch_axis]
        D, L, H = self.num_directions, self.num_layers, self.hidden_size

        params = []
        for names in self._all_weights:
            params.extend(self._parameters[n] for n in names)

        has_lstm_state = self.mode == "LSTM"
        if initial_states is None:
            from ...ops import zeros

            if has_lstm_state:
                initial_states = (zeros([L * D, batch, H], dtype=inputs.dtype),
                                  zeros([L * D, batch, H], dtype=inputs.dtype))
            else:
                initial_states = zeros([L * D, batch, H], dtype=inputs.dtype)
        state_tensors = list(initial_states) if has_lstm_state else [initial_states]

        n_per = 4

        def f(x, *flat):
            ps = flat[: len(params)]
            states = flat[len(params):]
            h0 = states[0]
            c0 = states[1] if has_lstm_state else None
            xs = x if self.time_major else jnp.swapaxes(x, 0, 1)
            layer_in = xs
            h_finals, c_finals = [], []
            for layer in range(L):
                outs_dir = []
                for d in range(D):
                    idx = (layer * D + d) * n_per
                    wi, wh, bi, bh = ps[idx : idx + 4]
                    sidx = layer * D + d
                    if has_lstm_state:
                        init = (h0[sidx], c0[sidx])
                    else:
                        init = h0[sidx]
                    ys, final = self._run_layer(layer_in, wi, wh, bi, bh, init, reverse=d == 1)
                    outs_dir.append(ys)
                    if has_lstm_state:
                        h_finals.append(final[0])
                        c_finals.append(final[1])
                    else:
                        h_finals.append(final)
                layer_in = jnp.concatenate(outs_dir, axis=-1) if D == 2 else outs_dir[0]
            out = layer_in if self.time_major else jnp.swapaxes(layer_in, 0, 1)
            h_n = jnp.stack(h_finals, 0)
            if has_lstm_state:
                c_n = jnp.stack(c_finals, 0)
                return out, h_n, c_n
            return out, h_n

        results = nary(f, [inputs] + params + state_tensors, self.mode.lower())
        if has_lstm_state:
            out, h_n, c_n = results
            return out, (h_n, c_n)
        out, h_n = results
        return out, h_n


class SimpleRNN(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        super().__init__("RNN", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation=activation, **kwargs)


class LSTM(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class GRU(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class LSTMCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               attr=weight_ih_attr,
                                               default_initializer=Uniform(-std, std))
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               attr=weight_hh_attr,
                                               default_initializer=Uniform(-std, std))
        self.bias_ih = self.create_parameter([4 * hidden_size], attr=bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=Uniform(-std, std))
        self.bias_hh = self.create_parameter([4 * hidden_size], attr=bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=Uniform(-std, std))

    def forward(self, inputs, states=None):
        inputs = ensure_tensor(inputs)
        if states is None:
            from ...ops import zeros

            b = inputs.shape[0]
            states = (zeros([b, self.hidden_size], dtype=inputs.dtype),
                      zeros([b, self.hidden_size], dtype=inputs.dtype))
        h, c = states

        def f(x, hh, cc, wi, wh, bi, bh):
            (h_new, c_new), _ = _lstm_step((hh, cc), x, wi, wh, bi, bh)
            return h_new, c_new

        h_new, c_new = nary(
            f, [inputs, h, c, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh],
            "lstm_cell",
        )
        return h_new, (h_new, c_new)


class GRUCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               attr=weight_ih_attr,
                                               default_initializer=Uniform(-std, std))
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               attr=weight_hh_attr,
                                               default_initializer=Uniform(-std, std))
        self.bias_ih = self.create_parameter([3 * hidden_size], attr=bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=Uniform(-std, std))
        self.bias_hh = self.create_parameter([3 * hidden_size], attr=bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=Uniform(-std, std))

    def forward(self, inputs, states=None):
        inputs = ensure_tensor(inputs)
        if states is None:
            from ...ops import zeros

            states = zeros([inputs.shape[0], self.hidden_size], dtype=inputs.dtype)

        def f(x, h, wi, wh, bi, bh):
            h_new, _ = _gru_step(h, x, wi, wh, bi, bh)
            return h_new

        h_new = nary(
            f, [inputs, states, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh],
            "gru_cell",
        )
        return h_new, h_new


class SimpleRNNCell(Layer):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               attr=weight_ih_attr,
                                               default_initializer=Uniform(-std, std))
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               attr=weight_hh_attr,
                                               default_initializer=Uniform(-std, std))
        self.bias_ih = self.create_parameter([hidden_size], attr=bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=Uniform(-std, std))
        self.bias_hh = self.create_parameter([hidden_size], attr=bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=Uniform(-std, std))

    def forward(self, inputs, states=None):
        inputs = ensure_tensor(inputs)
        if states is None:
            from ...ops import zeros

            states = zeros([inputs.shape[0], self.hidden_size], dtype=inputs.dtype)

        def f(x, h, wi, wh, bi, bh):
            h_new, _ = _rnn_step(h, x, wi, wh, bi, bh, self.activation)
            return h_new

        h_new = nary(
            f, [inputs, states, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh],
            "rnn_cell",
        )
        return h_new, h_new
