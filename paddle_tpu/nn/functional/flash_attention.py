"""Attention functionals.

Reference parity: python/paddle/nn/functional/flash_attention.py
(flash_attention :195, scaled_dot_product_attention :976) backed by the CUDA
flash-attn kernel (paddle/phi/kernels/gpu/flash_attn_kernel.cu). TPU-first:
the default path is XLA dot-softmax-dot (which XLA already pipelines well at
moderate seq len); a Pallas splash/flash kernel is used for long sequences
when available (paddle_tpu.ops.pallas.flash_attention).

Layouts follow the reference: q/k/v are [batch, seqlen, num_heads, head_dim].
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...framework.random import next_key
from ...ops._dispatch import nary, ensure_tensor


# -- packed-sequence segment context ----------------------------------------
# Layers deep inside a model (GPTAttention under the scan template) have
# no signature room for per-batch segment ids; the model's outer forward
# publishes them here for the duration of its trace and attention layers
# pick them up. The value is a [batch, seq] int Tensor/array (tokens
# attend only within their own segment) or None (dense attention).
_segment_ctx = [None]


@contextlib.contextmanager
def attention_segments(segment_ids):
    """Publish packed-sequence segment ids to every attention layer
    traced inside the block (None = plain dense/causal attention)."""
    _segment_ctx.append(segment_ids)
    try:
        yield
    finally:
        _segment_ctx.pop()


def current_segment_ids():
    return _segment_ctx[-1]


def _sdpa_ref(q, k, v, mask, scale, causal, dropout_p, key):
    # q,k,v: [b, s, h, d] — dots run in the input dtype on the MXU with fp32
    # accumulation (preferred_element_type); softmax math in fp32.
    #
    # Score storage dtype: the [b, h, s, s] score matrix is the dominant
    # HBM traffic of non-flash attention (written fwd, re-read/rewritten
    # under remat and in backward). With bf16/fp16 inputs we round the
    # accumulated scores back to the input dtype for HBM residency — the
    # same storage precision the reference's fused softmax path keeps
    # (fp16 scores, fp32 softmax internals) — halving that traffic.
    # FLAGS_attention_fp32_scores restores full-fp32 storage.
    from ...utils import flags as _flags

    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if (q.dtype in (jnp.bfloat16, jnp.float16)
            and not _flags.get_flag("FLAGS_attention_fp32_scores")):
        logits = logits.astype(q.dtype)
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cmask, logits, jnp.asarray(-jnp.inf, logits.dtype))
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits,
                               jnp.asarray(-jnp.inf, logits.dtype))
        else:
            logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True,
                                 segment_ids=None, name=None):
    """`segment_ids` ([batch, seq] int, or unset to consult the ambient
    `attention_segments` context) restricts attention to within-segment
    pairs — the packed-sequence training mask. Routed through the splash
    kernel (TPU) or its XLA fallback; with dropout active it lowers to a
    dense boolean mask instead."""
    query = ensure_tensor(query)
    key_t = ensure_tensor(key)
    value = ensure_tensor(value)
    head_dim = query.shape[-1]
    scale = 1.0 / (head_dim ** 0.5)
    drop = dropout_p if training else 0.0
    rng = next_key() if drop > 0.0 else None

    from ...ops.pallas import flash_attention as pallas_flash
    from ...ops.pallas import splash_attention as pallas_splash
    from ...utils import flags as _flags

    seqlen = query.shape[1]
    min_seq = int(_flags.get_flags(["FLAGS_pallas_flash_min_seqlen"])
                  ["FLAGS_pallas_flash_min_seqlen"])

    if segment_ids is None:
        segment_ids = current_segment_ids()
    splash_on = bool(_flags.get_flag("FLAGS_splash_attn"))
    force_interp = bool(_flags.get_flag("FLAGS_pallas_force_interpret"))
    kvh = key_t.shape[2]

    if segment_ids is not None and attn_mask is not None:
        # combining an arbitrary user mask with the document-isolation
        # mask is not plumbed; dropping either silently would train
        # across document boundaries (or without the user's mask)
        raise ValueError(
            "scaled_dot_product_attention got both attn_mask and "
            "segment_ids (explicit or via attention_segments): the "
            "masks are not combinable — fold the segment mask into "
            "attn_mask yourself, or drop one")

    if segment_ids is not None:
        seg = ensure_tensor(segment_ids)
        if splash_on and drop == 0.0:
            # splash owns the segment mask: fused into the score tiles
            # on TPU (or interpret mode), dense-equivalent XLA fallback
            # elsewhere — no [s, s] mask tensor either way
            interp = True if force_interp else None

            def f_seg(q, k, v, s):
                return pallas_splash.splash_attention(
                    q, k, v, causal=is_causal, segment_ids=s,
                    scale=scale, interpret=interp)

            return nary(f_seg, [query, key_t, value, seg],
                        "splash_attention_segments")
        # dropout (or splash off): lower segments to a dense bool mask
        segd = seg.astype("int32")

        def f_mask(q, k, v, s):
            m = (s[:, None, :, None] == s[:, None, None, :])
            return _sdpa_ref(q, k, v, m, scale, is_causal, drop, rng)

        return nary(f_mask, [query, key_t, value, segd],
                    "sdpa_segment_mask")

    # splash takes the long-seq training slot ahead of flash: same
    # routing conditions, tiled fwd + stats-recompute bwd, GQA-capable
    use_splash = (
        splash_on and seqlen >= min_seq and attn_mask is None
        and drop == 0.0
        and pallas_splash.supports(tuple(query.shape), kvh,
                                   query._data.dtype)
        and (force_interp or pallas_splash._on_tpu())
    )
    if use_splash:
        interp = True if force_interp else None
        return nary(
            lambda q, k, v: pallas_splash.splash_attention(
                q, k, v, causal=is_causal, scale=scale,
                interpret=interp),
            [query, key_t, value], "splash_attention")

    use_pallas = (
        seqlen >= min_seq and attn_mask is None and drop == 0.0
        and query.shape == key_t.shape == value.shape
        and pallas_flash.supports(tuple(query.shape), query._data.dtype,
                                  is_causal)
    )

    if use_pallas:
        inputs = [query, key_t, value]
        return nary(
            lambda q, k, v: pallas_flash.flash_attention(
                q, k, v, causal=is_causal, scale=scale),
            inputs, "flash_attention_pallas",
        )

    inputs = [query, key_t, value]
    if attn_mask is not None:
        inputs.append(ensure_tensor(attn_mask))

        def f(q, k, v, m):
            return _sdpa_ref(q, k, v, m, scale, is_causal, drop, rng)
    else:

        def f(q, k, v):
            return _sdpa_ref(q, k, v, None, scale, is_causal, drop, rng)

    return nary(f, inputs, "scaled_dot_product_attention")


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """flash_attention parity (reference :195). Returns (out, softmax or None)."""
    out = scaled_dot_product_attention(
        query, key, value, attn_mask=None, dropout_p=dropout,
        is_causal=causal, training=training,
    )
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen ("unpadded") attention parity (reference flash_attention.py's
    flash_attn_unpadded over flash_attn_varlen CUDA kernels).

    q/k/v: [total_tokens, num_heads, head_dim] — sequences packed back to
    back; cu_seqlens_*: [batch+1] cumulative boundaries. TPU-first: instead
    of ragged kernels, segment-id masking — one dense masked attention with
    static shapes (block-diagonal over segments, causal within a segment),
    which XLA fuses like any other attention. Returns (out, None).
    """
    query = ensure_tensor(query)
    key_t = ensure_tensor(key)
    value = ensure_tensor(value)
    cu_q = ensure_tensor(cu_seqlens_q, dtype="int32")
    cu_k = ensure_tensor(cu_seqlens_k, dtype="int32")
    drop = float(dropout) if training else 0.0
    rng = next_key() if drop > 0.0 else None

    def f(q, k, v, cq, ck):
        tq, tk = q.shape[0], k.shape[0]
        iq = jnp.arange(tq, dtype=jnp.int32)
        ik = jnp.arange(tk, dtype=jnp.int32)
        seg_q = jnp.searchsorted(cq, iq, side="right")      # [tq] 1-based
        seg_k = jnp.searchsorted(ck, ik, side="right")
        pos_q = iq - cq[seg_q - 1]                          # pos in own seq
        pos_k = ik - ck[seg_k - 1]
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            mask = mask & (pos_q[:, None] >= pos_k[None, :])
        logits = jnp.einsum("qhd,khd->hqk", q, k,
                            preferred_element_type=jnp.float32) * scale
        logits = jnp.where(mask[None], logits, jnp.float32(-jnp.inf))
        probs = jax.nn.softmax(logits, axis=-1)
        # rows whose segment is empty (shouldn't happen) -> nan guard
        probs = jnp.where(jnp.any(mask, axis=1)[None, :, None], probs, 0.0)
        if drop > 0.0 and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - drop, probs.shape)
            probs = jnp.where(keep, probs / (1.0 - drop), 0.0)
        out = jnp.einsum("hqk,khd->qhd", probs.astype(v.dtype), v,
                         preferred_element_type=jnp.float32)
        return out.astype(q.dtype)

    out = nary(f, [query, key_t, value, cu_q, cu_k], "flash_attn_unpadded")
    return out, None


def sparse_attention(*args, **kwargs):
    raise NotImplementedError("sparse attention is not in the TPU v1 op set")
