"""LLaMA model family — the BASELINE config-5 flagship
(LLaMA-7B HybridParallel tp=4 pp=2 + sequence parallel).

Reference parity: the reference trains LLaMA through its Fleet stack
(fleet meta-parallel wrappers over mpu layers; fused kernels
fused_rms_norm / fused_rope in paddle/phi/kernels/fusion/). TPU-first:
RMSNorm/RoPE/SwiGLU are jnp expressions XLA fuses on its own; GQA K/V
heads broadcast inside the einsum; TP/SP/ZeRO placement comes from
`llama_sharding_rules` regexes consumed by the same GSPMD mechanism as
the GPT family (match_sharding + NamedSharding), so every fleet wrapper
(TrainStep, sharding stages, SegmentParallel, pipeline) composes
unchanged.
"""
from __future__ import annotations

import math
import re
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F

__all__ = [
    "LlamaConfig", "LlamaForCausalLM", "LlamaModel",
    "LlamaPretrainingCriterion", "llama_config", "llama_sharding_rules",
]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 0            # 0 -> llama's 8/3 * hidden rule
    num_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 0          # 0 -> MHA (= num heads); <n -> GQA
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    hidden_dropout_prob: float = 0.0
    use_recompute: bool = False
    recompute_policy: str = None
    use_ring_attention: bool = False

    def __post_init__(self):
        if not self.intermediate_size:
            # llama rounds 8/3*h up to a multiple of 256
            target = int(8 * self.hidden_size / 3)
            self.intermediate_size = 256 * ((target + 255) // 256)
        if not self.num_key_value_heads:
            self.num_key_value_heads = self.num_attention_heads


LLAMA_CONFIGS = {
    "llama-7b": dict(hidden_size=4096, num_layers=32,
                     num_attention_heads=32, intermediate_size=11008),
    "llama-13b": dict(hidden_size=5120, num_layers=40,
                      num_attention_heads=40, intermediate_size=13824),
    "llama2-70b": dict(hidden_size=8192, num_layers=80,
                       num_attention_heads=64, num_key_value_heads=8,
                       intermediate_size=28672),
    "tinyllama-1.1b": dict(hidden_size=2048, num_layers=22,
                           num_attention_heads=32, num_key_value_heads=4,
                           intermediate_size=5632),
}


def llama_config(name: str, **overrides) -> LlamaConfig:
    kw = dict(LLAMA_CONFIGS[name])
    kw.update(overrides)
    return LlamaConfig(**kw)


class LlamaRMSNorm(nn.Layer):
    def __init__(self, hidden_size, epsilon=1e-5):
        super().__init__()
        from ..nn.initializer import Constant

        self.weight = self.create_parameter(
            [hidden_size], default_initializer=Constant(1.0))
        self.epsilon = epsilon

    def forward(self, x):
        return F.rms_norm(x, weight=self.weight, epsilon=self.epsilon)


def _rope_tables(seq, dim, theta, dtype=jnp.float32):
    inv = 1.0 / (theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    t = jnp.arange(seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)                     # [s, dim/2]
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rotary_pos_emb(x, cos, sin):
    """x: [b, s, h, d]; rotate-half convention (llama)."""
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


class LlamaAttention(nn.Layer):
    """GQA attention with RoPE. K/V heads repeat across query groups
    inside the score einsum (no materialized repeat on HBM)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = h // self.num_heads
        self.q_proj = nn.Linear(h, self.num_heads * self.head_dim,
                                bias_attr=False)
        self.k_proj = nn.Linear(h, self.num_kv_heads * self.head_dim,
                                bias_attr=False)
        self.v_proj = nn.Linear(h, self.num_kv_heads * self.head_dim,
                                bias_attr=False)
        self.o_proj = nn.Linear(self.num_heads * self.head_dim, h,
                                bias_attr=False)
        self.rope_theta = config.rope_theta
        self._use_ring = config.use_ring_attention

    def _ring_mesh(self, s):
        if not self._use_ring:
            return None
        from ..distributed import env as denv

        if not denv.is_initialized():
            return None
        mesh = denv.get_mesh()
        if ("sep" in mesh.axis_names and mesh.shape["sep"] > 1
                and s % int(mesh.shape["sep"]) == 0):
            return mesh
        return None

    def forward(self, x):
        b, s, h = x.shape
        q = self.q_proj(x).reshape([b, s, self.num_heads, self.head_dim])
        k = self.k_proj(x).reshape([b, s, self.num_kv_heads, self.head_dim])
        v = self.v_proj(x).reshape([b, s, self.num_kv_heads, self.head_dim])

        from ..ops._dispatch import nary

        theta = self.rope_theta
        hd = self.head_dim
        groups = self.num_heads // self.num_kv_heads
        ring_mesh = self._ring_mesh(s)

        def attn(qd, kd, vd):
            cos, sin = _rope_tables(s, hd, theta, jnp.float32)
            qr = apply_rotary_pos_emb(qd.astype(jnp.float32), cos, sin
                                      ).astype(qd.dtype)
            kr = apply_rotary_pos_emb(kd.astype(jnp.float32), cos, sin
                                      ).astype(kd.dtype)
            if ring_mesh is not None:
                from ..distributed.fleet.meta_parallel import ring_attention

                kv_rep = jnp.repeat(kr, groups, axis=2)
                vv_rep = jnp.repeat(vd, groups, axis=2)
                return ring_attention(qr, kv_rep, vv_rep, mesh=ring_mesh,
                                      axis="sep", causal=True)
            # grouped scores: fold query groups, broadcast kv heads
            qg = qr.reshape(b, s, self.num_kv_heads, groups, hd)
            logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, kr,
                                preferred_element_type=jnp.float32)
            logits = logits / math.sqrt(hd)
            # bf16 score HBM residency (same policy as _sdpa_ref — softmax
            # math stays fp32; FLAGS_attention_fp32_scores restores fp32)
            from ..utils import flags as _flags

            if (qd.dtype in (jnp.bfloat16, jnp.float16)
                    and not _flags.get_flag("FLAGS_attention_fp32_scores")):
                logits = logits.astype(qd.dtype)
            mask = jnp.tril(jnp.ones((s, s), bool))
            logits = jnp.where(mask[None, None, None], logits,
                               jnp.asarray(-jnp.inf, logits.dtype))
            probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
            out = jnp.einsum("bhgqk,bkhd->bqhgd", probs.astype(vd.dtype),
                             vd, preferred_element_type=jnp.float32)
            return out.reshape(b, s, self.num_heads, hd).astype(qd.dtype)

        out = nary(attn, [q, k, v], "llama_attention")
        return self.o_proj(out.reshape([b, s,
                                        self.num_heads * self.head_dim]))


class LlamaMLP(nn.Layer):
    """SwiGLU: down(silu(gate(x)) * up(x))."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, m = config.hidden_size, config.intermediate_size
        self.gate_proj = nn.Linear(h, m, bias_attr=False)
        self.up_proj = nn.Linear(h, m, bias_attr=False)
        self.down_proj = nn.Linear(m, h, bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = LlamaRMSNorm(config.hidden_size,
                                            config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = LlamaRMSNorm(config.hidden_size,
                                                     config.rms_norm_eps)
        self.mlp = LlamaMLP(config)
        self._use_recompute = config.use_recompute
        self._recompute_policy = config.recompute_policy

    def _inner(self, x):
        x = x + self.self_attn(self.input_layernorm(x))
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x

    def forward(self, x):
        if self._use_recompute and self.training:
            from ..distributed.fleet import recompute

            return recompute(self._inner, x, policy=self._recompute_policy)
        return self._inner(x)


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = nn.Embedding(config.vocab_size,
                                         config.hidden_size)
        self.layers = nn.LayerList([LlamaDecoderLayer(config)
                                    for _ in range(config.num_layers)])
        self.norm = LlamaRMSNorm(config.hidden_size, config.rms_norm_eps)
        self._init_weights(config)

    def _init_weights(self, config):
        from ..framework.random import host_normal

        std = config.initializer_range
        for name, p in self.named_parameters():
            if p.ndim >= 2:
                p._data = host_normal(p._data.shape, std)
                if re.search(r"(o_proj|down_proj)\.weight$", name):
                    p._data = p._data / math.sqrt(2.0 * config.num_layers)

    def forward(self, input_ids):
        x = self.embed_tokens(input_ids)
        for layer in self.layers:
            x = layer(x)
        return self.norm(x)


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)

    def forward(self, input_ids):
        hidden = self.llama(input_ids)
        if self.lm_head is not None:
            return self.lm_head(hidden)
        from .. import ops

        return ops.matmul(hidden, self.llama.embed_tokens.weight,
                          transpose_y=True)

    def loss(self, input_ids, labels, loss_mask=None):
        """Fused LM-head training loss (see GPTForCausalLM.loss)."""
        from .gpt import fused_lm_loss

        hidden = self.llama(input_ids)
        if self.lm_head is None:
            w, t_y = self.llama.embed_tokens.weight, True
        else:
            w, t_y = self.lm_head.weight, False
        return fused_lm_loss(hidden, w, t_y, labels, loss_mask)


# the GPT criterion is architecture-agnostic CE over shifted tokens
from .gpt import GPTPretrainingCriterion as LlamaPretrainingCriterion  # noqa: E402


def llama_sharding_rules(tp_axis="mp", fsdp_axis=None):
    """Megatron TP placement for llama weights (+ optional ZeRO-3 dim).

    Column-parallel: q/k/v/gate/up (out-features on tp);
    row-parallel: o/down (in-features on tp); embeddings vocab-sharded.
    """
    return [
        (r"(q_proj|k_proj|v_proj|gate_proj|up_proj)\.weight$",
         (fsdp_axis, tp_axis)),
        (r"(o_proj|down_proj)\.weight$", (tp_axis, fsdp_axis)),
        (r"embed_tokens\.weight$", (tp_axis, fsdp_axis)),
        (r"lm_head\.weight$", (fsdp_axis, tp_axis)),
        (r"(layernorm|norm)\.weight$", (None,)),
    ]
