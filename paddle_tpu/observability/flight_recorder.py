"""Crash flight recorder: a bounded ring of recent runtime events plus
a one-call crash dump.

Training and serving both feed it for free (`StepTimeline.record`,
retrace-sentinel events, `ServingEngine` recovery, checkpoint saves);
on a crash — an uncaught exception once `install()` ran, or an explicit
``dump()`` from a recovery path — the ring, the exception, and a full
metrics-registry snapshot are written to one JSON file under
``.flight_recorder/`` (override with PADDLE_FLIGHT_DIR). The file is
what a postmortem needs: the last N steps' telemetry and what the
counters said at the moment of death, without any always-on log volume.
"""
from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
import traceback

__all__ = ["FlightRecorder", "recorder", "install",
           "install_signal_dump", "thread_stacks"]


def thread_stacks() -> dict:
    """Formatted stack trace of EVERY live thread (via
    ``sys._current_frames``), keyed ``name(tid)`` — the hung-process
    forensics payload: what each thread was executing at dump time."""
    import threading as _threading

    names = {t.ident: t.name for t in _threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        key = f"{names.get(tid, '?')}({tid})"
        out[key] = "".join(traceback.format_stack(frame))[-8000:]
    return out


class FlightRecorder:
    def __init__(self, capacity=512):
        self._lock = threading.Lock()
        self._events = collections.deque(maxlen=int(capacity))
        self.last_dump_path = None

    def note(self, kind, **fields):
        """Append one event (O(1), bounded). Values should be JSON
        scalars/short lists — this is a black box, not a log."""
        ev = {"ts": round(time.time(), 6), "kind": kind}
        ev.update(fields)
        with self._lock:
            self._events.append(ev)

    def snapshot(self, blocking=True):
        """``blocking=False`` is the SIGNAL-HANDLER path: the handler
        may be running on top of an interrupted frame that already
        holds this lock (note() is on the step hot path), so it must
        try-acquire and degrade to an empty list rather than deadlock
        the thread it interrupted."""
        if not self._lock.acquire(blocking=blocking):
            return []
        try:
            return list(self._events)
        finally:
            self._lock.release()

    def clear(self):
        with self._lock:
            self._events.clear()

    def dump(self, reason="", exc=None, path=None, threads=False,
             signal_safe=False) -> str:
        """Write the black box to disk; returns the file path. Never
        raises (a failing dump must not mask the original crash) —
        returns None on failure. ``threads=True`` adds every live
        thread's stack (the SIGQUIT hung-process path).

        ``signal_safe=True`` (the signal handler sets it) avoids every
        blocking lock acquisition: the interrupted frame underneath the
        handler may HOLD the recorder's or an instrument's lock, and a
        blocking acquire would deadlock the process the dump exists to
        diagnose — the event ring is try-acquired and the registry
        snapshot (per-instrument locks) is skipped."""
        try:
            from .registry import registry

            rec = {
                "reason": reason,
                "ts": round(time.time(), 6),
                "pid": os.getpid(),
                "events": self.snapshot(blocking=not signal_safe),
            }
            if threads:
                try:
                    rec["threads"] = thread_stacks()
                except Exception:
                    rec["threads"] = {}
            if exc is not None:
                rec["exception"] = {
                    "type": type(exc).__name__,
                    "message": str(exc)[:2000],
                    "traceback": "".join(traceback.format_exception(
                        type(exc), exc, exc.__traceback__))[-8000:],
                }
            if signal_safe:
                rec["metrics"] = {}     # instrument locks not safe here
            else:
                try:
                    rec["metrics"] = registry().snapshot()
                except Exception:
                    rec["metrics"] = {}
                try:
                    # ISSUE 19: if a fault injector is live, its firing
                    # log belongs in the black box — "what did we
                    # inject" is the first question a chaos-run crash
                    # dump has to answer
                    from . import faults as _faults

                    inj = _faults.active()
                    if inj is not None:
                        rec["faults"] = inj.summary()
                except Exception:
                    pass
            if path is None:
                root = os.environ.get("PADDLE_FLIGHT_DIR",
                                      ".flight_recorder")
                os.makedirs(root, exist_ok=True)
                path = os.path.join(
                    root,
                    f"crash_{os.getpid()}_{int(time.time() * 1e3)}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(rec, f, default=str)
            os.replace(tmp, path)
            if threads:
                # faulthandler's C-level dump alongside (catches
                # threads wedged in C extensions that
                # sys._current_frames renders less faithfully)
                try:
                    import faulthandler

                    with open(path + ".stacks.txt", "w") as f:
                        faulthandler.dump_traceback(file=f,
                                                    all_threads=True)
                except Exception:
                    pass
            self.last_dump_path = path
            return path
        except Exception:
            return None


_lock = threading.Lock()
_recorder = None
_installed = False
_prev_hook = None


def recorder() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        with _lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


def install():
    """Chain the flight recorder into ``sys.excepthook``: an uncaught
    exception dumps the black box before the normal traceback prints.
    Idempotent."""
    global _installed, _prev_hook
    with _lock:
        if _installed:
            return
        _prev_hook = sys.excepthook
        _installed = True

    def hook(exc_type, exc, tb):
        try:
            e = exc if isinstance(exc, BaseException) else exc_type(exc)
            if tb is not None and getattr(e, "__traceback__", None) is None:
                e = e.with_traceback(tb)
            recorder().dump(reason="uncaught exception", exc=e)
        except Exception:
            pass
        (_prev_hook or sys.__excepthook__)(exc_type, exc, tb)

    sys.excepthook = hook


_signal_prev: dict = {}


def install_signal_dump(signum=None):
    """Hung-process forensics: installing on SIGQUIT (Ctrl-\\; fallback
    SIGUSR2 where SIGQUIT is absent) makes the signal dump the event
    ring PLUS every thread's stack trace to the crash-dump path and
    RETURN — the process keeps running (installing replaces SIGQUIT's
    default core-dump death), so you can poke a wedged trainer/server
    from outside without killing it. Any existing Python-level handler
    is chained after the dump. Idempotent per signal; returns the
    signal number installed. Main-thread only (signal module rule)."""
    import signal as _signal

    sig = signum
    if sig is None:
        sig = getattr(_signal, "SIGQUIT", None)
        if sig is None:                       # e.g. Windows
            sig = getattr(_signal, "SIGUSR2", None)
    if sig is None:
        return None
    with _lock:
        if sig in _signal_prev:
            return sig
    prev = _signal.getsignal(sig)

    def handler(s, frame):
        # signal_safe: no note() and no blocking lock — the frame this
        # handler interrupted may hold the very locks a normal dump
        # takes, and blocking here would wedge the process harder than
        # whatever prompted the poke
        recorder().dump(reason=f"signal {s} (hung-process dump)",
                        threads=True, signal_safe=True)
        p = _signal_prev.get(s)
        if callable(p):
            try:
                p(s, frame)
            except Exception:
                pass

    # install FIRST: signal.signal raises off the main thread, and the
    # idempotency record must not be poisoned by a failed install
    _signal.signal(sig, handler)
    with _lock:
        _signal_prev[sig] = prev
    return sig
