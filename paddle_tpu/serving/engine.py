"""ServingEngine: the continuous-batching loop over the compiled steps.

One engine owns one (model, PagedKVCache) pair and exactly TWO compiled
programs in steady state: a `ServeDecodeStep` over the full slot batch
(traced once — admissions, preemptions and retirements only refresh its
inputs) and a `ChunkPrefillStep` per chunk bucket (a handful of
power-of-two sizes). Every `step()`:

1. **admit** — the scheduler moves queue-head requests into free slots
   (capacity probed via `can_allocate` before commit);
2. **chunk-prefill** — at most `prefill_chunks_per_step` bounded chunks
   of the oldest resident prompt run between decode steps, so TTFT for
   new arrivals stays bounded while resident sequences keep streaming;
3. **decode** — one token for every decode-active slot (per-slot RNG
   streams keyed on (request seed, context length): a request's tokens
   never depend on its batch neighbours);
4. **stream/retire** — tokens push to handles (callback / poll /
   `stream()` iterator); EOS or token-budget retirement frees pages
   immediately.

The cache's device state threads functionally through the steps with
the KV pools donated (HBM-neutral steady state); the host bookkeeping
(page tables, active flags, free lists) is refreshed into the step
inputs each call — an input refresh, never a retrace.

Tracing (ISSUE 13): every request carries a root span from submit to
retire with children for queue wait, admission, each chunked prefill
call (bucket, batch composition, slot, pages held), each decode burst
(k, batch), preemption/resume (victim reason, pages reclaimed) and
stream delivery — a retired request's trace is a complete causal
timeline. Requests whose TTFT or worst inter-token gap lands beyond a
configurable percentile of the live distribution keep their full span
tree in the tail-exemplar ring (`slow_requests()`); declared SLOs get
rolling-window burn-rate gauges; `start_debug_server()` serves
/metrics /healthz /tracez /sloz /flightz /memz over loopback.
"""
from __future__ import annotations

import time

import numpy as np

from ..inference.kv_cache import PagedKVCache
from ..jit.decode_step import (ChunkPrefillStep, SelfDraftProposer,
                               ServeDecodeStep, ServeSpecDecodeStep,
                               _split_state, refresh_serving_buffers)
from ..jit.train_step import _tree_data
from ..observability import SLOTracker, Tracer, faults
from .metrics import ServingMetrics
from .request import FinishReason, Request, RequestHandle, RequestState
from .scheduler import RequestScheduler

__all__ = ["ServingEngine"]


class ServingEngine:
    def __init__(self, model, max_slots=8, max_len=256, page_size=16,
                 num_pages=None, chunk_size=64,
                 prefill_chunks_per_step=1, prefill_batch=4,
                 decode_burst=1, do_sample=False, top_k=0, top_p=1.0,
                 temperature=1.0, compiled=True, cache_dtype=None,
                 kv_quant=None, draft_model=None, spec_k=4,
                 donate=True, admit_watermark="auto",
                 clock=time.perf_counter,
                 trace=True, trace_capacity=256, exemplar_capacity=32,
                 exemplar_quantile=99.0, exemplar_min_samples=32,
                 slos=(), debug_port=None, tuner=False, tuner_kw=None,
                 prefill_only=False, host_kv_ring=None,
                 recover_retries=0, recover_backoff_s=0.05):
        import jax.numpy as jnp

        cfg = model.config
        model.gpt._check_decodable()
        if max_len > cfg.max_position_embeddings:
            raise ValueError(
                f"max_len={max_len} exceeds max_position_embeddings="
                f"{cfg.max_position_embeddings}")
        self.model = model
        self.kind = "paged"
        self.max_slots = int(max_slots)
        self.max_len = int(max_len)
        self.page_size = int(page_size)
        self.chunk_size = int(chunk_size)
        self.prefill_chunks_per_step = int(prefill_chunks_per_step)
        # one chunk-prefill call advances up to this many prompts at
        # once (fixed batch dim, dummy rows masked to the trash page) —
        # amortizes the per-call cost that otherwise serializes
        # admissions under a deep queue
        self.prefill_batch = max(1, min(int(prefill_batch),
                                        self.max_slots))
        # decode_burst > 1 fuses that many decode steps INSIDE the
        # compiled ServeDecodeStep: one dispatch + one host sync per k
        # tokens (multi-step scheduling) — the host loop's per-call
        # cost is what dominates small decode steps. Streaming and
        # admission granularity coarsen to k steps; tokens a request
        # samples past its EOS/budget inside a burst are discarded.
        self.decode_burst = max(1, int(decode_burst))
        self.do_sample = bool(do_sample)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.temperature = float(temperature)
        self.compiled = bool(compiled)
        self.clock = clock
        self._cache_dtype = cache_dtype or jnp.float32
        self.pages_per_seq = -(-self.max_len // self.page_size)
        # full provisioning by default; pass a smaller pool to
        # oversubscribe (preemption reclaims pages under pressure)
        self.num_pages = int(num_pages or
                             1 + self.max_slots * self.pages_per_seq)
        self._params = list(model.parameters())
        # int8/int4 paged KV (ISSUES 16/20): ~2x / ~4x the resident
        # tokens per page of HBM (per-row scales, dequant fused into
        # the attention gather; int4 packs two values per byte)
        if kv_quant not in (None, "int8", "int4"):
            raise ValueError(f"unknown KV quant mode {kv_quant!r}")
        self.kv_quant = kv_quant
        # speculative decoding (ISSUE 16): the decode program becomes
        # draft-k-propose / verify-once with variable per-slot yield.
        # draft_model="self" (ISSUE 20) resolves to the target's own
        # draft heads — no second checkpoint, no draft KV pools.
        if isinstance(draft_model, str):
            if draft_model != "self":
                raise ValueError(
                    f"unknown draft_model {draft_model!r} (the only "
                    "string form is 'self')")
            draft_model = SelfDraftProposer(model)
        self.draft_model = draft_model
        self.spec_k = int(spec_k)
        self.cache = self._make_cache()
        if draft_model is not None:
            self_draft = getattr(draft_model, "is_self_draft", False)
            if self_draft:
                if self.spec_k > cfg.num_draft_heads:
                    raise ValueError(
                        f"spec_k={self.spec_k} exceeds the target's "
                        f"num_draft_heads={cfg.num_draft_heads}")
            else:
                draft_model.gpt._check_decodable()
                if draft_model.config.vocab_size != cfg.vocab_size:
                    raise ValueError(
                        "draft model vocab_size "
                        f"{draft_model.config.vocab_size} != target "
                        f"{cfg.vocab_size} (proposals must be target "
                        "ids)")
            if self.spec_k < 1:
                raise ValueError("spec_k must be >= 1")
            self._draft_params = ([] if self_draft
                                  else list(draft_model.parameters()))
            self.draft_cache = (None if self_draft
                                else self._make_draft_cache())
        else:
            self._draft_params = []
            self.draft_cache = None
        # live-buffer attribution (ISSUE 14): a serving-only process
        # has no train step to claim the model weights
        from ..observability.memory import live_registry

        live_registry().track(self)
        # request-scoped tracing + SLOs (ISSUE 13): per-engine tracer
        # over the per-engine registry; `slos` declares objectives as
        # (name, metric, threshold_s[, target[, window_s]]) tuples,
        # e.g. slos=[("ttft", "ttft_s", 0.25, 0.99, 60.0)]
        from ..observability import MetricsRegistry

        self.exemplar_quantile = float(exemplar_quantile)
        self.exemplar_min_samples = int(exemplar_min_samples)
        reg = MetricsRegistry()
        self.slo = SLOTracker(registry=reg, clock=clock)
        for spec in (slos or ()):
            self.declare_slo(*spec)
        self.metrics = ServingMetrics(clock=clock, registry=reg,
                                      slo=self.slo)
        self._register_mem_gauges()
        self.tracer = Tracer(capacity=trace_capacity,
                             exemplar_capacity=exemplar_capacity,
                             clock=clock,
                             registry=self.metrics.registry,
                             enabled=trace)
        self._retired_this_call: list = []
        self._exemplar_thr = (None, None)
        self._exemplar_refresh_at = 0
        self._debug_server = None
        if debug_port is not None:
            self.start_debug_server(debug_port)
        self.scheduler = RequestScheduler(
            self.cache, self.metrics, admit_watermark=admit_watermark,
            tracer=self.tracer)
        # fleet roles (ISSUE 18): a prefill-only replica runs chunked
        # prefill and stops — its finished sequences are exported to a
        # decode replica via the KV hand-off; a host KV ring turns
        # preemption into evict-to-host with onload-on-readmit
        self.prefill_only = bool(prefill_only)
        self.scheduler.host_ring = host_kv_ring
        # the "auto" admission watermark provisions free pages for one
        # dispatch's worth of growth per live slot
        self.scheduler.token_lookahead = (
            self.spec_k + 1 if draft_model is not None
            else self.decode_burst)
        self._donate_cache = bool(donate)
        self.prefill_step = ChunkPrefillStep(self, donate_cache=donate)
        self.decode_step = ServeDecodeStep(self, donate_cache=donate)
        self.spec_step = (ServeSpecDecodeStep(self, donate_cache=donate)
                          if draft_model is not None else None)
        bkts, b = [], 8
        while b < self.chunk_size:
            bkts.append(b)
            b *= 2
        self.chunk_buckets = tuple(bkts) + (self.chunk_size,)
        # closed-loop knob tuner (ISSUE 17): OFF by default — without
        # one, step() runs the exact PR-16 path. `tuner=True` builds an
        # OnlineTuner with defaults; pass an instance for full control.
        self.last_warmup_ms = None
        if tuner is True:
            from .tuner import OnlineTuner

            self.tuner = OnlineTuner(self, **(tuner_kw or {}))
        else:
            self.tuner = tuner or None
        self._buffers, _ = _split_state(
            "paged", _tree_data(self.cache.state()))
        if self.draft_cache is not None:
            self._buffers["draft"], _ = _split_state(
                "paged", _tree_data(self.draft_cache.state()))
        # per-slot host mirrors refreshed every step (plain input data)
        self._tokens = np.zeros((self.max_slots,), np.int32)
        self._seeds = np.zeros((self.max_slots,), np.uint32)
        self._rid = 0
        # self-healing (ISSUE 19): up to `recover_retries` CONSECUTIVE
        # step failures are absorbed in place (recover + exponential
        # backoff) before escalating to the caller — the fleet watchdog
        # turns the escalation into replica-dead. 0 = raise through on
        # the first failure (the pre-chaos behaviour).
        self.recover_retries = int(recover_retries)
        self.recover_backoff_s = float(recover_backoff_s)
        self._recover_streak = 0
        # set (GIL-atomically, from the fleet watchdog) when this
        # engine is quarantined while a step is still wedged in flight:
        # the next statement the unstuck step reaches bails out instead
        # of emitting tokens for handles a survivor now owns
        self._fenced = False
        # fleet-assigned replica name, threaded into fault-point
        # context so a chaos script can target one replica by name
        self.name = None
        # open hand-off leases (ISSUE 19): lease_id -> (slot, rid).
        # A leased export keeps its pages allocated here until the
        # adopter acks, so a decode replica dying between export and
        # import loses nothing — the blob is re-exportable.
        self._leased: dict[int, tuple] = {}
        self._lease_seq = 0
        # deadline sweep runs only once a deadline request exists
        self._has_deadlines = False

    def _make_cache(self):
        cfg = self.model.config
        nh = cfg.num_attention_heads
        return PagedKVCache(
            cfg.num_layers, nh, cfg.hidden_size // nh,
            num_pages=self.num_pages, page_size=self.page_size,
            max_slots=self.max_slots, pages_per_seq=self.pages_per_seq,
            dtype=self._cache_dtype, quant=self.kv_quant)

    def _make_draft_cache(self):
        """Draft-model pools over the TARGET's slot/page geometry (page
        tables are shared; only the pools differ). Un-quantized: the
        draft's pools are small and a noisy draft only costs accept
        rate."""
        dcfg = self.draft_model.config
        nh = dcfg.num_attention_heads
        return PagedKVCache(
            dcfg.num_layers, nh, dcfg.hidden_size // nh,
            num_pages=self.num_pages, page_size=self.page_size,
            max_slots=self.max_slots, pages_per_seq=self.pages_per_seq,
            dtype=self._cache_dtype)

    # -- client surface ---------------------------------------------------
    def submit(self, prompt, max_new_tokens, priority=0,
               eos_token_id=None, seed=None, on_token=None, rid=None,
               deadline_s=None) -> RequestHandle:
        """Queue a request; returns a streaming handle immediately.
        Tokens arrive as the engine steps (`step()`/`run()`/`stream()`).

        ``rid`` (optional) overrides the engine-local request id: the
        fleet assigns GLOBALLY unique rids so one request's trace legs
        stitch across replicas (prefill leg, decode leg, onload) by the
        same ``req<rid>`` track name.

        ``deadline_s`` (optional) is a wall budget from submit: a
        request still unfinished when it expires retires with finish
        reason ``deadline_exceeded`` (pages freed, span annotated) at
        the next step — a wedged replica cannot hold a client forever.
        """
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = int(prompt.size) + int(max_new_tokens)
        if total > self.max_len:
            raise ValueError(
                f"prompt {prompt.size} + {max_new_tokens} new tokens "
                f"exceeds the engine max_len {self.max_len}")
        if self.cache.pages_needed(total) > self.num_pages - 1:
            raise ValueError(
                f"request needs {self.cache.pages_needed(total)} pages "
                f"but the pool only has {self.num_pages - 1}")
        if rid is None:
            rid = self._rid
            self._rid += 1
        else:
            rid = int(rid)
            self._rid = max(self._rid, rid + 1)
        req = Request(rid, prompt, int(max_new_tokens),
                      priority=int(priority), eos_token_id=eos_token_id,
                      seed=int(seed) if seed is not None else rid,
                      deadline_s=(float(deadline_s)
                                  if deadline_s is not None else None))
        handle = RequestHandle(req, on_token=on_token)
        handle.arrival_seq = rid
        handle.submit_time = self.clock()
        if req.deadline_s is not None:
            handle.deadline = handle.submit_time + req.deadline_s
            self._has_deadlines = True
        # root of this request's causal timeline + the first queue wait
        handle._span = self.tracer.begin(
            "request", track=f"req{rid}", rid=rid,
            prompt_len=int(prompt.size),
            max_new_tokens=int(max_new_tokens), priority=int(priority),
            deadline_s=req.deadline_s)
        handle._span_queue = self.tracer.begin("queue_wait",
                                               parent=handle._span)
        self.scheduler.enqueue(handle)
        self.metrics.on_submit()
        return handle

    def step(self) -> bool:
        """One scheduler iteration: admit, <=N prefill chunks, one
        decode for all running sequences. Returns False when idle."""
        sched = self.scheduler
        worked = False
        try:
            faults.maybe_delay("serving.step.stuck", engine=self.name)
            faults.maybe_raise("serving.step.raise", engine=self.name)
            if self._fenced:
                # quarantined while a step was wedged: the fleet has
                # already re-dispatched every resident handle to a
                # survivor, so when this thread unsticks it must not
                # touch handle state again. Drop the local roster and
                # go idle; pages/slots leak inside this quarantined
                # engine by design (leak_check exempts it).
                sched.running.clear()
                sched.waiting.clear()
                return False
            if self._has_deadlines:
                self._expire_deadlines()
            onloaded = False
            for h in sched.admit():
                # full-width uint32: distinct seeds stay distinct
                # streams (per_slot_keys folds the raw 32-bit value)
                self._seeds[h.slot] = np.uint32(
                    h.request.seed & 0xFFFFFFFF)
                self.tracer.end(h._span_queue,
                                resumed=h.preemptions > 0)
                h._span_queue = None
                if (h.state is RequestState.RUNNING
                        and h._onload_token is not None):
                    # host-ring re-onload: the imported slot rejoins
                    # decode directly; its last sampled token travelled
                    # with the pages
                    self._tokens[h.slot] = int(h._onload_token)
                    h._onload_token = None
                    onloaded = True
                self.tracer.instant(
                    "admit", parent=h._span, slot=h.slot,
                    pages_held=len(
                        self.cache._slot_pages.get(h.slot, ())),
                    resumed=h.preemptions > 0,
                    onload=h.state is RequestState.RUNNING)
            if onloaded:
                # import_slot rewrote pool pages out-of-band — re-split
                # at the safe boundary before the next compiled call
                refresh_serving_buffers(self)
            for _ in range(self.prefill_chunks_per_step):
                heads = sched.prefill_heads(self.prefill_batch)
                if not heads:
                    break
                self._run_prefill_chunk(heads)
                worked = True
            if not self.prefill_only and sched.decode_slots():
                worked |= self._run_decode()
            self._recover_streak = 0
        except BaseException as e:
            self._recover(exc=e)
            if not self._retry_after_recover(e):
                raise
            worked = True
        self.metrics.observe(len(sched.waiting), len(sched.running))
        if self.tuner is not None:
            # the safe boundary: no compiled call is in flight here, so
            # even a retrace-triggering knob (decode burst) can rebuild
            # its step object cleanly
            self.tuner.on_step()
        return worked

    def run(self, max_steps=1_000_000):
        """Drive the loop until every submitted request finished."""
        steps = 0
        while self.scheduler.has_work():
            self.step()
            steps += 1
            if steps >= max_steps:
                raise RuntimeError(
                    f"serving loop did not drain in {max_steps} steps")
        return self.metrics.snapshot()

    def stream(self, handle: RequestHandle):
        """Generator yielding `handle`'s tokens as they are produced,
        stepping the engine (and every other resident request) along."""
        while True:
            for t in handle.new_tokens():
                yield t
            if handle.done:
                return
            if not self.scheduler.has_work():
                raise RuntimeError("request is not resident and the "
                                   "engine is idle")
            self.step()

    # -- deadlines (ISSUE 19) ---------------------------------------------
    def _expire_deadlines(self):
        """Retire every request whose wall deadline has passed: waiting
        handles finish straight from the queue, resident ones through
        the normal retire path (pages freed immediately). Runs at the
        top of each step, so a request can overrun its deadline by at
        most one dispatch."""
        now = self.clock()
        sched = self.scheduler
        for h in [h for h in sched.waiting
                  if h.deadline is not None and now > h.deadline]:
            sched.waiting.remove(h)
            h.state = RequestState.FINISHED
            h.finish_reason = FinishReason.DEADLINE_EXCEEDED
            h.finish_time = now
            self.metrics.on_finish(h)
            self._retired_this_call.append(h)
        for slot, h in [(s, h) for s, h in sched.running.items()
                        if h.deadline is not None and now > h.deadline]:
            self.tracer.instant("deadline_exceeded", parent=h._span,
                                slot=slot,
                                tokens=len(h.output_tokens))
            sched.retire(slot, FinishReason.DEADLINE_EXCEEDED, now)
            self._retired_this_call.append(h)
        if self._retired_this_call:
            from ..observability import registry as _greg

            _greg().counter("serving.deadline_exceeded").inc(
                len(self._retired_this_call))
            self._flush_retired()

    # -- prefill/decode disaggregation (ISSUE 18) -------------------------
    def export_handoff(self, slot: int, lease: bool = False):
        """Detach a freshly-prefilled sequence for adoption by a decode
        replica: copies its KV pages out and closes this engine's leg
        of the request trace. Returns ``(handle, blob, last_token)`` —
        the not-yet-cached last sample travels with the pages, exactly
        like an eviction.

        ``lease=True`` (ISSUE 19) makes the hand-off a transaction:
        the slot's pages stay allocated HERE (inactive) under an open
        lease — stamped into the blob as ``blob["lease_id"]`` — until
        the adopter acks via :meth:`ack_handoff`, so an adopter dying
        between export and import loses nothing:
        :meth:`reexport_handoff` re-materializes the blob from the
        retained pages. ``lease=False`` frees the slot immediately
        (the pre-chaos fire-and-forget hand-off)."""
        handle = self.scheduler.running.pop(slot)
        blob = self.cache.export_slot(slot)
        last_token = int(handle.output_tokens[-1])
        lease_id = None
        if lease:
            lease_id = self._lease_seq
            self._lease_seq += 1
            self.cache.set_active(slot, False)
            self._leased[lease_id] = (slot, handle.request.rid)
            blob["lease_id"] = lease_id
        else:
            self.cache.free(slot)
        handle.slot = None
        if handle._span is not None:
            self.tracer.instant("kv_handoff_export", parent=handle._span,
                                slot=slot, pages=blob["pages"],
                                bytes=blob["nbytes"], lease=lease_id)
            self.tracer.end(handle._span, handoff=True,
                            tokens=len(handle.output_tokens))
            handle._span = None
        return handle, blob, last_token

    def ack_handoff(self, lease_id: int) -> bool:
        """Adopter confirmed the import landed: release the leased
        slot's retained pages. Idempotent (a re-delivered ack after a
        re-export/recovery is a no-op)."""
        ent = self._leased.pop(lease_id, None)
        if ent is None:
            return False
        slot, _rid = ent
        self.cache.free(slot)
        return True

    def reexport_handoff(self, lease_id: int):
        """Re-materialize a still-leased hand-off blob from the
        retained pages (the first copy was corrupted in flight, or its
        adopter died holding it). The lease stays open until an ack."""
        slot, _rid = self._leased[lease_id]
        blob = self.cache.export_slot(slot)
        blob["lease_id"] = lease_id
        return blob

    @property
    def leased_count(self) -> int:
        return len(self._leased)

    def can_adopt(self, blob: dict) -> bool:
        """Would ``adopt_handoff`` land without instantly starving the
        resident decode set? Same watermark rule as admission."""
        seq_len = int(blob["seq_len"])
        if not self.cache.can_allocate(seq_len):
            return False
        left = self.cache.free_page_count - int(blob["pages"])
        return left >= self.scheduler._watermark()

    def adopt_handoff(self, handle: RequestHandle, blob: dict,
                      last_token: int, refresh: bool = True) -> int:
        """Land a prefill replica's exported sequence: import the pages,
        join the decode set, open this engine's leg of the trace (same
        ``req<rid>`` track — the fleet stitches the legs by rid).
        ``refresh=False`` lets a caller adopting a BATCH defer the
        buffer resync and pay it once (it must call
        ``refresh_serving_buffers`` itself before the next step)."""
        slot = self.cache.import_slot(blob, active=True)
        if refresh:
            refresh_serving_buffers(self)
        rid = handle.request.rid
        handle.slot = slot
        handle.state = RequestState.RUNNING
        self.scheduler.running[slot] = handle
        self._tokens[slot] = int(last_token)
        self._seeds[slot] = np.uint32(handle.request.seed & 0xFFFFFFFF)
        handle._span = self.tracer.begin(
            "request", track=f"req{rid}", rid=rid, phase="decode",
            handoff=True, prompt_len=len(handle.request.prompt),
            max_new_tokens=handle.request.max_new_tokens,
            priority=handle.request.priority)
        self.tracer.instant("kv_handoff_import", parent=handle._span,
                            slot=slot, pages=blob["pages"],
                            bytes=blob["nbytes"])
        self.metrics.on_admit(resumed=False)
        return slot

    def resubmit(self, handle: RequestHandle) -> RequestHandle:
        """Adopt an in-flight handle harvested from a dead replica
        (fleet re-dispatch, ISSUE 19): the request resumes by
        re-prefill on THIS engine. Tokens already streamed to the
        client replay through ``pending`` — they are never re-pushed —
        and the per-request (seed, context-position) RNG stream
        reproduces the continuation bit-exactly, so the client's
        delivery stays exactly-once. The caller must have requeued the
        handle (``_requeue_for_resume``) and bumped its epoch fence."""
        rid = handle.request.rid
        self._rid = max(self._rid, rid + 1)
        if handle.deadline is not None:
            self._has_deadlines = True
        handle._span = self.tracer.begin(
            "request", track=f"req{rid}", rid=rid, phase="redispatch",
            delivered=len(handle.output_tokens),
            prompt_len=len(handle.request.prompt),
            max_new_tokens=handle.request.max_new_tokens,
            priority=handle.request.priority)
        handle._span_queue = self.tracer.begin(
            "queue_wait", parent=handle._span, redispatch=True)
        self.scheduler.enqueue(handle)
        return handle

    def compile_counts(self) -> dict:
        """Retrace probe surface: decode must stay at ONE trace across
        arbitrary admit/preempt/retire churn; prefill at most one trace
        per chunk bucket."""
        # under speculative decoding the decode program IS the spec
        # step — report it under the same keys so retrace probes keep
        # asserting "one decode trace" unchanged
        dstep = self.spec_step if self.spec_step is not None \
            else self.decode_step
        return {
            "decode_traces": dstep.trace_count,
            "decode_executables": dstep.cache_size(),
            "prefill_traces": self.prefill_step.trace_count,
            "prefill_executables": self.prefill_step.cache_size(),
            "chunk_buckets": list(self.chunk_buckets),
        }

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()

    def metrics_text(self) -> str:
        """Prometheus text exposition of this engine's metrics — the
        scrape endpoint body (ISSUE 12): counters/gauges plus TTFT and
        inter-token-latency summaries with p50/p90/p99 quantiles."""
        return self.metrics.expose()

    def retrace_stats(self) -> dict:
        """Sentinel receipts for the serving step programs."""
        out = {"decode": self.decode_step.retrace_stats(),
               "prefill": self.prefill_step.retrace_stats()}
        if self.spec_step is not None:
            out["spec"] = self.spec_step.retrace_stats()
        return out

    def reset_metrics(self):
        """Fresh counters (e.g. after a compile warmup run) — the bench
        lanes measure steady-state serving, not trace time. Traces and
        SLO windows clear too (warmup spans are compile noise); SLO
        declarations and the tracer survive, rebound onto the fresh
        registry."""
        self.slo.reset()
        self.metrics = ServingMetrics(clock=self.clock, slo=self.slo)
        self.scheduler.metrics = self.metrics
        self.slo.bind_registry(self.metrics.registry)
        self._register_mem_gauges()
        self.tracer.clear()
        self.tracer.bind_registry(self.metrics.registry)
        self._exemplar_thr = (None, None)
        self._exemplar_refresh_at = 0
        self._retired_this_call.clear()

    def warmup(self):
        """Build every program the serving loop can hit — the decode
        step and one prefill program per chunk bucket — then reset the
        counters, so a measured window never eats a trace. Buckets warm
        one at a time (a joint batch would only compile the largest).

        With the persistent compile cache active (ISSUE 17,
        ``PADDLE_TPU_COMPILE_CACHE``) this is a BULK CACHE-LOAD: every
        program a previous process compiled deserializes in
        milliseconds, so warmup time IS the replica's cold start.
        `last_warmup_ms` and `warmup_report` record the receipt."""
        from ..observability import registry as _greg

        reg = _greg()
        h0 = reg.counter("jit.cache.hit").value
        m0 = reg.counter("jit.cache.miss").value
        t0 = time.perf_counter()
        # a prefill-only replica never decodes: warm just the chunk
        # buckets (1-token requests finish at prefill), skipping the
        # decode program entirely
        new_tokens = 1 if self.prefill_only else 2
        for b in self.chunk_buckets:
            plen = max(1, min(b, self.max_len - 2))
            self.submit(np.ones((plen,), np.int32), new_tokens)
            self.run()
        self.last_warmup_ms = (time.perf_counter() - t0) * 1e3
        self._warmup_report = {
            "warmup_ms": round(self.last_warmup_ms, 3),
            "programs": len(self.chunk_buckets) + (
                0 if self.prefill_only else 1),
            "cache_hits": reg.counter("jit.cache.hit").value - h0,
            "cache_misses": reg.counter("jit.cache.miss").value - m0,
        }
        self.reset_metrics()
        return self

    @property
    def warmup_report(self) -> dict:
        """Cold-start receipt of the last `warmup()`: wall time, program
        count, and how many executables came from the persistent cache
        (hits) vs fresh compiles (misses)."""
        return dict(getattr(self, "_warmup_report", {}) or {})

    def set_decode_burst(self, k):
        """Change the decode burst at a SAFE BOUNDARY (between engine
        steps). The burst is unrolled inside the compiled decode step,
        so this rebuilds the step object — a fresh program and a fresh
        retrace sentinel (the new program's first trace is a first
        signature, never an unexpected recompile; strict mode stays
        clean). With the persistent compile cache warm, a previously
        seen burst deserializes instead of recompiling. No-op under
        speculative decoding (spec_k owns the decode program shape)."""
        k = max(1, int(k))
        if k == self.decode_burst:
            return self
        if self.spec_step is not None:
            raise ValueError("decode_burst is unused under speculative "
                             "decoding (spec_k owns the decode "
                             "program); tune spec_k at construction")
        old = self.decode_burst
        self.decode_burst = k
        self.decode_step = ServeDecodeStep(
            self, donate_cache=self._donate_cache)
        self.scheduler.token_lookahead = k
        from ..observability import recorder

        recorder().note("decode_burst_rebuild", engine_from=old,
                        engine_to=k)
        return self

    # -- step mechanics ---------------------------------------------------
    def _param_data(self):
        return [p._data for p in self._params]

    def _draft_param_data(self):
        return [p._data for p in self._draft_params]

    def _meta(self):
        c = self.cache
        return _tree_data({"page_tables": c.page_tables,
                           "seq_lens": c.seq_lens,
                           "active": c.active})

    def _commit(self, buffers, meta):
        self._buffers = buffers
        self.cache.load_state({**buffers, **meta})

    def _chunk_bucket(self, n):
        for b in self.chunk_buckets:
            if b >= n:
                return b
        return self.chunk_buckets[-1]

    def _run_prefill_chunk(self, heads: list):
        """One compiled call advances the next chunk of up to
        `prefill_batch` prompts. Rows beyond `len(heads)` are dummies:
        their slot id is max_slots (out of bounds — the seq_lens
        scatter drops, the page-table gather clamps harmlessly) and
        their zero-length chunk routes every write to the trash page.
        """
        B = self.prefill_batch
        heads = heads[:B]
        # epoch fence (ISSUE 19): if the fleet re-dispatches a handle
        # off this replica while this call is in flight (wedged thread
        # later unsticking), its results must be discarded — advancing
        # prefill_pos or emitting here would race the survivor
        epochs = [h._epoch for h in heads]
        chunks = [h.pending[h.prefill_pos:
                            h.prefill_pos + self.chunk_size]
                  for h in heads]
        bucket = self._chunk_bucket(max(len(c) for c in chunks))
        spans = [self.tracer.begin(
            "prefill_chunk", parent=h._span, slot=h.slot,
            bucket=bucket, chunk_len=len(c), start=int(h.prefill_pos),
            batch=len(heads),
            pages_held=len(self.cache._slot_pages.get(h.slot, ())),
            resume=h.preemptions > 0)
            for h, c in zip(heads, chunks)]
        ids = np.zeros((B, bucket), np.int32)
        slot_ids = np.full((B,), self.max_slots, np.int32)
        start = np.zeros((B,), np.int32)
        lens_new = np.zeros((B,), np.int32)
        seeds = np.zeros((B,), np.uint32)
        for j, (h, chunk) in enumerate(zip(heads, chunks)):
            ids[j, :len(chunk)] = chunk
            slot_ids[j] = h.slot
            start[j] = h.prefill_pos
            lens_new[j] = h.prefill_pos + len(chunk)
            seeds[j] = self._seeds[h.slot]
        # spans must close even when the compiled call (or a user
        # on_token callback) raises — a leaked open span would sit in
        # the tracer's open set forever and break the zero-orphan
        # invariant after the engine recovers
        try:
            ids_next, _logits, buffers, meta = self.prefill_step(
                self._param_data(), self._buffers, self._meta(),
                ids, slot_ids, start, lens_new, seeds,
                self._draft_param_data())
            self._commit(buffers, meta)
            for sp in spans:
                self.tracer.end(sp)
            tok = None
            for j, (h, chunk) in enumerate(zip(heads, chunks)):
                if h._epoch != epochs[j]:
                    continue   # harvested mid-call: stale result
                self.metrics.prefill_chunks += 1
                h.prefill_pos += len(chunk)
                if h.prefill_pos < len(h.pending):
                    continue
                # prompt fully cached: the sampled token is the
                # request's next real token (its FIRST on a fresh
                # admission -> TTFT)
                if tok is None:
                    tok = np.asarray(ids_next)
                self.cache.set_active(h.slot, True)
                h.state = RequestState.RUNNING
                token = int(tok[j])
                self._tokens[h.slot] = token
                self.tracer.instant("stream_deliver", parent=h._span,
                                    tokens=1, first=True)
                self._emit(h, token)
        finally:
            for sp in spans:
                self.tracer.end(sp, error=True)
            self._flush_retired()

    def _run_decode(self) -> bool:
        if self.draft_model is not None:
            return self._run_spec_decode()
        sched = self.scheduler
        # highest priority first so page pressure lands on the lowest
        order = sorted(sched.decode_slots(),
                       key=lambda s: sched._key(sched.running[s]))
        # burst length k is uniform, but the PAGE lookahead is capped
        # per slot by the request's remaining token budget (and the
        # engine window): tokens a request samples past its budget
        # inside a burst are garbage the host discards, and their
        # writes land on the trash page (unmapped page-table entries
        # are 0) — reserving real pages for them could force a
        # preemption purely to hold discarded tokens
        k = self.decode_burst
        live = []
        for slot in order:
            h = sched.running.get(slot)
            if h is None or h.state is not RequestState.RUNNING:
                continue   # preempted as a victim earlier in this loop
            remaining = h.request.max_new_tokens - len(h.output_tokens)
            ahead = max(1, min(k, remaining,
                               self.max_len - sched._context_len(h)))
            if sched.ensure_token_capacity(slot, lookahead=ahead):
                live.append(slot)
        # a slot approved early can still be sacrificed to a later
        # (higher-priority-tied) slot's reservation — keep only slots
        # that survived the whole capacity pass
        live = [s for s in live
                if sched.running.get(s) is not None
                and sched.running[s].state is RequestState.RUNNING]
        if not live:
            return False
        faults.maybe_delay("serving.decode.straggler", engine=self.name)
        # epoch fence (ISSUE 19): see _run_prefill_chunk
        epochs = {s: sched.running[s]._epoch for s in live}
        # spans must close even when the compiled call (or a user
        # on_token callback) raises — see _run_prefill_chunk
        dspans = {slot: self.tracer.begin(
            "decode_burst", parent=sched.running[slot]._span,
            slot=slot, k=k, batch=len(live)) for slot in live}
        sspans = {}
        emitted = dict.fromkeys(live, 0)
        try:
            out, _logits, buffers, meta = self.decode_step(
                self._param_data(), self._buffers, self._meta(),
                self._tokens, self._seeds)
            self._commit(buffers, meta)
            # ONE host sync per burst: [k, b] sampled ids (the
            # in-graph burst re-feeds them without the host round-trip)
            step_tokens = np.asarray(out)
            for sp in dspans.values():   # burst span covers the sync
                self.tracer.end(sp)
            sspans = {slot: self.tracer.begin(
                "stream_deliver", parent=sched.running[slot]._span)
                for slot in live if sched.running.get(slot) is not None}
            self.metrics.decode_steps += k
            for tok in step_tokens:
                for slot in live:
                    handle = sched.running.get(slot)
                    if (handle is None or handle.state
                            is not RequestState.RUNNING
                            or handle._epoch != epochs[slot]):
                        continue   # retired earlier in this burst
                    token = int(tok[slot])
                    self._tokens[slot] = token
                    emitted[slot] += 1
                    self._emit(handle, token)
        finally:
            for sp in dspans.values():
                self.tracer.end(sp, error=True)
            for slot, sp in sspans.items():
                self.tracer.end(sp, tokens=emitted[slot])
            self._flush_retired()
        return True

    def _run_spec_decode(self) -> bool:
        """Speculative decode dispatch (ISSUE 16): one compiled
        ServeSpecDecodeStep call yields a VARIABLE 1..spec_k+1 tokens
        per running slot — the draft proposes, the target verifies all
        positions in one multi-token attention call, acceptance is
        traced bookkeeping. The scheduler sees only the yield: page
        lookahead covers the worst case (k+1 tokens, capped per slot
        by the request's remaining budget and the engine window), and
        each slot's `caps` bound keeps acceptance from outrunning its
        reserved pages. Spec health lands on the metrics registry
        (serving.spec.accept_rate / .tokens_per_dispatch) and on the
        per-request decode_burst spans (proposed vs accepted)."""
        sched = self.scheduler
        order = sorted(sched.decode_slots(),
                       key=lambda s: sched._key(sched.running[s]))
        kk = self.spec_k
        live, ahead = [], {}
        for slot in order:
            h = sched.running.get(slot)
            if h is None or h.state is not RequestState.RUNNING:
                continue   # preempted as a victim earlier in this loop
            remaining = h.request.max_new_tokens - len(h.output_tokens)
            a = max(1, min(kk + 1, remaining,
                           self.max_len - sched._context_len(h)))
            if sched.ensure_token_capacity(slot, lookahead=a):
                live.append(slot)
                ahead[slot] = a
        live = [s for s in live
                if sched.running.get(s) is not None
                and sched.running[s].state is RequestState.RUNNING]
        if not live:
            return False
        faults.maybe_delay("serving.decode.straggler", engine=self.name)
        # epoch fence (ISSUE 19): see _run_prefill_chunk
        epochs = {s: sched.running[s]._epoch for s in live}
        # per-slot acceptance cap = context + approved lookahead; non-
        # participating slots cap at their current length (zero yield)
        caps = np.array(self.cache._host("seq_lens"), np.int32)
        for slot in live:
            caps[slot] = (sched._context_len(sched.running[slot])
                          + ahead[slot])
        dspans = {slot: self.tracer.begin(
            "decode_burst", parent=sched.running[slot]._span,
            slot=slot, k=kk + 1, batch=len(live), spec=True)
            for slot in live}
        sspans = {}
        emitted = dict.fromkeys(live, 0)
        accepted = dict.fromkeys(live, 0)
        try:
            out, counts, _logits, buffers, meta = self.spec_step(
                self._param_data(), self._buffers, self._meta(),
                self._draft_param_data(), self._tokens, self._seeds,
                caps)
            self._commit(buffers, meta)
            # ONE host sync for the whole dispatch: tokens + yields
            toks = np.asarray(out)
            counts_h = np.asarray(counts)
            self.metrics.decode_steps += 1
            # `proposed` counts only cap-USABLE proposals (ahead-1, not
            # spec_k): a request's last dispatch may have room for one
            # more token, and charging the full k would read as
            # rejection — the accept-rate gauge must measure draft
            # quality, not end-of-request clamping
            usable = {slot: max(ahead[slot] - 1, 0) for slot in live}
            for slot in live:
                c = int(counts_h[slot])
                self.metrics.spec_dispatches += 1
                self.metrics.spec_proposed += usable[slot]
                accepted[slot] = max(c - 1, 0)
                self.metrics.spec_accepted += accepted[slot]
            # span-attributed yield: the burst span covers the sync
            for slot, sp in dspans.items():
                self.tracer.end(sp, proposed=usable[slot],
                                accepted=accepted[slot],
                                yielded=int(counts_h[slot]))
            sspans = {slot: self.tracer.begin(
                "stream_deliver", parent=sched.running[slot]._span)
                for slot in live if sched.running.get(slot) is not None}
            for slot in live:
                handle = sched.running.get(slot)
                for t in range(int(counts_h[slot])):
                    if (handle is None or handle.state
                            is not RequestState.RUNNING
                            or handle._epoch != epochs[slot]):
                        break   # retired earlier in this dispatch
                    token = int(toks[slot, t])
                    self._tokens[slot] = token
                    emitted[slot] += 1
                    self.metrics.spec_emitted += 1
                    self._emit(handle, token)
        finally:
            for sp in dspans.values():
                self.tracer.end(sp, error=True)
            for slot, sp in sspans.items():
                self.tracer.end(sp, tokens=emitted[slot])
            self._flush_retired()
        return True

    def _emit(self, handle: RequestHandle, token: int):
        now = self.clock()
        handle._push_token(token, now)
        self.metrics.on_token()
        req = handle.request
        if (req.eos_token_id is not None
                and token == req.eos_token_id):
            self.scheduler.retire(handle.slot, FinishReason.EOS, now)
            self._retired_this_call.append(handle)
        elif len(handle.output_tokens) >= req.max_new_tokens:
            self.scheduler.retire(handle.slot, FinishReason.LENGTH, now)
            self._retired_this_call.append(handle)

    def _flush_retired(self):
        """Close the trace of every request retired by the call that
        just finished (deferred past the stream spans so children never
        end after their root) and run the tail-exemplar check."""
        for h in self._retired_this_call:
            root = h._span
            if root is None:
                continue
            self.tracer.end(h._span_queue)      # defensive: never open
            h._span_queue = None
            self.tracer.end(
                root,
                finish=(h.finish_reason.value if h.finish_reason
                        else None),
                tokens=len(h.output_tokens),
                preemptions=h.preemptions,
                ttft_ms=(round(h.ttft * 1e3, 3)
                         if h.ttft is not None else None))
            self._maybe_exemplar(h, root)
            h._span = None
        self._retired_this_call.clear()

    def _exemplar_thresholds(self):
        """(ttft_thr, itl_thr) at `exemplar_quantile`, refreshed every
        few retirements — percentile selection sorts the ring window,
        which must not run on every retire."""
        m = self.metrics
        if m.finished >= self._exemplar_refresh_at:
            q = self.exemplar_quantile
            n = self.exemplar_min_samples
            self._exemplar_thr = (
                m.ttft_s.percentile(q) if m.ttft_s.count >= n else None,
                m.itl_s.percentile(q) if m.itl_s.count >= n else None)
            self._exemplar_refresh_at = m.finished + max(
                1, self.exemplar_min_samples // 4)
        return self._exemplar_thr

    def _maybe_exemplar(self, handle: RequestHandle, root):
        """Tail-latency forensics: keep the full span tree of a request
        whose TTFT or worst inter-token gap lands beyond the configured
        percentile of the live distribution (threshold selection needs
        `exemplar_min_samples` observations first — early traffic must
        not all read as slow)."""
        q = self.exemplar_quantile
        ttft = handle.ttft
        itls = handle.inter_token_latencies
        why = []
        ttft_thr, itl_thr = self._exemplar_thresholds()
        if ttft is not None and ttft_thr is not None \
                and ttft > ttft_thr:
            why.append(f"ttft>p{q:g}")
        if itls and itl_thr is not None and max(itls) > itl_thr:
            why.append(f"itl>p{q:g}")
        if handle.preemptions and why:
            why.append("preempted")
        if why:
            self.tracer.add_exemplar(
                root, ",".join(why), rid=handle.request.rid,
                ttft_s=None if ttft is None else round(ttft, 6),
                max_itl_s=round(max(itls), 6) if itls else None,
                preemptions=handle.preemptions)

    def slow_requests(self) -> list:
        """Tail exemplars: full span trees of the slowest requests
        (TTFT / inter-token outliers past `exemplar_quantile`), oldest
        first — each entry {reason, rid, ttft_s, max_itl_s, trace}."""
        return self.tracer.exemplars()

    def request_trace(self, rid):
        """The completed root Span of request ``rid`` (None if it fell
        off the trace ring) — the per-request forensics lookup."""
        return self.tracer.find_trace(f"req{int(rid)}")

    def declare_slo(self, name, metric, threshold_s, target=0.99,
                    window_s=60.0):
        """Declare a serving objective, e.g. ("ttft", "ttft_s", 0.25):
        at least `target` of requests get `metric` <= `threshold_s`
        over a rolling `window_s` window. Burn-rate/breach gauges land
        on this engine's registry (`metrics_text()` scrapes them);
        `slo_status()` returns the live snapshot."""
        if metric not in ("ttft_s", "itl_s"):
            raise ValueError(
                f"unknown SLO metric {metric!r}: the serving engine "
                "feeds 'ttft_s' and 'itl_s'")
        return self.slo.declare(name, metric, threshold_s,
                                target=target, window_s=window_s)

    def slo_status(self) -> dict:
        return self.slo.snapshot()

    # -- memory observability (ISSUE 14) ----------------------------------
    def _mem_owners(self):
        # shard-backed params (a sharded-storage train step sharing
        # this model) are skipped: reading them would GATHER on scrape,
        # and the owning step already claims the shards
        return {"params": [p._data for p in self._params
                           if not getattr(type(p), "_shard_backed",
                                          False)]}

    def _pool_stats_cached(self, ttl_s=0.2):
        """One `pool_stats()` walk shared by the four gauges of a
        single registry scrape (the walk sorts the free list — paying
        it per gauge would quadruple scrape cost for identical data).
        The tiny TTL only coalesces gauges read back-to-back; the
        serve loop never reads it. Wall-clock TTL on purpose — the
        injectable `self.clock` may be frozen in tests."""
        now = time.monotonic()
        cached = self._pool_stats_memo
        if cached is None or now - cached[0] > ttl_s:
            cached = (now, self.cache.pool_stats())
            self._pool_stats_memo = cached
        return cached[1]

    def _register_mem_gauges(self):
        """Page-pool occupancy/fragmentation as LAZY gauges on this
        engine's registry: a scrape pays the O(pool) walk (once — see
        `_pool_stats_cached`), the serve loop never does. Bound
        through ``self`` so `_recover`'s cache swap stays covered."""
        self._pool_stats_memo = None
        reg = self.metrics.registry
        reg.gauge("serving.kv.free_pages").set_fn(
            lambda: self.cache.free_page_count)
        for stat in ("used_pages", "occupancy", "fragmentation",
                     "max_contiguous_free"):
            reg.gauge(f"serving.kv.{stat}").set_fn(
                (lambda s: lambda: self._pool_stats_cached()[s])(stat))

    def memory_profile(self, top_k=8, publish=True):
        """Compiled serve-decode-step memory profile at this engine's
        live geometry (params + KV pools + host metadata) — the AOT
        buffer-assignment view of what one decode burst reserves. See
        `_Step.memory_profile`."""
        if self.spec_step is not None:
            caps = np.asarray(self.cache._host("seq_lens"), np.int32)
            return self.spec_step.memory_profile(
                self._param_data(), self._buffers, self._meta(),
                self._draft_param_data(), self._tokens, self._seeds,
                caps, top_k=top_k, publish=publish)
        return self.decode_step.memory_profile(
            self._param_data(), self._buffers, self._meta(),
            self._tokens, self._seeds, top_k=top_k, publish=publish)

    def memz(self) -> dict:
        """The /memz debug-endpoint body for this engine: process-wide
        live-buffer attribution + published compiled profiles + THIS
        engine's page-pool stats."""
        from ..observability.memory import memz_payload

        out = memz_payload()
        out["pool"] = self.cache.pool_stats()
        return out

    def start_debug_server(self, port=0) -> int:
        """Opt-in loopback debug/scrape server for THIS engine:
        /metrics (this engine's registry as Prometheus text, ==
        `metrics_text()`), /healthz, /tracez (recent traces + tail
        exemplars), /sloz (burn rates), /flightz (process flight
        recorder), /memz (live-buffer attribution + page-pool stats).
        Returns the bound port."""
        if self._debug_server is not None:
            return self._debug_server.port
        from ..observability import DebugServer

        self._debug_server = DebugServer(
            registry=lambda: self.metrics.registry,
            tracer=lambda: self.tracer,
            extra={"sloz": lambda: self.slo.snapshot(),
                   "memz": self.memz},
            port=port)
        return self._debug_server.start()

    def stop_debug_server(self):
        if self._debug_server is not None:
            self._debug_server.stop()
            self._debug_server = None

    def _recover(self, exc=None):
        """A failed step leaves donated buffers dead — rebuild the cache
        pristine and requeue every resident request for resume. The
        flight recorder keeps the black box of what led here (ISSUE
        12); the dump itself happens at the raise site/excepthook."""
        from ..observability import recorder

        recorder().note("serving_recover",
                        running=len(self.scheduler.running),
                        waiting=len(self.scheduler.waiting),
                        leases_dropped=len(self._leased),
                        error=repr(exc) if exc is not None else None)
        self.scheduler.abort_all()
        # open hand-off leases die with the pools; adopters that
        # already hold the blob are unaffected (the blob is
        # self-contained), ones that come back for a re-export fall
        # back to resume-by-re-prefill
        self._leased.clear()
        self.cache = self._make_cache()
        self.scheduler.cache = self.cache
        self._buffers, _ = _split_state(
            "paged", _tree_data(self.cache.state()))
        if self.draft_cache is not None:
            self.draft_cache = self._make_draft_cache()
            self._buffers["draft"], _ = _split_state(
                "paged", _tree_data(self.draft_cache.state()))

    def _retry_after_recover(self, exc) -> bool:
        """Bounded-retry policy after a failed step (ISSUE 19): absorb
        up to `recover_retries` consecutive failures with exponential
        backoff — `_recover` already requeued every resident request,
        so the next step resumes them — then escalate by re-raising;
        under a fleet, the watchdog turns that into replica-dead."""
        if isinstance(exc, (KeyboardInterrupt, SystemExit)):
            return False
        self._recover_streak += 1
        if (self.recover_retries <= 0
                or self._recover_streak > self.recover_retries):
            return False
        delay = self.recover_backoff_s * 2 ** (self._recover_streak - 1)
        from ..observability import recorder

        recorder().note("serving_recover_retry",
                        engine=self.name, attempt=self._recover_streak,
                        retries=self.recover_retries,
                        backoff_s=round(delay, 4), error=repr(exc))
        if delay > 0:
            time.sleep(delay)
        return True

    # -- introspection ----------------------------------------------------
    def leak_check(self) -> dict:
        """Post-drain invariant surface: every page and slot is back in
        the pool once no request is resident."""
        c = self.cache
        return {
            "free_pages": c.free_page_count,
            "total_pages": self.num_pages - 1,   # page 0 is trash
            "free_slots": c.free_slot_count,
            "total_slots": self.max_slots,
            "resident_slot_pages": len(c._slot_pages),
            "leased_slots": len(self._leased),
        }
