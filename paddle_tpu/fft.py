"""Discrete Fourier transforms (paddle.fft parity: reference
python/paddle/fft.py — fft/ifft/rfft/irfft/hfft/ihfft families, 1-D/2-D/N-D,
plus helper fftfreq/rfftfreq/fftshift/ifftshift).

TPU-first: each transform is one jnp.fft call dispatched through the op
layer, so it jits, differentiates (jax defines fft VJPs) and shards like any
other op. Norm semantics follow numpy/paddle: "backward" (default),
"ortho", "forward".
"""
from __future__ import annotations

import jax.numpy as jnp

from .framework.tensor import Tensor
from .ops._dispatch import unary, ensure_tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    if norm is None:
        return "backward"
    if norm not in ("backward", "ortho", "forward"):
        raise ValueError(
            f"norm should be 'backward', 'ortho' or 'forward', got {norm!r}")
    return norm


def _make1(jnp_fn, opname):
    def f(x, n=None, axis=-1, norm="backward", name=None):
        nm = _norm(norm)
        return unary(lambda a: jnp_fn(a, n=n, axis=axis, norm=nm),
                     ensure_tensor(x), opname)

    f.__name__ = opname
    return f


def _make2(jnp_fn, opname):
    def f(x, s=None, axes=(-2, -1), norm="backward", name=None):
        nm = _norm(norm)
        return unary(lambda a: jnp_fn(a, s=s, axes=tuple(axes), norm=nm),
                     ensure_tensor(x), opname)

    f.__name__ = opname
    return f


def _maken(jnp_fn, opname):
    def f(x, s=None, axes=None, norm="backward", name=None):
        nm = _norm(norm)
        ax = tuple(axes) if axes is not None else None
        return unary(lambda a: jnp_fn(a, s=s, axes=ax, norm=nm),
                     ensure_tensor(x), opname)

    f.__name__ = opname
    return f


fft = _make1(jnp.fft.fft, "fft")
ifft = _make1(jnp.fft.ifft, "ifft")
rfft = _make1(jnp.fft.rfft, "rfft")
irfft = _make1(jnp.fft.irfft, "irfft")
hfft = _make1(jnp.fft.hfft, "hfft")
ihfft = _make1(jnp.fft.ihfft, "ihfft")

fft2 = _make2(jnp.fft.fft2, "fft2")
ifft2 = _make2(jnp.fft.ifft2, "ifft2")
rfft2 = _make2(jnp.fft.rfft2, "rfft2")
irfft2 = _make2(lambda a, s=None, axes=(-2, -1), norm="backward":
                jnp.fft.irfftn(a, s=s, axes=axes, norm=norm), "irfft2")

fftn = _maken(jnp.fft.fftn, "fftn")
ifftn = _maken(jnp.fft.ifftn, "ifftn")
rfftn = _maken(jnp.fft.rfftn, "rfftn")
irfftn = _maken(jnp.fft.irfftn, "irfftn")


def _hfft_nd(a, s, axes, norm):
    # hermitian-input FFT over the last axis in `axes` after plain FFTs on
    # the leading ones (reference hfftn/hfft2 semantics: c2r with conjugate
    # symmetry on the final axis)
    axes = tuple(range(a.ndim)) if axes is None else tuple(axes)
    lead, last = axes[:-1], axes[-1]
    if lead:
        a = jnp.fft.fftn(a, s=None if s is None else s[:-1], axes=lead,
                         norm=norm)
    return jnp.fft.hfft(a, n=None if s is None else s[-1], axis=last,
                        norm=norm)


def _ihfft_nd(a, s, axes, norm):
    axes = tuple(range(a.ndim)) if axes is None else tuple(axes)
    lead, last = axes[:-1], axes[-1]
    out = jnp.fft.ihfft(a, n=None if s is None else s[-1], axis=last,
                        norm=norm)
    if lead:
        out = jnp.fft.ifftn(out, s=None if s is None else s[:-1], axes=lead,
                            norm=norm)
    return out


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return unary(lambda a: _hfft_nd(a, s, tuple(axes), _norm(norm)),
                 ensure_tensor(x), "hfft2")


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return unary(lambda a: _ihfft_nd(a, s, tuple(axes), _norm(norm)),
                 ensure_tensor(x), "ihfft2")


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    return unary(lambda a: _hfft_nd(a, s, axes, _norm(norm)),
                 ensure_tensor(x), "hfftn")


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    return unary(lambda a: _ihfft_nd(a, s, axes, _norm(norm)),
                 ensure_tensor(x), "ihfftn")


def fftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.fftfreq(int(n), d=float(d))
    if dtype is not None:
        from .framework.dtype import to_jax_dtype

        out = out.astype(to_jax_dtype(dtype))
    return Tensor._wrap(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(int(n), d=float(d))
    if dtype is not None:
        from .framework.dtype import to_jax_dtype

        out = out.astype(to_jax_dtype(dtype))
    return Tensor._wrap(out)


def fftshift(x, axes=None, name=None):
    ax = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return unary(lambda a: jnp.fft.fftshift(a, axes=ax), ensure_tensor(x),
                 "fftshift")


def ifftshift(x, axes=None, name=None):
    ax = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return unary(lambda a: jnp.fft.ifftshift(a, axes=ax), ensure_tensor(x),
                 "ifftshift")
