"""paddle.callbacks parity (python/paddle/callbacks.py): re-exports the
hapi callback set used by paddle.Model.fit."""
from .hapi.callbacks import (  # noqa: F401
    Callback, CallbackList, EarlyStopping, LRScheduler, ModelCheckpoint,
    ProgBarLogger,
)

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler"]
