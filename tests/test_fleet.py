"""Disaggregated multi-replica serving fleet tests (ISSUE 18).

KV hand-off blob invariants (bit-parity round-trips on fp32 and int8
pools, pool conservation, no stale page-table aliasing, geometry/quant
validation before allocation, warmable migration buckets), rendezvous
+ P2C routing properties, merged-sample fleet percentiles vs the
averaged-p99 fallacy, deterministic per-request traffic seeding (the
1-vs-N replay property), host-ring LRU byte-cap behavior, and
abort/drain hygiene: zero leaked pages/slots/spans across fleet churn.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=96,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _prompts(n, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 64, (int(rng.integers(4, 28)),))
            .astype(np.int32) for i in range(n)]


# ---------------------------------------------------------------------------
# export_slot / import_slot blob invariants (kv_cache)
# ---------------------------------------------------------------------------

class TestHandoffBlob:
    def _cache(self, quant=None, num_pages=17, max_slots=4,
               pages_per_seq=6, page_size=8):
        from paddle_tpu.inference.kv_cache import PagedKVCache

        return PagedKVCache(num_layers=2, num_kv_heads=2, head_dim=4,
                            num_pages=num_pages, page_size=page_size,
                            max_slots=max_slots,
                            pages_per_seq=pages_per_seq, quant=quant)

    def _fill(self, cache, seed):
        """Distinct random content in every pool element so a gather
        from the wrong page can never pass a bit-compare."""
        rng = np.random.default_rng(seed)

        def rnd(a):
            if a.dtype == jnp.int8:
                return jnp.asarray(rng.integers(
                    -127, 128, a.shape).astype(np.int8))
            return jnp.asarray(
                rng.standard_normal(a.shape).astype(a.dtype))

        cache.k_layers = [rnd(a) for a in cache.k_layers]
        cache.v_layers = [rnd(a) for a in cache.v_layers]
        if cache.quant == "int8":
            cache.k_scales = [rnd(a) for a in cache.k_scales]
            cache.v_scales = [rnd(a) for a in cache.v_scales]

    @pytest.mark.parametrize("quant", [None, "int8"])
    def test_round_trip_bit_parity(self, quant):
        src = self._cache(quant=quant)
        self._fill(src, seed=1)
        slot = src.allocate(21)            # 3 pages
        src._host("seq_lens")[slot] = 21
        blob = src.export_slot(slot)
        assert blob["seq_len"] == 21 and blob["pages"] == 3

        dst = self._cache(quant=quant)
        slot2 = dst.import_slot(blob)
        blob2 = dst.export_slot(slot2)
        for key in (("k", "v") if quant is None else
                    ("k", "v", "k_scales", "v_scales")):
            for a, b in zip(blob[key], blob2[key]):
                assert a.dtype == b.dtype
                np.testing.assert_array_equal(a, b)

    def test_pool_conservation_through_moves(self):
        c = self._cache()
        self._fill(c, seed=2)
        slots = [c.allocate(17) for _ in range(3)]
        for s in slots:
            c._host("seq_lens")[s] = 17
        blobs = [c.export_slot(s) for s in slots]
        for s in slots:
            c.free(s)
        landed = [c.import_slot(b) for b in blobs]
        ps = c.pool_stats()
        assert ps["used_pages"] + ps["free_pages"] == ps["total_pages"]
        assert ps["used_pages"] == 3 * 3      # 3 slots x 3 pages
        for s in landed:
            c.free(s)
        ps = c.pool_stats()
        assert ps["used_pages"] == 0 and ps["slot_pages"] == {}
        assert ps["free_pages"] == ps["total_pages"]

    def test_import_never_aliases_neighbour_pages(self):
        """Landing a blob must not disturb a resident neighbour: its
        page-table row and its re-exported bits stay identical."""
        c = self._cache()
        self._fill(c, seed=3)
        resident = c.allocate(30)          # 4 pages
        c._host("seq_lens")[resident] = 30
        before_tbl = c.page_tables[resident].copy()
        before = c.export_slot(resident)

        donor = self._cache()
        self._fill(donor, seed=4)
        d = donor.allocate(21)
        donor._host("seq_lens")[d] = 21
        c.import_slot(donor.export_slot(d))

        np.testing.assert_array_equal(c.page_tables[resident],
                                      before_tbl)
        after = c.export_slot(resident)
        for key in ("k", "v"):
            for a, b in zip(before[key], after[key]):
                np.testing.assert_array_equal(a, b)

    def test_geometry_and_quant_mismatch_raise_before_alloc(self):
        src = self._cache()
        self._fill(src, seed=5)
        s = src.allocate(21)
        src._host("seq_lens")[s] = 21
        blob = src.export_slot(s)

        other_geom = self._cache(page_size=4, num_pages=33,
                                 pages_per_seq=12)
        with pytest.raises(ValueError):
            other_geom.import_slot(blob)
        other_quant = self._cache(quant="int8")
        with pytest.raises(ValueError):
            other_quant.import_slot(blob)
        # rejected imports allocated nothing
        for c in (other_geom, other_quant):
            ps = c.pool_stats()
            assert ps["used_pages"] == 0 and ps["slot_pages"] == {}

    def test_migration_buckets_cover_reachable_widths(self):
        """Every page count one slot can hold maps to a bucket the
        warmup can actually exercise (an allocatable seq_len exists) —
        the property that keeps hand-offs compile-free mid-stream."""
        for kw in (dict(), dict(num_pages=225, pages_per_seq=28),
                   dict(num_pages=9, pages_per_seq=8)):
            c = self._cache(**kw)
            buckets = c.migration_buckets()
            cap = min(c.num_pages - 1, c.pages_per_seq)
            assert buckets[-1] == cap
            for n in range(1, cap + 1):
                w = c.migration_bucket(n)
                assert w >= n and w in buckets, (n, w, buckets)
            for w in buckets:
                lo = w // 2
                n = next((n for n in range(w, lo, -1)
                          if c.can_allocate((n - 1) * c.page_size + 1)),
                         None)
                assert n is not None, (w, buckets)


# ---------------------------------------------------------------------------
# routing: rendezvous affinity + P2C
# ---------------------------------------------------------------------------

class TestReplicaRouter:
    def test_affinity_remaps_only_lost_replicas_sessions(self):
        from paddle_tpu.serving.router import ReplicaRouter

        names = [f"d{i}" for i in range(4)]
        r = ReplicaRouter(names, seed=0)
        sessions = [f"s{i}" for i in range(200)]
        before = {s: r.pick(lambda _: 0, session=s) for s in sessions}
        r.remove("d2")
        after = {s: r.pick(lambda _: 0, session=s) for s in sessions}
        moved = [s for s in sessions if before[s] != after[s]]
        # EXACTLY the sessions that lived on the removed replica move
        assert set(moved) == {s for s in sessions
                              if before[s] == "d2"}
        # and that is ~1/N of them (loose statistical band)
        assert 0.10 <= len(moved) / len(sessions) <= 0.42

        # adding a replica only pulls sessions ONTO the newcomer
        r2 = ReplicaRouter(names, seed=0)
        r2.add("d4")
        grown = {s: r2.pick(lambda _: 0, session=s) for s in sessions}
        for s in sessions:
            if grown[s] != before[s]:
                assert grown[s] == "d4", (s, before[s], grown[s])

    def test_p2c_prefers_shorter_queue(self):
        from paddle_tpu.serving.router import ReplicaRouter

        r = ReplicaRouter(["a", "b"], seed=1)
        load = {"a": 10, "b": 1}
        for _ in range(50):
            assert r.pick(lambda n: load[n]) == "b"
        # and under many replicas the hottest one is rarely picked
        r = ReplicaRouter(["a", "b", "c", "d"], seed=2)
        load = {"a": 100, "b": 1, "c": 1, "d": 1}
        picks = [r.pick(lambda n: load[n]) for _ in range(200)]
        assert picks.count("a") == 0

    def test_p2c_seeded_replay(self):
        from paddle_tpu.serving.router import ReplicaRouter

        load = dict(a=3, b=1, c=2, d=5)
        r1 = ReplicaRouter(list(load), seed=7)
        r2 = ReplicaRouter(list(load), seed=7)
        assert [r1.pick(load.get) for _ in range(64)] == \
            [r2.pick(load.get) for _ in range(64)]


# ---------------------------------------------------------------------------
# fleet percentiles: merged samples, never averaged p99s
# ---------------------------------------------------------------------------

class TestMergedPercentiles:
    def _hist(self, name, samples, window=4096):
        from paddle_tpu.observability import MetricsRegistry

        h = MetricsRegistry().histogram(name, window=window)
        h.extend(samples)
        return h

    def test_slow_minority_tail_survives_merge(self):
        """One slow replica's tail must dominate the fleet p99 even
        when a fast replica has 99x the traffic — averaging per-replica
        p99s would halve it."""
        from paddle_tpu.observability import merge_histograms

        fast = self._hist("fast", [0.001] * 990)
        slow = self._hist("slow", [1.0] * 30)
        merged = merge_histograms([fast, slow], name="fleet")
        avg_of_p99 = (fast.percentile(99) + slow.percentile(99)) / 2
        assert merged.percentile(99) == pytest.approx(1.0)
        assert avg_of_p99 == pytest.approx(0.5005, rel=1e-2)

    def test_tiny_outlier_replica_does_not_inflate(self):
        """Opposite skew: 10 slow samples in 10_000 are NOT the fleet
        p99, but averaging per-replica p99s says 0.5s."""
        from paddle_tpu.observability import merge_histograms

        fast = self._hist("fast", [0.001] * 9990, window=16384)
        slow = self._hist("slow", [1.0] * 10)
        merged = merge_histograms([fast, slow], name="fleet",
                                  window=16384)
        assert merged.percentile(99) == pytest.approx(0.001)
        assert merged.percentile(50) == pytest.approx(0.001)

    def test_merge_folds_lifetime_counts(self):
        from paddle_tpu.observability import merge_histograms

        a = self._hist("a", [1.0, 2.0, 3.0])
        b = self._hist("b", [4.0])
        m = merge_histograms([a, b])
        snap = m.snapshot()
        assert snap["count"] == 4
        assert snap["sum"] == pytest.approx(10.0)


# ---------------------------------------------------------------------------
# traffic: deterministic per-request identity
# ---------------------------------------------------------------------------

class TestTrafficSeeding:
    def test_replay_is_bit_identical(self):
        from paddle_tpu.serving.traffic import poisson_traffic

        a = poisson_traffic(32, 100.0, 64, seed=5, sessions=4)
        b = poisson_traffic(32, 100.0, 64, seed=5, sessions=4)
        for x, y in zip(a, b):
            assert x.arrival_s == y.arrival_s
            assert x.seed == y.seed and x.session == y.session
            np.testing.assert_array_equal(x.prompt, y.prompt)
        # per-request seeds are distinct (streams never collide)
        assert len({r.seed for r in a}) == len(a)

    def test_identity_stream_never_shifts_load_draws(self):
        """Seeds/sessions come from a separate generator: toggling
        sessions must not move arrivals, prompts or budgets (the lanes
        tuned on the pre-fleet traffic stay byte-identical)."""
        from paddle_tpu.serving.traffic import poisson_traffic

        plain = poisson_traffic(32, 100.0, 64, seed=5)
        tagged = poisson_traffic(32, 100.0, 64, seed=5, sessions=8)
        for x, y in zip(plain, tagged):
            assert x.arrival_s == y.arrival_s
            assert x.max_new_tokens == y.max_new_tokens
            np.testing.assert_array_equal(x.prompt, y.prompt)
        assert plain[0].session is None
        assert all(t.session is not None for t in tagged)

    def test_one_vs_two_replica_streams_identical(self, model):
        """The property the seeding exists for: the SAME workload
        replayed against 1 and 2 replicas yields bit-identical tokens
        per request, sampled, whatever the router did."""
        from paddle_tpu.serving import FleetRouter
        from paddle_tpu.serving.traffic import poisson_traffic

        kw = dict(max_slots=4, max_len=64, page_size=8, chunk_size=16,
                  do_sample=True, temperature=0.9, top_k=8)
        traffic = poisson_traffic(10, 1e9, 64, prompt_lens=(4, 20),
                                  out_lens=(4, 12), seed=13)

        def serve(n):
            fleet = FleetRouter(model=model, decode_replicas=n,
                                engine_kw=kw, seed=3)
            hs = [fleet.submit(t.prompt, t.max_new_tokens, seed=t.seed,
                               session=t.session) for t in traffic]
            fleet.run()
            lk = fleet.leak_check()
            assert lk["clean"], lk
            return [list(h.output_tokens) for h in hs]

        assert serve(1) == serve(2)


# ---------------------------------------------------------------------------
# host ring: byte-capped LRU parking lot
# ---------------------------------------------------------------------------

class TestHostKVRing:
    def _blob(self, nbytes):
        return {"nbytes": int(nbytes)}

    def test_lru_drop_on_overflow(self):
        from paddle_tpu.serving import HostKVRing

        ring = HostKVRing(capacity_mb=1.0)     # 1 MiB
        kb512 = 512 * 1024
        ring.put(1, self._blob(kb512), 7)
        ring.put(2, self._blob(kb512), 8)
        assert len(ring) == 2 and ring.bytes == 2 * kb512
        ring.put(3, self._blob(kb512), 9)      # overflows: rid 1 drops
        stats = ring.stats()
        assert stats["drops"] == 1 and len(ring) == 2
        assert ring.take(1) is None
        blob, tok = ring.take(3)
        assert tok == 9
        assert ring.bytes == kb512

    def test_put_same_rid_replaces_not_double_counts(self):
        from paddle_tpu.serving import HostKVRing

        ring = HostKVRing(capacity_mb=1.0)
        ring.put(1, self._blob(1000), 1)
        ring.put(1, self._blob(2000), 2)
        assert ring.bytes == 2000 and len(ring) == 1
        blob, tok = ring.take(1)
        assert blob["nbytes"] == 2000 and tok == 2
        assert ring.bytes == 0

    def test_oversized_blob_never_wedges(self):
        from paddle_tpu.serving import HostKVRing

        ring = HostKVRing(capacity_mb=0.001)    # ~1 KB
        ring.put(1, self._blob(10_000), 1)      # larger than the cap
        assert len(ring) == 0 and ring.bytes == 0
        assert ring.stats()["drops"] == 1


# ---------------------------------------------------------------------------
# abort/drain hygiene across fleet churn
# ---------------------------------------------------------------------------

class TestFleetChurnHygiene:
    def test_abort_then_drain_no_orphans_no_leaks(self, model):
        from paddle_tpu.serving import FleetRouter

        kw = dict(max_slots=3, max_len=64, page_size=8, chunk_size=8)
        fleet = FleetRouter(model=model, decode_replicas=2,
                            prefill_replicas=1, engine_kw=kw, seed=5)
        hs = [fleet.submit(p, 8, seed=40 + i)
              for i, p in enumerate(_prompts(6, seed=6))]
        for _ in range(6):
            fleet.step()
        # mid-flight abort on every replica: residents re-queue, then
        # the drain must close every span and return every page
        for r in fleet._replicas:
            r.engine.scheduler.abort_all()
        fleet.run()
        assert all(h.done for h in hs)
        lk = fleet.leak_check()
        assert lk["clean"], lk
        for name, rep in lk["replicas"].items():
            assert rep["open_spans"] == 0, (name, rep)
            assert rep["orphan_spans"] == 0, (name, rep)
            assert rep["pending_imports"] == 0, (name, rep)

    def test_disagg_handoff_leaves_prefill_clean(self, model):
        from paddle_tpu.serving import FleetRouter

        kw = dict(max_slots=3, max_len=64, page_size=8, chunk_size=8)
        fleet = FleetRouter(model=model, decode_replicas=1,
                            prefill_replicas=1, engine_kw=kw)
        hs = [fleet.submit(p, 6, seed=i)
              for i, p in enumerate(_prompts(5, seed=9))]
        fleet.run()
        assert all(h.done for h in hs)
        snap = fleet.metrics_snapshot()
        assert snap["replicas"]["d0"]["prefill_chunks"] == 0
        assert snap["replicas"]["p0"]["prefill_chunks"] > 0
        lk = fleet.leak_check()
        assert lk["clean"], lk
