"""paddle.distributed.fleet parity (reference python/paddle/distributed/fleet/).

Strategy layers over the collective core: topology/HCG, distributed_model
wrappers, hybrid optimizer, sharding stages, recompute.
"""
from .recompute import recompute, recompute_sequential  # noqa: F401
