"""HLO-derived per-step accounting (ISSUE 12 tentpole part 4).

The analytic 6N-flops MFU the bench has always reported assumes the
model math; ``compiled.cost_analysis()`` asks the COMPILER what the
program actually executes. `summarize_compiled` pulls flops /
bytes-accessed per step from the compiled executable and — via
tools/hlo_overlap.py's per-axis collective census extended with payload
bytes — the communication bytes per step per mesh axis, then publishes
everything into the metrics registry (``hlo.*`` gauges) so BENCH
records and Prometheus scrapes carry both MFU flavors and the comm
budget of every step program.
"""
from __future__ import annotations

import os

from .registry import registry as _registry

__all__ = ["load_hlo_overlap", "summarize_compiled", "cost_analysis_of"]


def load_hlo_overlap():
    """tools/hlo_overlap.py by path (tools/ lives at the repo root,
    next to the paddle_tpu package — same loader the linalg probe and
    the sharded-scan selftest use)."""
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(root, "tools", "hlo_overlap.py")
    if os.path.exists(path):
        spec = importlib.util.spec_from_file_location("hlo_overlap", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    import tools.hlo_overlap as mod  # namespace-package fallback

    return mod


def _cost_dict(compiled):
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def summarize_compiled(compiled, axis_degrees=None, publish=True,
                       prefix="hlo") -> dict:
    """Per-step accounting of one compiled XLA executable.

    Returns {"flops_per_step", "bytes_accessed_per_step",
    "collectives": {counts, per_axis_counts?, per_axis_bytes?,
    total_comm_bytes}}; numbers are PER DEVICE (cost_analysis and the
    per-device HLO module both are). ``axis_degrees`` (ordered
    {axis: degree}, mesh order) labels the comm traffic per mesh axis.
    Publishes ``<prefix>.*`` gauges into the global registry unless
    publish=False. Never raises — fields missing on a backend are
    reported as None."""
    out = {"flops_per_step": None, "bytes_accessed_per_step": None,
           "collectives": None}
    try:
        ca = _cost_dict(compiled)
        if "flops" in ca:
            out["flops_per_step"] = float(ca["flops"])
        if "bytes accessed" in ca:
            out["bytes_accessed_per_step"] = float(ca["bytes accessed"])
    except Exception as e:
        out["cost_analysis_error"] = f"{type(e).__name__}: {e}"[:200]
    try:
        text = compiled.as_text()
        mod = load_hlo_overlap()
        verdict = mod.analyze(text, axis_degrees=axis_degrees)
        coll = {"counts": verdict.get("counts", {}),
                "total_comm_bytes": verdict.get("total_comm_bytes", 0)}
        for k in ("per_axis_counts", "per_axis_bytes"):
            if k in verdict:
                coll[k] = verdict[k]
        out["collectives"] = coll
    except Exception as e:
        out["collectives_error"] = f"{type(e).__name__}: {e}"[:200]
    if publish:
        try:
            reg = _registry()
            if out["flops_per_step"] is not None:
                reg.gauge(f"{prefix}.flops_per_step").set(
                    out["flops_per_step"])
            if out["bytes_accessed_per_step"] is not None:
                reg.gauge(f"{prefix}.bytes_accessed_per_step").set(
                    out["bytes_accessed_per_step"])
            coll = out.get("collectives") or {}
            reg.gauge(f"{prefix}.comm_bytes_per_step").set(
                coll.get("total_comm_bytes", 0))
            for axis, nbytes in (coll.get("per_axis_bytes")
                                 or {}).items():
                reg.gauge(
                    f"{prefix}.comm_bytes_per_step.{axis}").set(nbytes)
        except Exception:
            pass
    return out


def cost_analysis_of(jitted, *args, axis_degrees=None, prefix="hlo",
                     **kw) -> dict:
    """AOT-lower + compile ``jitted`` for ``args`` and summarize. With
    the persistent XLA compile cache warm (the jit call already
    compiled the same program) this is cheap; a cold compile is the
    price of the receipt."""
    compiled = jitted.lower(*args, **kw).compile()
    return summarize_compiled(compiled, axis_degrees=axis_degrees,
                              prefix=prefix)
