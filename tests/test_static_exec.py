"""paddle.static functional surface (VERDICT r4 missing #6): Executor.run
over to_static-captured programs, startup no-op, raising graph APIs."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static


class TestStaticExecutor:
    def test_run_captured_program_dict_fetch(self):
        def body(x, y):
            return {"out": paddle.matmul(x, y),
                    "s": paddle.sum(x)}

        prog = static.Program.from_function(body, feed_list=["x", "y"])
        exe = static.Executor()
        a = np.random.default_rng(0).standard_normal((3, 4)).astype(
            np.float32)
        b = np.random.default_rng(1).standard_normal((4, 2)).astype(
            np.float32)
        out, = exe.run(prog, feed={"x": a, "y": b}, fetch_list=["out"])
        np.testing.assert_allclose(out, a @ b, atol=1e-5)
        both = exe.run(prog, feed={"x": a, "y": b},
                       fetch_list=["s", "out"])
        np.testing.assert_allclose(both[0], a.sum(), rtol=1e-5)

    def test_single_output_and_startup_noop(self):
        prog = static.Program.from_function(
            lambda x: x * 2, feed_list=["x"])
        exe = static.Executor(static.cpu_places(1)[0])
        assert exe.run(static.default_startup_program()) == []
        r, = exe.run(prog, feed={"x": np.ones(3, np.float32)})
        np.testing.assert_allclose(r, [2.0, 2.0, 2.0])

    def test_missing_feed_raises(self):
        prog = static.Program.from_function(
            lambda x: x, feed_list=["x"])
        with pytest.raises(KeyError, match="missing input"):
            static.Executor().run(prog, feed={})

    def test_tensor_if_compiles_inside_program(self):
        """The captured body goes through to_static, so tensor control
        flow stages (the r5 dy2static surface composes here)."""
        def body(x):
            if x.sum() > 0:
                return x * 2
            return x - 1

        prog = static.Program.from_function(body, feed_list=["x"])
        exe = static.Executor()
        r, = exe.run(prog, feed={"x": np.asarray([1.0], np.float32)})
        np.testing.assert_allclose(r, [2.0])
        r2, = exe.run(prog, feed={"x": np.asarray([-1.0], np.float32)})
        np.testing.assert_allclose(r2, [-2.0])

    def test_graph_apis_still_raise(self):
        with pytest.raises(RuntimeError, match="to_static"):
            static.default_main_program().global_block()
