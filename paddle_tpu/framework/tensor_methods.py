"""Tensor method completion (r5): attach every reference tensor-method
name (python/paddle/tensor/__init__.py tensor_method_func) whose
functional form exists in this framework, plus generated in-place
variants and the small set of tensor-only predicates/utilities.

Runs once from paddle_tpu/__init__ AFTER all namespaces exist, so the
binder can resolve names through paddle.*, paddle.linalg.* and
paddle.signal.*.
"""
from __future__ import annotations


def install(paddle):
    import jax.numpy as jnp

    from .tensor import Tensor

    # names the reference patches onto Tensor; resolved through these
    # namespaces in order
    spaces = [paddle, paddle.linalg, paddle.signal, paddle.geometric]

    def resolve(name):
        for sp in spaces:
            fn = getattr(sp, name, None)
            if callable(fn):
                return fn
        return None

    plain = [
        "add_n", "angle", "as_complex", "as_real", "as_strided",
        "atleast_1d", "atleast_2d", "atleast_3d", "bincount",
        "bitwise_left_shift", "bitwise_right_shift", "block_diag",
        "broadcast_shape", "broadcast_tensors", "cdist",
        "cholesky_inverse", "cholesky_solve", "concat", "cond", "conj",
        "copysign", "corrcoef", "cov", "create_parameter",
        "create_tensor", "cummax", "cummin", "cumulative_trapezoid",
        "deg2rad", "diag", "diag_embed", "diagflat", "diagonal",
        "diagonal_scatter", "dsplit", "eig", "eigvals", "eigvalsh",
        "floor_mod", "frexp", "gammainc", "gammaincc", "gammaln", "gcd",
        "histogram", "histogram_bin_edges", "histogramdd",
        "householder_product", "hsplit", "hypot", "i0", "i0e", "i1",
        "i1e", "imag", "index_fill", "index_put", "inverse", "isin",
        "isneginf", "isposinf", "isreal", "istft", "kthvalue", "lcm",
        "ldexp", "logaddexp", "lstsq", "lu", "lu_unpack",
        "masked_scatter", "multi_dot", "multigammaln", "multinomial",
        "multiplex", "nanmedian", "nanquantile", "nextafter",
        "ormqr", "pca_lowrank", "pinv", "polar", "polygamma", "qr", "rad2deg",
        "real", "reduce_as", "renorm", "reverse", "scatter_nd",
        "select_scatter", "sgn", "shard_index", "signbit", "sinc",
        "slice", "slice_scatter", "solve", "stack", "stanh", "stft",
        "strided_slice", "svd_lowrank", "tensor_split", "tensordot",
        "top_p_sampling", "trapezoid", "triangular_solve", "tril",
        "triu", "trunc", "unflatten", "unfold", "unstack", "vander",
        "vsplit",
    ]
    for name in plain:
        if hasattr(Tensor, name):
            continue
        fn = resolve(name)
        if fn is None:
            continue

        def method(self, *a, _fn=fn, **k):
            return _fn(self, *a, **k)

        method.__name__ = name
        setattr(Tensor, name, method)

    # generated in-place variants: run the base op, rebind the buffer
    inplace = [
        "acos_", "acosh_", "addmm_", "asin_", "asinh_", "atan_",
        "atanh_", "bitwise_and_", "bitwise_left_shift_", "bitwise_not_",
        "bitwise_or_", "bitwise_right_shift_", "bitwise_xor_", "cast_",
        "copysign_", "cosh_", "cumprod_", "cumsum_", "digamma_",
        "equal_", "erfinv_", "flatten_", "floor_divide_", "floor_mod_",
        "frac_", "gammainc_", "gammaincc_", "gammaln_", "gcd_",
        "greater_equal_", "greater_than_", "hypot_", "i0_",
        "index_fill_", "index_put_", "lcm_", "ldexp_", "lerp_",
        "less_equal_", "less_than_", "lgamma_", "log10_", "log1p_",
        "log2_", "log_", "logical_and_", "logical_not_", "logical_or_",
        "logical_xor_", "logit_", "masked_scatter_", "mod_",
        "multigammaln_", "nan_to_num_", "not_equal_", "polygamma_",
        "pow_", "put_along_axis_", "remainder_", "renorm_", "sinc_",
        "sinh_", "t_", "tan_", "transpose_", "tril_", "triu_", "trunc_",
        "where_",
    ]
    for name in inplace:
        if hasattr(Tensor, name):
            continue
        base = resolve(name[:-1])
        if base is None:
            continue

        def method(self, *a, _fn=base, **k):
            out = _fn(self, *a, **k)
            self._inplace_from(out)
            return self

        method.__name__ = name
        setattr(Tensor, name, method)

    # --- tensor-only predicates / utilities ----------------------------
    def _rank(self):
        return paddle.to_tensor(int(self.ndim))

    def _numel(self):
        return paddle.to_tensor(int(self.size))

    def _is_empty(self):
        return paddle.to_tensor(self.size == 0)

    def _is_complex(self):
        return jnp.issubdtype(self._data.dtype, jnp.complexfloating)

    def _is_integer(self):
        return jnp.issubdtype(self._data.dtype, jnp.integer)

    def _is_floating_point(self):
        return jnp.issubdtype(self._data.dtype, jnp.floating)

    def _is_tensor(self):
        return True

    def _increment(self, value=1.0):
        self._inplace_from(self + value)
        return self

    def _view(self, shape_or_dtype):
        """reference Tensor.view: reshape when given a shape; when given
        a dtype, reinterpret the SAME bytes with the last dim resized by
        the width ratio (reference view-dtype semantics — a [4] f32
        views as [8] int16 or [2] f64, unlike raw bitcast_convert_type
        which appends/consumes a trailing dim)."""
        if isinstance(shape_or_dtype, (list, tuple)):
            return self.reshape(list(shape_or_dtype))
        from .dtype import to_jax_dtype

        from ..ops._dispatch import unary
        import jax

        dt = to_jax_dtype(shape_or_dtype)

        def f(v):
            src_bits = v.dtype.itemsize * 8
            dst_bits = jnp.dtype(dt).itemsize * 8
            if src_bits == dst_bits:
                return jax.lax.bitcast_convert_type(v, dt)
            if src_bits > dst_bits:
                if src_bits % dst_bits:
                    raise ValueError("incompatible view dtype widths")
                out = jax.lax.bitcast_convert_type(v, dt)
                return out.reshape(v.shape[:-1]
                                   + (v.shape[-1] * (src_bits
                                                     // dst_bits),))
            ratio = dst_bits // src_bits
            if dst_bits % src_bits or v.shape[-1] % ratio:
                raise ValueError(
                    "last dim must divide by the dtype width ratio")
            vv = v.reshape(v.shape[:-1] + (v.shape[-1] // ratio, ratio))
            return jax.lax.bitcast_convert_type(vv, dt)

        return unary(f, self, "view_dtype")

    def _view_as(self, other):
        return self.reshape(list(other.shape))

    def _inverse(self):
        return paddle.linalg.inv(self)

    def _histogram_bin_edges(self, bins=100, min=0.0, max=0.0):
        import numpy as np

        v = np.asarray(self._data)
        rng = None if (min == 0 and max == 0) else (min, max)
        return paddle.to_tensor(np.histogram_bin_edges(
            v, bins=bins, range=rng).astype(np.float32))

    def _uniform_(self, min=-1.0, max=1.0, seed=0):
        from . import random as _random
        import jax

        key = _random.next_key()
        self._inplace_from(Tensor._wrap(jax.random.uniform(
            key, self._data.shape, self._data.dtype, min, max)))
        return self

    def _bernoulli_(self, p=0.5, seed=0):
        from . import random as _random
        import jax

        key = _random.next_key()
        self._inplace_from(Tensor._wrap(jax.random.bernoulli(
            key, p, self._data.shape).astype(self._data.dtype)))
        return self

    def _cauchy_(self, loc=0, scale=1, seed=0):
        from . import random as _random
        import jax

        key = _random.next_key()
        u = jax.random.uniform(key, self._data.shape, jnp.float32,
                               1e-6, 1 - 1e-6)
        self._inplace_from(Tensor._wrap(
            (loc + scale * jnp.tan(jnp.pi * (u - 0.5)))
            .astype(self._data.dtype)))
        return self

    def _geometric_(self, probs, seed=0):
        from . import random as _random
        import jax

        key = _random.next_key()
        u = jax.random.uniform(key, self._data.shape, jnp.float32,
                               1e-6, 1 - 1e-6)
        self._inplace_from(Tensor._wrap(
            jnp.ceil(jnp.log(u) / jnp.log1p(-probs))
            .astype(self._data.dtype)))
        return self

    extras = {
        "rank": _rank, "numel": _numel, "is_empty": _is_empty,
        "is_complex": _is_complex, "is_integer": _is_integer,
        "is_floating_point": _is_floating_point, "is_tensor": _is_tensor,
        "increment": _increment, "view": _view, "view_as": _view_as,
        "inverse": _inverse,
        "histogram_bin_edges": _histogram_bin_edges,
        "uniform_": _uniform_, "bernoulli_": _bernoulli_,
        "cauchy_": _cauchy_, "geometric_": _geometric_,
    }
    for name, fn in extras.items():
        if not hasattr(Tensor, name):
            fn.__name__ = name
            setattr(Tensor, name, fn)
