"""paddle.audio — spectral feature extraction.

Reference parity: python/paddle/audio/ (functional/functional.py
hz_to_mel:29 / compute_fbank_matrix:189 / power_to_db:262 / create_dct:306,
features/layers.py Spectrogram:45 / MelSpectrogram:130 /
LogMelSpectrogram:237 / MFCC:344). All computation is jnp over the
framework's stft (signal.py), so features jit and run on the MXU/VPU;
dataset classes are download-backed and raise (zero egress).
"""
from . import functional  # noqa: F401
from .features import (  # noqa: F401
    LogMelSpectrogram, MelSpectrogram, MFCC, Spectrogram,
)

from . import backends  # noqa: E402,F401
from .backends import info, load, save  # noqa: E402,F401

__all__ = ["functional", "features", "backends", "info", "load", "save",
           "Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class _RaisingDataset:
    """Corpus-downloading dataset (reference audio/datasets/*): this
    environment has no egress, so construction raises with guidance —
    the attribute itself exists (API-surface contract)."""

    def __init__(self, *a, **k):
        raise RuntimeError(
            f"paddle.audio.datasets.{type(self).__name__} downloads its "
            "corpus; this environment has no network egress — load "
            "files locally via paddle.io.")


class _DatasetsNS:
    ESC50 = type("ESC50", (_RaisingDataset,), {})
    TESS = type("TESS", (_RaisingDataset,), {})
    GTZAN = type("GTZAN", (_RaisingDataset,), {})
    UrbanSound8K = type("UrbanSound8K", (_RaisingDataset,), {})


datasets = _DatasetsNS()
