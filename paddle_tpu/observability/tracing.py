"""Request-scoped distributed tracing: a lightweight Tracer/Span store.

PR 12's registry answers "what are the aggregates" (p50/p99 TTFT, queue
depth, tok/s); this module answers the question a production on-call
actually asks: *why was this specific request slow?* A `Span` is one
timed interval with attributes and children; a `Tracer` owns a bounded
ring of COMPLETED root-span trees plus the set of currently-open spans,
so a retired serving request's trace is a complete causal timeline
(queue wait -> admission -> chunked prefills -> decode bursts ->
preempt/resume -> stream delivery) and an in-flight one is inspectable
mid-run.

Design constraints (same bar as the registry):

- **O(1) begin/end, monotonic timestamps.** ``begin`` allocates one
  slotted object and appends to its parent's child list; ``end`` stamps
  ``t1`` and, for roots, rotates the bounded ring. No percentile math,
  no serialization, no device access ever happens on the hot path —
  `to_dict` trees are built at scrape time (`/tracez`, selftests).
- **Bounded everywhere.** Completed roots live in a ring
  (``capacity``), tail exemplars in their own ring
  (``exemplar_capacity``), children per span are capped
  (``max_children`` — beyond it children are dropped and counted on
  the parent, so a runaway 10k-token request cannot hold 10k span
  objects live).
- **Orphan detection.** An *orphan* is a span that outlived its trace:
  still open while its root is closed (the churn-with-preemption bug
  class — a decode span leaked across a retire), or closed with a
  dangling parent that was never recorded. ``orphans()`` walks the
  open set at call time; the serving selftest asserts it is empty
  after drain + ``abort_all``.
- **Chrome export on per-request tracks.** Ended spans (when
  ``chrome=True``) land in a bounded module buffer on the same
  perf_counter timebase as the PR 12 counter tracks; the Profiler
  export drains ``drain_chrome_spans()`` next to
  ``drain_chrome_counters()``, one chrome *thread* per track (the
  request id), so traces render under the host spans in ui.perfetto.
"""
from __future__ import annotations

import collections
import threading
import time
import weakref

from .sentinel import enabled
from .registry import registry as _registry

__all__ = ["Span", "Tracer", "drain_chrome_spans"]

# chrome span-track buffer (bounded), drained by Profiler._finish_cycle
# into the exported trace next to the StepTimeline counter tracks
_span_events = collections.deque(maxlen=65536)
_span_lock = threading.Lock()
_track_tids: dict = {}          # track name -> chrome tid (bounded)
_emitted_meta: set = set()      # tracks whose "M" events are in the
#                                 CURRENT buffer (cleared per drain so
#                                 every profiler cycle gets metadata)
_next_tid = 1                   # monotonic: a tid is never reassigned
_MAX_TRACKS = 4096
_CHROME_PID = 1                 # separate process group from host spans


def drain_chrome_spans():
    """Pop all pending chrome-trace span events ("ph": "X"/"M")."""
    with _span_lock:
        out = list(_span_events)
        _span_events.clear()
        # metadata must be re-emitted into the NEXT cycle's buffer
        _emitted_meta.clear()
    return out


def _profiler_recording() -> bool:
    """Chrome span events are only consumed by the Profiler export, so
    the buffer is fed only while a profiler cycle is RECORDing — with
    no profiler active, span end() skips the chrome dict build
    entirely (the difference between ~2µs and ~5µs per span on the
    serve loop)."""
    rec = _profiler_recorder()
    return rec is not None and rec.enabled


_prof_recorder = None


def _profiler_recorder():
    global _prof_recorder
    if _prof_recorder is None:
        try:
            from ..profiler import _recorder

            _prof_recorder = _recorder
        except Exception:
            _prof_recorder = False
    return _prof_recorder or None


def _chrome_tid(track):
    """Stable tid per track name, re-announced per drain cycle via the
    thread-name metadata event (a profiler cycle after the first must
    not render bare numeric tids). Tids are MONOTONIC — never
    reassigned, so two tracks can never collide inside one export no
    matter how many recycles happen — and the name->tid map is bounded
    by evicting its oldest entries (an evicted track that reappears
    simply gets a fresh tid and fresh metadata)."""
    global _next_tid
    tid = _track_tids.get(track)
    if tid is None:
        while len(_track_tids) >= _MAX_TRACKS:
            evicted = next(iter(_track_tids))
            del _track_tids[evicted]
            _emitted_meta.discard(evicted)
        tid = _next_tid
        _next_tid += 1
        _track_tids[track] = tid
    if track not in _emitted_meta:
        if not _emitted_meta:
            _span_events.append({
                "name": "process_name", "ph": "M", "pid": _CHROME_PID,
                "tid": 0, "args": {"name": "requests"}})
        _emitted_meta.add(track)
        _span_events.append({
            "name": "thread_name", "ph": "M", "pid": _CHROME_PID,
            "tid": tid, "args": {"name": str(track)}})
    return tid


class Span:
    """One timed interval in a trace tree. Created by `Tracer.begin`;
    ``t1 is None`` while open. Attributes are a plain dict of JSON
    scalars; children are Spans appended by later ``begin`` calls.

    CYCLE-FREE by construction: the child->parent link is a weakref
    (parent->children is the only strong direction), so a trace tree
    evicted from the ring frees by refcount immediately instead of
    waiting for a gen2 cycle collection — measured in the serving
    lane, span cycles were enough extra cyclic garbage to land a
    ~170 ms full GC inside a 260 ms measured traffic window. The
    children list is lazily allocated (most spans are leaves)."""

    __slots__ = ("name", "span_id", "track", "t0", "t1", "attrs",
                 "_parent_ref", "_children", "dropped_children",
                 "__weakref__")

    def __init__(self, name, span_id, track, parent, t0, attrs):
        self.name = name
        self.span_id = span_id
        self.track = track
        self._parent_ref = (weakref.ref(parent) if parent is not None
                            else None)
        self.t0 = t0
        self.t1 = None
        self.attrs = attrs
        self._children = None
        self.dropped_children = 0

    @property
    def parent(self):
        """The parent span, or None for roots (and for spans whose
        tree was already collected)."""
        return (self._parent_ref() if self._parent_ref is not None
                else None)

    @property
    def children(self) -> list:
        return self._children if self._children is not None else []

    @property
    def closed(self):
        return self.t1 is not None

    @property
    def root(self):
        """The tree root, or None when an ancestor was collected (the
        span outlived its trace — an orphan by definition)."""
        s = self
        while s._parent_ref is not None:
            p = s._parent_ref()
            if p is None:
                return None
            s = p
        return s

    def duration_s(self):
        return None if self.t1 is None else self.t1 - self.t0

    def to_dict(self) -> dict:
        """Nested JSON-able tree (scrape-time only — never hot path)."""
        d = {"name": self.name, "track": self.track,
             "t0": round(self.t0, 6),
             "t1": None if self.t1 is None else round(self.t1, 6),
             "dur_ms": (None if self.t1 is None
                        else round((self.t1 - self.t0) * 1e3, 4)),
             "attrs": dict(self.attrs)}
        if self._children:
            d["children"] = [c.to_dict() for c in self._children]
        if self.dropped_children:
            d["dropped_children"] = self.dropped_children
        return d

    def find(self, name) -> list:
        """All descendant spans (depth-first) with the given name."""
        out = []
        stack = list(self.children)
        while stack:
            s = stack.pop()
            if s.name == name:
                out.append(s)
            if s._children:
                stack.extend(s._children)
        return out

    def __repr__(self):
        state = "open" if self.t1 is None else f"{self.duration_s():.6f}s"
        return f"<Span {self.name!r} track={self.track} {state}>"


# shared no-op span: returned when tracing is disabled or a parent's
# child budget is exhausted — begin/end on it are O(1) no-ops and it
# never enters the open set or any tree
_NOOP = Span("<noop>", -1, None, None, 0.0, {})
_NOOP.t1 = 0.0


class Tracer:
    """Bounded store of span trees.

    Args:
      capacity: completed root spans kept (ring, newest wins).
      exemplar_capacity: tail-exemplar root spans kept (separate ring —
        an exemplar survives ring churn).
      max_children: per-span child cap; excess children are dropped and
        counted on the parent (``dropped_children``).
      chrome: publish ended spans to the chrome span-track buffer
        (only while a Profiler cycle is recording — the export is the
        buffer's sole consumer, and skipping the event build otherwise
        keeps span end() at ~2µs).
      clock: monotonic clock (the serving engine passes its own so span
        times line up with TTFT bookkeeping).
      registry: MetricsRegistry for the lazy ``trace.*`` gauges.
      enabled: False builds a tracer whose ``begin`` returns a shared
        no-op span — the zero-overhead opt-out.
    """

    def __init__(self, capacity=256, exemplar_capacity=32,
                 max_children=1024, chrome=True,
                 clock=time.perf_counter, registry=None, enabled=True):
        self.capacity = int(capacity)
        self.max_children = int(max_children)
        self.chrome = bool(chrome)
        self.clock = clock
        self._on = bool(enabled)
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=self.capacity)
        self._exemplars = collections.deque(maxlen=int(exemplar_capacity))
        self._open: dict = {}            # span_id -> Span
        self._next_id = 0
        self.spans_begun = 0
        self.spans_ended = 0
        self.spans_dropped = 0
        self.completed_total = 0
        self.bind_registry(registry if registry is not None
                           else _registry())

    def bind_registry(self, reg):
        """(Re-)register the lazy trace gauges — the serving engine
        rebinds after `reset_metrics` swaps its registry."""
        if reg is None:
            return
        reg.gauge("trace.open_spans").set_fn(lambda: len(self._open))
        reg.gauge("trace.completed_traces").set_fn(
            lambda: self.completed_total)
        reg.gauge("trace.exemplars").set_fn(lambda: len(self._exemplars))
        reg.gauge("trace.orphans").set_fn(lambda: len(self.orphans()))
        reg.gauge("trace.dropped_spans").set_fn(
            lambda: self.spans_dropped)

    # -- hot path --------------------------------------------------------
    def begin(self, name, parent=None, track=None, **attrs) -> Span:
        """Open a span. ``parent=None`` opens a root (a new trace);
        otherwise the span joins ``parent.children``. O(1)."""
        if not self._on or not enabled():
            return _NOOP
        if parent is _NOOP:
            return _NOOP
        if parent is not None:
            kids = parent._children
            if kids is not None and len(kids) >= self.max_children:
                parent.dropped_children += 1
                with self._lock:
                    self.spans_dropped += 1
                return _NOOP
        t0 = self.clock()
        with self._lock:
            self._next_id += 1
            sid = self._next_id
            span = Span(name, sid,
                        track if track is not None
                        else (parent.track if parent is not None
                              else f"t{sid}"),
                        parent, t0, attrs)
            self._open[sid] = span
            self.spans_begun += 1
        if parent is not None:
            if parent._children is None:
                parent._children = []
            parent._children.append(span)
        return span

    def end(self, span: Span, **attrs):
        """Close a span. Roots rotate into the completed ring. O(1)."""
        if span is None or span is _NOOP or span.t1 is not None:
            return
        span.t1 = self.clock()
        if attrs:
            span.attrs.update(attrs)
        with self._lock:
            self._open.pop(span.span_id, None)
            self.spans_ended += 1
            if span._parent_ref is None:       # a root completes a trace
                self._ring.append(span)
                self.completed_total += 1
        if self.chrome and _profiler_recording():
            with _span_lock:
                tid = _chrome_tid(span.track)
                _span_events.append({
                    "name": span.name, "ph": "X", "cat": "request",
                    "ts": span.t0 * 1e6,
                    "dur": (span.t1 - span.t0) * 1e6,
                    "pid": _CHROME_PID, "tid": tid,
                    "args": {k: v for k, v in span.attrs.items()
                             if isinstance(v, (int, float, str, bool))
                             or v is None}})

    def instant(self, name, parent=None, track=None, **attrs) -> Span:
        """Zero-duration marker span (admission, preemption)."""
        span = self.begin(name, parent=parent, track=track, **attrs)
        self.end(span)
        return span

    # -- scrape surface --------------------------------------------------
    def open_spans(self) -> list:
        with self._lock:
            return list(self._open.values())

    def orphans(self) -> list:
        """Spans that outlived their trace: open while the root is
        closed, or whose parent chain is gone entirely (the tree was
        collected out from under a still-open span)."""
        out = []
        for s in self.open_spans():
            if s._parent_ref is None:
                continue                        # open roots are fine
            root = s.root
            if root is None or root.closed:
                out.append(s)
        return out

    def traces(self, n=None) -> list:
        """Completed traces as nested dicts, oldest first."""
        with self._lock:
            roots = list(self._ring)
        if n is not None:
            roots = roots[-int(n):]
        return [r.to_dict() for r in roots]

    def find_trace(self, track):
        """Newest completed root on ``track`` (Span, not dict) — the
        per-request lookup (serving tracks are ``req<rid>``)."""
        with self._lock:
            roots = list(self._ring)
        for r in reversed(roots):
            if r.track == track:
                return r
        return None

    # -- tail exemplars --------------------------------------------------
    def add_exemplar(self, root: Span, reason, **attrs):
        """Pin a root span tree into the exemplar ring (bounded; the
        full tree survives ring churn). Idempotent per root."""
        if root is None or root is _NOOP:
            return
        with self._lock:
            if any(r is root for _, _, r in self._exemplars):
                return
            self._exemplars.append((reason, dict(attrs), root))

    def exemplars(self) -> list:
        """[{reason, ...attrs, trace}] oldest first (scrape surface —
        `ServingEngine.slow_requests()`)."""
        with self._lock:
            items = list(self._exemplars)
        return [{"reason": reason, **attrs, "trace": root.to_dict()}
                for reason, attrs, root in items]

    # -- lifecycle -------------------------------------------------------
    def clear(self):
        """Drop all state (e.g. after engine warmup — compile-time
        traces are noise). Counters reset too."""
        with self._lock:
            self._ring.clear()
            self._exemplars.clear()
            self._open.clear()
            self.spans_begun = 0
            self.spans_ended = 0
            self.spans_dropped = 0
            self.completed_total = 0

    def stats(self) -> dict:
        with self._lock:
            return {"open": len(self._open),
                    "completed": self.completed_total,
                    "begun": self.spans_begun,
                    "ended": self.spans_ended,
                    "dropped": self.spans_dropped,
                    "exemplars": len(self._exemplars),
                    "ring": len(self._ring)}
