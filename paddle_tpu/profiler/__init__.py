"""paddle.profiler parity — host event recorder + XLA device traces.

Reference: python/paddle/profiler/profiler.py:358 (Profiler with
scheduler/on_trace_ready), :227 (export_chrome_tracing), :592/:641
(start/stop); RecordEvent annotations (python/paddle/profiler/utils.py);
host event collection (paddle/fluid/platform/profiler/host_event_recorder.h).

TPU-first split of responsibilities:
- *Host side*: a lightweight in-process event recorder (RecordEvent spans +
  per-step marks) — the analog of HostEventRecorder; exported as
  chrome-trace JSON and summarized in `summary()`.
- *Device side*: `jax.profiler` traces (XLA/TPU timeline, HLO cost, memory
  viewer) written to the same directory when device tracing is requested —
  CUPTI's job (cuda_tracer.cc) is done by the XLA/TSL profiler.
"""
from __future__ import annotations

import contextlib
import enum
import json
import os
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

__all__ = [
    "ProfilerState", "ProfilerTarget", "Profiler", "RecordEvent",
    "make_scheduler", "export_chrome_tracing", "load_profiler_result",
]


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3  # last RECORD step of a cycle: trace is handed off


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


@dataclass
class _HostEvent:
    name: str
    start_ns: int
    end_ns: int
    tid: int
    step: Optional[int]


@dataclass
class _ProfileResult:
    """What on_trace_ready receives; also returned by Profiler.stop()."""

    events: list = field(default_factory=list)
    steps: list = field(default_factory=list)  # (step_idx, start_ns, end_ns)
    device_trace_dir: Optional[str] = None
    # chrome counter-track events ("ph": "C") drained from the
    # observability StepTimeline at cycle end (ISSUE 12): step metrics
    # render as counter lanes under the host spans
    counters: list = field(default_factory=list)
    # chrome request-track span events ("ph": "X"/"M") drained from the
    # observability Tracer (ISSUE 13): per-request serving timelines
    # render as their own thread tracks next to the counter lanes
    request_spans: list = field(default_factory=list)

    def chrome_trace(self) -> dict:
        evts = []
        for e in self.events:
            evts.append({
                "name": e.name, "ph": "X", "cat": "host",
                "ts": e.start_ns / 1e3, "dur": (e.end_ns - e.start_ns) / 1e3,
                "pid": 0, "tid": e.tid,
            })
        for idx, s, t in self.steps:
            evts.append({
                "name": f"ProfileStep#{idx}", "ph": "X", "cat": "step",
                "ts": s / 1e3, "dur": (t - s) / 1e3, "pid": 0, "tid": 0,
            })
        evts.extend(self.counters)
        evts.extend(self.request_spans)
        return {"traceEvents": evts, "displayTimeUnit": "ms"}


class _HostEventRecorder:
    """Process-global span recorder (host_event_recorder.h analog)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: list = []
        self.enabled = False
        self._step: Optional[int] = None

    def record(self, name, start_ns, end_ns):
        if not self.enabled:
            return
        ev = _HostEvent(name, start_ns, end_ns,
                        threading.get_ident() & 0xFFFF, self._step)
        with self._lock:
            self._events.append(ev)

    def drain(self):
        with self._lock:
            out, self._events = self._events, []
        return out


_recorder = _HostEventRecorder()


class RecordEvent:
    """User annotation span (reference profiler/utils.py RecordEvent).

    Usable as a context manager or begin()/end() pair. Also emits a
    `jax.profiler.TraceAnnotation` so the span shows up inside the XLA
    device timeline when device tracing is on.
    """

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._t0 = None
        self._jax_ctx = None

    def begin(self):
        self._t0 = time.perf_counter_ns()
        if _recorder.enabled:
            try:
                import jax.profiler as jp

                self._jax_ctx = jp.TraceAnnotation(self.name)
                self._jax_ctx.__enter__()
            except Exception:
                self._jax_ctx = None
        return self

    def end(self):
        if self._t0 is None:
            return
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(None, None, None)
            self._jax_ctx = None
        _recorder.record(self.name, self._t0, time.perf_counter_ns())
        self._t0 = None

    __enter__ = begin

    def __exit__(self, *exc):
        self.end()


def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Reference profiler.py make_scheduler: per-step state machine
    [skip_first][closed][ready][record ... RECORD_AND_RETURN], repeating."""
    cycle = closed + ready + record
    if record <= 0 or cycle <= 0:
        raise ValueError("record must be > 0")

    def fn(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s // cycle >= repeat:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return fn


def _default_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD  # record everything; RETURN on stop()


def export_chrome_tracing(dir_name: str,
                          worker_name: Optional[str] = None) -> Callable:
    """on_trace_ready handler writing chrome://tracing JSON
    (reference profiler.py:227)."""

    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        worker = worker_name or f"host_{os.getpid()}"
        n = getattr(prof, "_export_count", 0)
        prof._export_count = n + 1
        fname = os.path.join(dir_name, f"{worker}_time_{n}.paddle_trace.json")
        with open(fname, "w") as f:
            json.dump(prof._last_result.chrome_trace(), f)
        prof._last_export_path = fname
        return fname

    return handler


def load_profiler_result(file_name: str) -> dict:
    with open(file_name) as f:
        return json.load(f)


class Profiler:
    """Reference profiler.py:358.

    Args:
      targets: iterable of ProfilerTarget; including TPU/GPU turns on the
        XLA device tracer (`jax.profiler.start_trace`).
      scheduler: ``(start, end)`` tuple or a ``make_scheduler`` callable.
      on_trace_ready: callable(prof) fired at every RECORD_AND_RETURN step
        and at stop(); default exports chrome tracing to ./profiler_log.
      timer_only: host step timing only — never touches the device tracer.
    """

    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready: Optional[Callable] = None,
                 trace_dir: str = "profiler_log", timer_only: bool = False):
        targets = list(targets) if targets is not None else [
            ProfilerTarget.CPU]
        self.targets = targets
        if scheduler is None:
            self._scheduler = _default_scheduler
        elif callable(scheduler):
            self._scheduler = scheduler
        else:
            start, end = scheduler
            self._scheduler = make_scheduler(
                closed=max(start, 0), ready=0, record=end - start, repeat=1)
        self.on_trace_ready = on_trace_ready or export_chrome_tracing(
            trace_dir)
        self.timer_only = timer_only
        self._trace_dir = trace_dir
        self._device_on = (not timer_only) and any(
            t in (ProfilerTarget.TPU, ProfilerTarget.GPU,
                  ProfilerTarget.CUSTOM_DEVICE) for t in targets)
        self.current_state = ProfilerState.CLOSED
        self._step = 0
        self._step_start_ns = None
        self._steps: list = []
        self._device_tracing = False
        self._last_result = _ProfileResult()
        self._last_export_path = None

    # -- lifecycle ------------------------------------------------------
    def start(self):
        self.current_state = self._scheduler(self._step)
        self._apply_state()
        self._step_start_ns = time.perf_counter_ns()
        return self

    def stop(self):
        self._mark_step_end()
        # finish only a cycle that was actually recording — otherwise a
        # CLOSED tail (scheduler exhausted) would clobber the completed
        # cycle's result with an empty one and double-fire on_trace_ready
        if _recorder.enabled:
            self._finish_cycle()
        self._stop_device()
        _recorder.enabled = False
        self.current_state = ProfilerState.CLOSED
        return self._last_result

    def step(self):
        """Advance the step counter (call once per training iteration)."""
        self._mark_step_end()
        prev = self.current_state
        if prev == ProfilerState.RECORD_AND_RETURN:
            self._finish_cycle()
        self._step += 1
        self.current_state = self._scheduler(self._step)
        self._apply_state()
        self._step_start_ns = time.perf_counter_ns()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- internals ------------------------------------------------------
    def _apply_state(self):
        rec = self.current_state in (ProfilerState.RECORD,
                                     ProfilerState.RECORD_AND_RETURN)
        _recorder.enabled = rec
        _recorder._step = self._step
        if rec and self._device_on and not self._device_tracing:
            try:
                import jax.profiler as jp

                os.makedirs(self._trace_dir, exist_ok=True)
                jp.start_trace(self._trace_dir)
                self._device_tracing = True
            except Exception:
                self._device_tracing = False
        elif not rec:
            self._stop_device()

    def _stop_device(self):
        if self._device_tracing:
            try:
                import jax.profiler as jp

                jp.stop_trace()
            except Exception:
                pass
            self._device_tracing = False

    def _mark_step_end(self):
        if self._step_start_ns is not None:
            self._steps.append((self._step, self._step_start_ns,
                                time.perf_counter_ns()))
            self._step_start_ns = None

    def _finish_cycle(self):
        events = _recorder.drain()
        steps = list(self._steps)
        # the chrome buffers are process-global and may hold a long
        # backlog recorded before this profiling cycle (a timeline or
        # tracer running with no Profiler active) — keep only events
        # inside the cycle's host window (buffer ts is µs on the same
        # perf_counter timebase as the span ns timestamps)
        lo = min([s for _, s, _ in steps]
                 + [e.start_ns for e in events], default=None)
        try:
            from ..observability import drain_chrome_counters

            counters = drain_chrome_counters()
            if lo is not None:
                counters = [c for c in counters if c["ts"] * 1e3 >= lo]
        except Exception:
            counters = []
        try:
            from ..observability import drain_chrome_spans

            spans = drain_chrome_spans()
            # metadata ("ph": "M", no ts) is kept unconditionally
            if lo is not None:
                spans = [s for s in spans
                         if s.get("ph") == "M"
                         or s.get("ts", 0) * 1e3 >= lo]
        except Exception:
            spans = []
        self._last_result = _ProfileResult(
            events=events, steps=steps,
            device_trace_dir=self._trace_dir if self._device_on else None,
            counters=counters, request_spans=spans)
        self._steps = []
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    # -- reporting ------------------------------------------------------
    def summary(self, sorted_by: str = "total", max_rows: int = 50) -> str:
        """Host-event statistical table
        (profiler_statistic.py's role, host side)."""
        res = self._last_result
        agg = defaultdict(lambda: [0, 0.0, 0.0])  # name -> [count, total, max]
        for e in res.events:
            a = agg[e.name]
            dur = (e.end_ns - e.start_ns) / 1e6
            a[0] += 1
            a[1] += dur
            a[2] = max(a[2], dur)
        col = {"total": 1, "calls": 0, "max": 2, "avg": 1}.get(sorted_by, 1)
        if sorted_by == "avg":
            keyf = lambda kv: -(kv[1][1] / kv[1][0])  # noqa: E731
        else:
            keyf = lambda kv: -kv[1][col]  # noqa: E731
        rows = sorted(agg.items(), key=keyf)[:max_rows]
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}"
                 f"{'Avg(ms)':>12}{'Max(ms)':>12}"]
        for name, (cnt, total, mx) in rows:
            lines.append(f"{name[:40]:<40}{cnt:>8}{total:>12.3f}"
                         f"{total / cnt:>12.3f}{mx:>12.3f}")
        if res.steps:
            durs = [(t - s) / 1e6 for _, s, t in res.steps]
            lines.append(
                f"\nSteps: {len(durs)}  avg {sum(durs) / len(durs):.3f} ms"
                f"  min {min(durs):.3f}  max {max(durs):.3f}")
        return "\n".join(lines)

    @property
    def step_times_ms(self):
        return [(t - s) / 1e6 for _, s, t in self._last_result.steps]


@contextlib.contextmanager
def profile_step(name: str = "train_step"):
    """Tiny convenience: time one span even with no Profiler active.

    The always-on path is the observability registry — the span lands
    in the ``profile_step.<name>_ms`` histogram unconditionally
    (previously the recorder dropped it whenever no Profiler cycle was
    RECORDing, breaking this docstring's promise — ISSUE 12 satellite);
    when a Profiler IS recording, the span also joins its host events.
    """
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        t1 = time.perf_counter_ns()
        _recorder.record(name, t0, t1)
        try:
            from ..observability import registry

            registry().histogram(
                f"profile_step.{name}_ms").observe((t1 - t0) / 1e6)
        except Exception:
            pass


class SortedKeys(enum.Enum):
    """reference profiler.SortedKeys — summary_sort key choices."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView(enum.Enum):
    """reference profiler.SummaryView — summary table choices."""

    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_protobuf(dir_name="profiler_log", worker_name=None):
    """reference profiler.export_protobuf scheduler-callback factory.
    The TPU backend's native trace format is chrome tracing / the jax
    profiler's TensorBoard protobufs — this returns a callback that
    routes through export_chrome_tracing and notes the format."""
    return export_chrome_tracing(dir_name, worker_name)
