"""paddle.version parity (generated python/paddle/version/__init__.py)."""
full_version = "3.0.0-tpu"
major = "3"
minor = "0"
patch = "0"
rc = "0"
cuda_version = "False"
cudnn_version = "False"
xpu_version = "False"
istaged = True
commit = "tpu-native"
with_pip_cuda_libraries = "OFF"


def show():
    print(f"commit: {commit}")
    print(f"full_version: {full_version}")
    print(f"major: {major}\nminor: {minor}\npatch: {patch}\nrc: {rc}")
    print("cuda: False\ncudnn: False\ntpu: True (XLA/PJRT)")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version


def tpu():
    return True
