"""Disaggregated multi-replica serving fleet (ISSUE 18).

One ``FleetRouter`` fronts N ``ServingEngine`` replicas — each with
its own registry, tracer, scheduler and KV pool — and owns four
policies the single engine cannot express:

* **Routing** (`router.ReplicaRouter`): sessions stick to a replica by
  rendezvous hashing (add/remove remaps only ~1/N sessions);
  sessionless requests go power-of-two-choices on live queue depth.
* **Prefill/decode disaggregation**: dedicated prefill replicas
  (``prefill_only=True`` engines) run chunked prefill and nothing
  else; every sequence that finishes prefill is harvested —
  ``export_handoff`` on the prefill side, ``adopt_handoff`` on a
  decode replica — so a prefill burst lands on prefill hardware and
  never lumps whole chunk batches into decode replicas' inter-token
  gaps. The first token is emitted by the prefill leg (TTFT is paid
  where the work is); the decode leg continues the stream
  bit-identically (same pages, same per-request seed, same programs).
* **KV eviction to host memory** (`HostKVRing`): decode replicas with
  a ring park preemption victims' pages host-side instead of
  discarding them; re-admission imports the pages back (a ``kv_onload``
  span on the victim's trace) instead of re-prefilling. The ring is
  byte-capped and drops oldest-first — a dropped blob silently falls
  back to the pre-fleet resume-by-re-prefill path.
* **SLO-burn autoscaling** (`SLOBurnAutoscaler`): the decode set
  grows when the worst per-replica SLO burn rate stays hot and shrinks
  when it stays cold — burn rate, not raw QPS, so an over-provisioned
  fleet under heavy-but-meeting-SLO load does NOT flap. Spawned
  replicas record cold-start-to-first-token; with the persistent
  compile cache warm that spin-up is a deserialize.

Threading model: one thread per replica (``threaded=True``) or a
cooperative round-robin ``step()``/``run()`` loop (deterministic —
the parity lanes use it). Locks are strictly one-at-a-time: replica
loops hold only their own lock; hand-off dispatch enqueues under the
target's lock AFTER releasing the source's; the autoscaler pauses the
whole fleet (ordered acquisition) only around a spawn's warmup so a
fresh trace never races a live dispatch.
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from ..jit.decode_step import refresh_serving_buffers
from ..observability import merge_histograms
from ..observability import registry as _global_registry
from .engine import ServingEngine
from .request import RequestState
from .router import ReplicaRouter

__all__ = ["FleetRouter", "HostKVRing", "SLOBurnAutoscaler"]

# host ring default size, MB (0 = off) — overridable per fleet
_RING_FLAG = "PADDLE_TPU_KV_HOST_RING_MB"


class HostKVRing:
    """Byte-capped host-memory parking lot for evicted KV blobs,
    keyed by rid. LRU-by-insertion: when a put overflows the cap the
    oldest entries drop (their requests fall back to re-prefill).
    Thread-safe — decode replicas share one ring, so fleet-wide host
    memory spent on parked sessions stays bounded by ONE number."""

    def __init__(self, capacity_mb: float = 64.0):
        self.capacity_bytes = max(0, int(float(capacity_mb) * (1 << 20)))
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()  # rid -> (blob, tok)
        self.bytes = 0
        self.puts = 0
        self.takes = 0
        self.drops = 0

    def put(self, rid: int, blob: dict, last_token: int):
        with self._lock:
            old = self._entries.pop(rid, None)
            if old is not None:
                self.bytes -= old[0]["nbytes"]
            self._entries[rid] = (blob, int(last_token))
            self.bytes += blob["nbytes"]
            self.puts += 1
            while self.bytes > self.capacity_bytes and self._entries:
                _, (dropped, _tok) = self._entries.popitem(last=False)
                self.bytes -= dropped["nbytes"]
                self.drops += 1

    def peek(self, rid: int):
        with self._lock:
            return self._entries.get(rid)

    def take(self, rid: int):
        with self._lock:
            entry = self._entries.pop(rid, None)
            if entry is not None:
                self.bytes -= entry[0]["nbytes"]
                self.takes += 1
            return entry

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self.bytes,
                    "capacity_bytes": self.capacity_bytes,
                    "puts": self.puts, "takes": self.takes,
                    "drops": self.drops}


class _Replica:
    """One engine + its thread/lock/hand-off inbox."""

    def __init__(self, name: str, role: str, engine):
        self.name = name
        self.role = role                    # "decode" | "prefill"
        self.engine = engine
        self.lock = threading.RLock()
        self.thread = None
        self.stop = False
        self.draining = False
        self.error = None
        self.pending_imports: deque = deque()  # (handle, blob, token)
        self.spawn_report = None

    @property
    def load(self) -> int:
        s = self.engine.scheduler
        return (len(s.waiting) + len(s.running)
                + len(self.pending_imports))


class FleetRouter:
    def __init__(self, model=None, model_factory=None,
                 decode_replicas=1, prefill_replicas=0, engine_kw=None,
                 threaded=False, seed=0, host_ring_mb=None,
                 autoscale=None, engine_cls=ServingEngine,
                 clock=time.perf_counter):
        if model is None and model_factory is None:
            raise ValueError("pass a model or a model_factory")
        # a shared model is safe because replicas only ever BIND the
        # same param objects (identical references); a model_factory
        # gives each replica its own instance instead
        self._model_factory = (model_factory if model_factory is not None
                               else (lambda: model))
        self.engine_cls = engine_cls
        self.engine_kw = dict(engine_kw or {})
        self.threaded = bool(threaded)
        self.clock = clock
        if host_ring_mb is None:
            host_ring_mb = float(os.environ.get(_RING_FLAG, "0") or 0)
        self.host_ring = (HostKVRing(host_ring_mb)
                          if host_ring_mb and host_ring_mb > 0 else None)
        self.router = ReplicaRouter(seed=seed)          # decode set
        self.prefill_router = ReplicaRouter(seed=seed + 1)
        self._replicas: list[_Replica] = []
        self._retired: list[_Replica] = []
        self._by_name: dict[str, _Replica] = {}
        self._spawned = {"decode": 0, "prefill": 0}
        self._requests: dict[int, dict] = {}    # rid -> routing entry
        self._rid = 0
        self._submit_lock = threading.Lock()
        # exported-but-not-yet-enqueued hand-offs: counted so has_work
        # (and therefore drain) can never observe "idle" while a
        # sequence is in flight between a prefill replica's harvest and
        # its decode replica's inbox
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        # adoptions per replica loop pass: one by default, so a wave of
        # hand-offs smears its import cost across many inter-token gaps
        # instead of landing the whole batch inside one (the thing the
        # disaggregation exists to prevent)
        self.adopt_batch = 1
        # threaded mode: a prefill replica sleeps this long after every
        # worked step. Prefill is the throughput role and decode the
        # latency role — without the yield the prefill thread convoys
        # the GIL through back-to-back chunk batches and decode's
        # inter-token gaps eat SEVERAL chunks instead of at most one
        # (measured 12ms vs 5ms p99 on the CPU lane)
        self.prefill_yield_s = 2e-4
        self._started = False
        self.events: list[dict] = []    # spawn/drain/autoscale log
        for _ in range(int(prefill_replicas)):
            self._add_replica(self._spawn_replica("prefill", warm=False))
        for _ in range(int(decode_replicas)):
            self._add_replica(self._spawn_replica("decode", warm=False))
        self.autoscaler = None
        if autoscale is not None:
            if isinstance(autoscale, SLOBurnAutoscaler):
                self.autoscaler = autoscale
            else:
                self.autoscaler = SLOBurnAutoscaler(
                    self, **(autoscale if isinstance(autoscale, dict)
                             else {}))
        self._bind_gauges()

    # -- construction -----------------------------------------------------
    def _spawn_replica(self, role: str, warm: bool) -> _Replica:
        idx = self._spawned[role]
        self._spawned[role] += 1
        name = f"{'p' if role == 'prefill' else 'd'}{idx}"
        t0 = self.clock()
        kw = dict(self.engine_kw)
        kw.setdefault("clock", self.clock)
        eng = self.engine_cls(
            self._model_factory(), prefill_only=(role == "prefill"),
            host_kv_ring=(self.host_ring if role == "decode" else None),
            **kw)
        r = _Replica(name, role, eng)
        if warm:
            # cold-start-to-first-token receipt: a tiny probe through
            # the fresh engine times the first prefill+decode programs
            # (compiles, or deserializes from the persistent cache),
            # then warmup covers the remaining chunk buckets
            probe = eng.submit(np.ones((4,), np.int32),
                               1 if role == "prefill" else 2)
            eng.run()
            first_ms = (probe.first_token_time - t0) * 1e3
            eng.warmup()
            if self._migration_enabled():
                self._warm_migration(eng)
            r.spawn_report = {
                "cold_start_to_first_token_ms": round(first_ms, 3),
                "spawn_ms": round((self.clock() - t0) * 1e3, 3),
                **eng.warmup_report,
            }
        return r

    def _add_replica(self, r: _Replica):
        self._replicas.append(r)
        self._by_name[r.name] = r
        (self.router if r.role == "decode"
         else self.prefill_router).add(r.name)
        if self.threaded and self._started:
            self._start_thread(r)

    def _bind_gauges(self):
        g = _global_registry()
        g.gauge("fleet.replicas").set_fn(
            lambda: len(self._replicas))
        g.gauge("fleet.decode_replicas").set_fn(
            lambda: len(self.decode_replicas()))
        g.gauge("fleet.queue_depth").set_fn(
            lambda: sum(r.load for r in list(self._replicas)))
        g.gauge("fleet.host_ring_bytes").set_fn(
            lambda: self.host_ring.bytes if self.host_ring else 0)
        g.gauge("fleet.host_ring_entries").set_fn(
            lambda: len(self.host_ring) if self.host_ring else 0)

    # -- replica views ----------------------------------------------------
    def decode_replicas(self) -> list[_Replica]:
        return [r for r in self._replicas
                if r.role == "decode" and not r.draining]

    def prefill_replicas(self) -> list[_Replica]:
        return [r for r in self._replicas
                if r.role == "prefill" and not r.draining]

    def replica(self, name: str) -> _Replica:
        return self._by_name[name]

    def _load_of(self, name: str) -> int:
        r = self._by_name.get(name)
        return r.load if r is not None else 1 << 30

    # -- client surface ---------------------------------------------------
    def submit(self, prompt, max_new_tokens, priority=0,
               eos_token_id=None, seed=None, session=None,
               on_token=None):
        """Route one request into the fleet; returns its handle. The
        fleet rid is globally unique (trace legs stitch by it) and
        doubles as the default sampling seed — a request's token
        stream depends only on (prompt, seed), never on which replica
        serves it."""
        with self._submit_lock:
            rid = self._rid
            self._rid += 1
        if seed is None:
            seed = rid
        dname = self.router.pick(self._load_of, session=session)
        entry = {"decode": dname, "session": session}
        if self.prefill_replicas():
            entry["prefill"] = self.prefill_router.pick(self._load_of)
            target = self._by_name[entry["prefill"]]
        else:
            target = self._by_name[dname]
        with target.lock:
            handle = target.engine.submit(
                prompt, max_new_tokens, priority=priority,
                eos_token_id=eos_token_id, seed=seed,
                on_token=on_token, rid=rid)
        entry["handle"] = handle
        self._requests[rid] = entry
        return handle

    # -- hand-off ---------------------------------------------------------
    def _harvest_locked(self, r: _Replica) -> list:
        """Export every sequence that finished prefill on a prefill
        replica (caller holds r.lock). Requests that FINISHED on the
        prefill leg (max_new_tokens == 1) retire there and are never
        exported."""
        out = []
        eng = r.engine
        cands = [slot for slot in sorted(eng.scheduler.running)
                 if eng.scheduler.running[slot].state
                 is RequestState.RUNNING
                 and not eng.scheduler.running[slot].done]
        if not cands:
            return out
        # count BEFORE exporting: export_handoff pops the handle from
        # the scheduler, so from that instant until dispatch the
        # in-flight counter is the only thing keeping has_work() true
        with self._inflight_lock:
            self._inflight += len(cands)
        done = 0
        try:
            for slot in cands:
                out.append(eng.export_handoff(slot))
                done += 1
        finally:
            if done < len(cands):
                with self._inflight_lock:
                    self._inflight -= len(cands) - done
        return out

    def _dispatch_handoff(self, item):
        """Enqueue an exported sequence on its decode replica's inbox
        (no other lock held). A draining/retired target re-routes."""
        handle, blob, _tok = item
        rid = handle.request.rid
        try:
            entry = self._requests.get(rid, {})
            r = self._by_name.get(entry.get("decode"))
            if r is None or r.draining or r.role != "decode":
                entry["decode"] = self.router.pick(
                    self._load_of, session=entry.get("session"))
                r = self._by_name[entry["decode"]]
            with r.lock:
                r.pending_imports.append(item)
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    def _drain_imports_locked(self, r: _Replica) -> bool:
        moved = False
        adopted = 0
        while r.pending_imports and adopted < self.adopt_batch:
            handle, blob, tok = r.pending_imports[0]
            if not r.engine.can_adopt(blob):
                break
            # adopt FIRST, pop after: the item must stay visible in the
            # inbox while the import runs, or has_work() (lockless, the
            # drain poll) sees an idle fleet mid-adoption and returns
            # with the sequence in limbo
            r.engine.adopt_handoff(handle, blob, tok, refresh=False)
            r.pending_imports.popleft()
            moved = True
            adopted += 1
        if moved:
            # one buffer resync for the whole adopted batch
            refresh_serving_buffers(r.engine)
        return moved

    # -- cooperative loop -------------------------------------------------
    def step(self) -> bool:
        """One round-robin pass over every replica (deterministic —
        single-threaded mode). Returns False when the fleet is idle."""
        worked = False
        exported = []
        for r in list(self._replicas):
            with r.lock:
                worked |= self._drain_imports_locked(r)
                if r.engine.scheduler.has_work():
                    worked |= bool(r.engine.step())
                if r.role == "prefill":
                    exported.extend(self._harvest_locked(r))
        for item in exported:
            self._dispatch_handoff(item)
            worked = True
        if self.autoscaler is not None:
            self.autoscaler.tick()
        self._finalize_drained()
        return worked

    def has_work(self) -> bool:
        return (self._inflight > 0
                or any(r.engine.scheduler.has_work() or r.pending_imports
                       for r in list(self._replicas)))

    def run(self, max_steps=2_000_000) -> dict:
        steps = 0
        while self.has_work():
            self.step()
            steps += 1
            if steps >= max_steps:
                raise RuntimeError(
                    f"fleet did not drain in {max_steps} steps")
        return self.metrics_snapshot()

    def warmup(self):
        """Serial warmup of every replica (all tracing up front — the
        threaded loops then only ever dispatch resident programs)."""
        migrate = self._migration_enabled()
        for r in list(self._replicas):
            with r.lock:
                r.engine.warmup()
                if migrate:
                    self._warm_migration(r.engine)
        return self

    def _migration_enabled(self) -> bool:
        return (self.host_ring is not None
                or any(r.role == "prefill" for r in self._replicas)
                or self._spawned["prefill"] > 0)

    @staticmethod
    def _warm_migration(eng):
        """Compile the bucketed export/import executables up front: one
        export gather + one import scatter per migration bucket. The
        page-index shape is bucketed (kv_cache.migration_bucket), so
        this covers EVERY shape a live hand-off, eviction or onload can
        dispatch — without it, the first migration mid-stream pays an
        op-by-op XLA compile inside somebody's inter-token gap (~250ms
        measured on the CPU lane)."""
        cache = eng.cache
        for w in cache.migration_buckets():
            # largest allocatable page count that still rounds up to
            # this bucket: a bucket reachable by live sequences (e.g. a
            # 28-page max_len slot in the 32 bucket) is warmed even when
            # a full-width allocation exceeds the engine's max_len
            lo = w // 2
            n = next((n for n in range(w, lo, -1)
                      if cache.can_allocate((n - 1) * cache.page_size
                                            + 1)), None)
            if n is None:
                continue
            seq_len = (n - 1) * cache.page_size + 1
            slot = cache.allocate(seq_len)
            cache._host("seq_lens")[slot] = seq_len
            blob = cache.export_slot(slot)
            cache.free(slot)
            cache.free(cache.import_slot(blob))
        # the imports rebound the pool arrays — resync the engine's
        # buffer dict at this safe boundary
        refresh_serving_buffers(eng)

    # -- threaded loop ----------------------------------------------------
    def start(self):
        self._started = True
        if self.threaded:
            for r in list(self._replicas):
                self._start_thread(r)
        return self

    def _start_thread(self, r: _Replica):
        if r.thread is not None:
            return
        r.stop = False
        r.thread = threading.Thread(target=self._replica_loop,
                                    args=(r,), daemon=True,
                                    name=f"fleet-{r.name}")
        r.thread.start()

    def _replica_loop(self, r: _Replica):
        while not r.stop:
            worked = False
            exported = ()
            try:
                with r.lock:
                    worked |= self._drain_imports_locked(r)
                    if r.engine.scheduler.has_work():
                        worked |= bool(r.engine.step())
                    if r.role == "prefill":
                        exported = self._harvest_locked(r)
            except BaseException as e:    # surfaced by drain()/stop()
                r.error = e
                return
            for item in exported:
                self._dispatch_handoff(item)
                worked = True
            if not worked:
                time.sleep(5e-4)
            elif r.role == "prefill" and self.prefill_yield_s:
                time.sleep(self.prefill_yield_s)

    def drain(self, timeout_s=300.0, poll_s=0.002) -> dict:
        """Block until every submitted request finished (threaded
        mode), then return the fleet snapshot."""
        deadline = self.clock() + float(timeout_s)
        while self.has_work():
            self._raise_replica_errors()
            if self.autoscaler is not None:
                self.autoscaler.tick()
            self._finalize_drained()
            if self.clock() > deadline:
                raise RuntimeError(
                    f"fleet did not drain within {timeout_s}s: "
                    f"{ {r.name: r.load for r in self._replicas} }")
            time.sleep(poll_s)
        self._raise_replica_errors()
        # quiesce before the snapshot: has_work() can go false while a
        # replica thread is still INSIDE the step() that retired the
        # last request (counters/handle flags not yet published —
        # observed as a 47/48 finished reading); every step runs under
        # the replica lock, so taking each lock once guarantees the
        # final step completed before we read
        for r in list(self._replicas):
            with r.lock:
                pass
        self._finalize_drained()
        return self.metrics_snapshot()

    def _raise_replica_errors(self):
        for r in list(self._replicas):
            if r.error is not None:
                raise RuntimeError(
                    f"replica {r.name} failed") from r.error

    def stop(self):
        for r in list(self._replicas):
            r.stop = True
        for r in list(self._replicas):
            if r.thread is not None:
                r.thread.join(timeout=30)
                r.thread = None
        self._started = False
        self._finalize_drained()

    def _paused(self):
        """Ordered acquisition of every replica lock — quiesces all
        dispatch so a spawn's warmup traces alone. Returns the lock
        list; caller releases in reverse."""
        locks = [r.lock for r in list(self._replicas)]
        for lk in locks:
            lk.acquire()
        return locks

    # -- elasticity -------------------------------------------------------
    def scale_up(self, reason="manual", burn=None) -> _Replica:
        """Spawn, warm and enlist one decode replica. Fleet-paused for
        the warmup in threaded mode (fresh traces never race live
        dispatches); the cold-start receipt lands in the event log."""
        locks = self._paused() if self.threaded else []
        try:
            r = self._spawn_replica("decode", warm=True)
            self._add_replica(r)
        finally:
            for lk in reversed(locks):
                lk.release()
        self.events.append({"action": "scale_up", "replica": r.name,
                            "reason": reason, "burn": burn,
                            "decode_replicas": len(
                                self.decode_replicas()),
                            **(r.spawn_report or {})})
        return r

    def scale_down(self, name=None, reason="manual", burn=None):
        """Mark one decode replica draining: routers stop sending it
        work (rendezvous remaps only its ~1/N sessions), resident
        requests finish in place, and the drained replica retires with
        its leak receipt in the event log."""
        cands = self.decode_replicas()
        if len(cands) <= 1:
            raise RuntimeError("cannot scale below one decode replica")
        if name is None:
            # least loaded, newest first: the cheapest drain
            r = min(reversed(cands), key=lambda c: c.load)
        else:
            r = self._by_name[name]
        r.draining = True
        self.router.remove(r.name)
        self.events.append({"action": "scale_down", "replica": r.name,
                            "reason": reason, "burn": burn,
                            "decode_replicas": len(
                                self.decode_replicas())})
        return r

    def _finalize_drained(self):
        for r in [x for x in self._replicas if x.draining]:
            with r.lock:
                busy = (r.engine.scheduler.has_work()
                        or r.pending_imports)
            if busy:
                continue
            r.stop = True
            if r.thread is not None and \
                    r.thread is not threading.current_thread():
                r.thread.join(timeout=30)
                r.thread = None
            self._replicas.remove(r)
            self._retired.append(r)
            self._by_name.pop(r.name, None)
            self.events.append({
                "action": "retired", "replica": r.name,
                "leak_check": r.engine.leak_check(),
                "open_spans": len(r.engine.tracer.open_spans()),
            })

    # -- observability ----------------------------------------------------
    def metrics_snapshot(self) -> dict:
        """Fleet-level rollup: per-replica snapshots plus MERGED-sample
        percentiles (a fleet p99 is the p99 of the union of samples —
        never an average of per-replica p99s)."""
        reps = list(self._replicas) + list(self._retired)
        per = {r.name: r.engine.metrics_snapshot() for r in reps}
        ttft = merge_histograms(
            [r.engine.metrics.ttft_s for r in reps], name="fleet.ttft_s")
        itl = merge_histograms(
            [r.engine.metrics.itl_s for r in reps], name="fleet.itl_s")
        out = {
            "replicas": per,
            "decode_replicas": len(self.decode_replicas()),
            "prefill_replicas": len(self.prefill_replicas()),
            "retired_replicas": len(self._retired),
            "fleet_ttft_p50_s": ttft.percentile(50),
            "fleet_ttft_p99_s": ttft.percentile(99),
            "fleet_itl_p50_s": itl.percentile(50),
            "fleet_itl_p99_s": itl.percentile(99),
            "events": list(self.events),
        }
        for key in ("submitted", "finished", "generated_tokens",
                    "preemptions", "kv_evictions", "kv_onloads",
                    "prefill_chunks", "decode_steps"):
            out[f"fleet_{key}"] = sum(p.get(key, 0)
                                      for p in per.values())
        if self.host_ring is not None:
            out["host_ring"] = self.host_ring.stats()
        return out

    def request_trace(self, rid: int) -> list:
        """Every replica's completed leg of one request, stitched by
        the shared ``req<rid>`` track and ordered by start time —
        disaggregated requests show a prefill leg (closed with
        ``handoff=True``) followed by a decode leg."""
        legs = []
        for r in list(self._replicas) + list(self._retired):
            root = r.engine.tracer.find_trace(f"req{rid}")
            if root is not None:
                legs.append({"replica": r.name, "role": r.role,
                             "root": root})
        legs.sort(key=lambda leg: leg["root"].t0)
        return legs

    def leak_check(self) -> dict:
        """Fleet-wide invariant surface: pool conservation and span
        hygiene on EVERY replica (live and retired) plus the host
        ring. After a drain, ``clean`` must be True: all pages/slots
        free, no open or orphaned spans, ring empty."""
        out = {"replicas": {}, "clean": True}
        for r in list(self._replicas) + list(self._retired):
            leaks = r.engine.leak_check()
            stats = r.engine.cache.pool_stats()
            rep = {
                **leaks,
                "pool_conserved": (stats["used_pages"]
                                   + stats["free_pages"]
                                   == stats["total_pages"]),
                "open_spans": len(r.engine.tracer.open_spans()),
                "orphan_spans": len(r.engine.tracer.orphans()),
                "pending_imports": len(r.pending_imports),
            }
            rep["clean"] = (
                leaks["free_pages"] == leaks["total_pages"]
                and leaks["free_slots"] == leaks["total_slots"]
                and leaks["resident_slot_pages"] == 0
                and rep["pool_conserved"] and rep["open_spans"] == 0
                and rep["orphan_spans"] == 0
                and rep["pending_imports"] == 0)
            out["replicas"][r.name] = rep
            out["clean"] = out["clean"] and rep["clean"]
        if self.host_ring is not None:
            ring = self.host_ring.stats()
            out["host_ring"] = ring
            out["clean"] = (out["clean"] and ring["entries"] == 0
                            and ring["bytes"] == 0)
        return out

    def retrace_stats(self) -> dict:
        return {r.name: r.engine.retrace_stats()
                for r in list(self._replicas) + list(self._retired)}


class SLOBurnAutoscaler:
    """Decode-set elasticity from SLO burn rate (ISSUE 18).

    ``tick()`` samples the WORST burn rate across decode replicas'
    declared SLOs (the fleet's engines carry the ISSUE-13 rolling
    windows). A streak of ``hysteresis`` hot evaluations
    (burn >= burn_up) grows the set; a streak of cold ones
    (burn <= burn_down) shrinks it; anything between resets both
    streaks. After any action the controller holds for ``cooldown_s``.
    Burn rate — violations spent against the error budget — is the
    actuation signal precisely because raw QPS lies in both
    directions: high QPS with met SLOs needs no replica, and low QPS
    with a pathological workload (one giant prompt) still burns."""

    def __init__(self, fleet, min_decode=1, max_decode=4, burn_up=1.0,
                 burn_down=0.25, hysteresis=2, cooldown_s=0.5,
                 interval_s=0.05):
        self.fleet = fleet
        self.min_decode = max(1, int(min_decode))
        self.max_decode = int(max_decode)
        self.burn_up = float(burn_up)
        self.burn_down = float(burn_down)
        self.hysteresis = max(1, int(hysteresis))
        self.cooldown_s = float(cooldown_s)
        self.interval_s = float(interval_s)
        self._lock = threading.Lock()
        self._next_eval = None
        self._hold_until = None
        self._up_streak = 0
        self._down_streak = 0
        self.evaluations = 0

    def burn(self) -> float:
        worst = 0.0
        for r in self.fleet.decode_replicas():
            for st in r.engine.slo.snapshot().values():
                worst = max(worst, float(st.get("burn_rate", 0.0)))
        return worst

    def tick(self):
        with self._lock:
            now = self.fleet.clock()
            if self._next_eval is not None and now < self._next_eval:
                return
            self._next_eval = now + self.interval_s
            self.evaluations += 1
            if self._hold_until is not None and now < self._hold_until:
                return
            b = self.burn()
            n = len(self.fleet.decode_replicas())
            if b >= self.burn_up and n < self.max_decode:
                self._up_streak += 1
                self._down_streak = 0
                if self._up_streak >= self.hysteresis:
                    self._up_streak = self._down_streak = 0
                    self._hold_until = now + self.cooldown_s
                    self.fleet.scale_up(reason="slo_burn", burn=b)
            elif b <= self.burn_down and n > self.min_decode:
                self._down_streak += 1
                self._up_streak = 0
                if self._down_streak >= self.hysteresis:
                    self._up_streak = self._down_streak = 0
                    self._hold_until = now + self.cooldown_s
                    self.fleet.scale_down(reason="slo_burn", burn=b)
            else:
                self._up_streak = self._down_streak = 0
