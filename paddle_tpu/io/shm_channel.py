"""Worker→parent tensor transport over the native SPSC shm ring.

ctypes bindings for csrc/shm_ring.cpp (built lazily with g++ on first use;
cached .so beside this file). The DataLoader falls back to plain
multiprocessing queues with an identical flow when no native toolchain is
available, so it works everywhere and is merely faster with the ring.
"""
from __future__ import annotations

import ctypes
import os
import pickle
import subprocess
import threading
import time

_SO_PATH = os.path.join(os.path.dirname(__file__), "_shm_ring.so")
_SRC = os.path.join(os.path.dirname(__file__), os.pardir, "csrc",
                    "shm_ring.cpp")
_build_lock = threading.Lock()
_lib = None
_lib_tried = False


def _load_lib():
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    with _build_lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        if not os.path.exists(_SO_PATH):
            try:
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                     "-o", _SO_PATH, os.path.abspath(_SRC), "-lrt",
                     "-lpthread"],
                    check=True, capture_output=True, timeout=120)
            except Exception:
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        lib.shm_ring_create.restype = ctypes.c_void_p
        lib.shm_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.shm_ring_attach.restype = ctypes.c_void_p
        lib.shm_ring_attach.argtypes = [ctypes.c_char_p]
        lib.shm_ring_push.restype = ctypes.c_int
        lib.shm_ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_uint64, ctypes.c_uint64]
        lib.shm_ring_next_size.restype = ctypes.c_int64
        lib.shm_ring_next_size.argtypes = [ctypes.c_void_p]
        lib.shm_ring_pop.restype = ctypes.c_int
        lib.shm_ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_uint64, ctypes.c_uint64]
        lib.shm_ring_close_producer.argtypes = [ctypes.c_void_p]
        lib.shm_ring_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load_lib() is not None


class ShmRingChannel:
    """One SPSC ring: worker process = producer, parent loader = consumer."""

    def __init__(self, name: str, capacity: int = 64 << 20, create=True):
        lib = _load_lib()
        if lib is None:
            raise RuntimeError("native shm ring unavailable")
        self._lib = lib
        self.name = name.encode()
        if create:
            self._ring = lib.shm_ring_create(self.name, capacity)
        else:
            self._ring = lib.shm_ring_attach(self.name)
        if not self._ring:
            raise OSError(f"shm ring {name!r} create/attach failed")

    # -- producer side --------------------------------------------------
    def send(self, obj, timeout_ms: int = 60_000):
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        rc = self._lib.shm_ring_push(self._ring, payload, len(payload),
                                     timeout_ms)
        if rc == -1:
            raise TimeoutError("shm ring full")
        if rc != 0:
            raise BrokenPipeError("shm ring closed")

    def close_producer(self):
        self._lib.shm_ring_close_producer(self._ring)

    # -- consumer side --------------------------------------------------
    def recv(self, timeout_ms: int = 60_000):
        """Next object; EOFError once producer closed + ring drained;
        TimeoutError on timeout."""
        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            size = self._lib.shm_ring_next_size(self._ring)
            if size == -2:
                raise EOFError
            if size > 0:
                buf = ctypes.create_string_buffer(int(size))
                rc = self._lib.shm_ring_pop(self._ring, buf, int(size),
                                            timeout_ms)
                if rc == -1:
                    raise TimeoutError("shm ring empty")
                if rc != 0:
                    raise EOFError
                return pickle.loads(buf.raw)
            if time.monotonic() >= deadline:
                raise TimeoutError("shm ring empty")
            time.sleep(0.0005)

    def free(self):
        if self._ring:
            self._lib.shm_ring_free(self._ring)
            self._ring = None
