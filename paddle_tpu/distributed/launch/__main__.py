"""python -m paddle_tpu.distributed.launch (reference:
python/paddle/distributed/launch/__main__.py)."""
import sys

from .main import main

sys.exit(main())
