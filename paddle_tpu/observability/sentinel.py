"""Retrace sentinel: executable-cache-miss accounting with attribution.

The bug class PR 6 hit — a numpy/device-array metadata mix keying a
fresh executable per combination, silently recompiling mid-serve — was
only detectable by hand-written compile-count probes. The sentinel
turns it into one attributed log line: every jitted step path
(`TrainStep`, `FusedScanTrainStep` + its sharded/pipeline subclasses,
the decode/serve `_Step`s) calls ``observe(args)`` right before
dispatching its compiled callable. The sentinel derives the same
abstract signature jax.jit keys its executable cache on (pytree
structure + per-leaf shape/dtype/weak-type/placement/host-vs-device
kind) and:

- counts cache hits and misses per signature;
- on a NEW signature after the first, diffs the leaves against the
  closest previously-seen signature and reports exactly WHICH argument
  leaf changed (``state['guard']['scale']: dtype float32 -> float16``);
- classifies the miss as *expected* when every changed leaf is a
  declared bucketed/optional argument (prefill length buckets, the
  optional segment-id arg) — everything else is an **unexpected
  recompile**, logged, counted in the registry, noted in the flight
  recorder, and raised as ``RetraceError`` in strict mode (selftests).

All the existing compile-count probes are expressible through the
sentinel: ``signatures`` is the trace count, ``calls`` the dispatch
count, ``unexpected`` must stay 0 on a clean run.
"""
from __future__ import annotations

import logging
import threading
import weakref

from .registry import registry as _registry

__all__ = ["RetraceSentinel", "RetraceError", "set_strict_retrace",
           "strict_retrace", "retrace_summary", "enabled"]

logger = logging.getLogger("paddle_tpu.observability")

_strict = False
_enabled_env = None


class RetraceError(RuntimeError):
    """An unexpected recompile under strict mode — the message names
    the offending argument leaf/leaves."""


def set_strict_retrace(on: bool):
    """Global strict toggle: any sentinel without an explicit
    ``strict=`` raises `RetraceError` on an unexpected recompile. The
    hybrid/serving/observability selftest lanes run with this ON."""
    global _strict
    _strict = bool(on)


def strict_retrace() -> bool:
    return _strict


def enabled() -> bool:
    """Telemetry kill-switch: PADDLE_TPU_TELEMETRY=0 disables the
    per-step observe/record calls (instruments stay importable)."""
    global _enabled_env
    if _enabled_env is None:
        import os

        _enabled_env = os.environ.get("PADDLE_TPU_TELEMETRY", "1") != "0"
    return _enabled_env


# -- signatures -------------------------------------------------------------

_jax = None
_np = None


def _mods():
    global _jax, _np
    if _jax is None:
        import jax
        import numpy

        _jax, _np = jax, numpy
    return _jax, _np


def _leaf_sig(leaf):
    """Hashable signature of one leaf covering the fields jax.jit's
    cache key depends on. HOT PATH (runs per state leaf per step): for
    jax arrays the signature is the aval OBJECT itself (ShapedArray —
    hashable, carries shape+dtype+weak_type in one attribute read) plus
    sharding and committed-ness; field-level description only happens
    on the rare mismatch (`_describe`)."""
    jax, np = _mods()

    if isinstance(leaf, jax.Array):
        try:
            sh = leaf.sharding
        except Exception:
            sh = None
        return (leaf.aval, sh, getattr(leaf, "_committed", True))
    if isinstance(leaf, (np.ndarray, np.generic)):
        return ("np", np.shape(leaf), leaf.dtype)
    # python scalars trace as weak-typed values; anything else is a
    # static-by-structure leaf — key by type
    return ("py", type(leaf))


_FIELDS = ("kind", "shape", "dtype", "weak_type", "placement")


def _describe(sig):
    """Expand a leaf signature into named fields for attribution."""
    if sig[0] == "np":
        return {"kind": "np(host)", "shape": tuple(sig[1]),
                "dtype": str(sig[2]), "weak_type": False,
                "placement": "host"}
    if sig[0] == "py":
        return {"kind": "py", "shape": (), "dtype": sig[1].__name__,
                "weak_type": True, "placement": None}
    aval, sh, committed = sig
    return {"kind": "jax", "shape": tuple(aval.shape),
            "dtype": str(aval.dtype),
            "weak_type": bool(getattr(aval, "weak_type", False)),
            "placement": f"{sh}|committed={bool(committed)}"}


def _format_path(path, names=None):
    """Human-readable leaf path; the TOP-LEVEL tuple index is replaced
    by the caller-provided argument name."""
    from jax.tree_util import DictKey, FlattenedIndexKey, GetAttrKey, SequenceKey

    parts = []
    for i, k in enumerate(path):
        if isinstance(k, SequenceKey):
            if i == 0 and names is not None and k.idx < len(names):
                parts.append(names[k.idx])
            else:
                parts.append(f"[{k.idx}]")
        elif isinstance(k, DictKey):
            parts.append(f"[{k.key!r}]")
        elif isinstance(k, GetAttrKey):
            parts.append(f".{k.name}")
        elif isinstance(k, FlattenedIndexKey):
            parts.append(f"[{k.key}]")
        else:
            parts.append(str(k))
    out = ""
    for p in parts:
        if out and not p.startswith((".", "[")):
            out += "." + p
        else:
            out += p
    return out or "<root>"


_all_sentinels = []
_sentinel_lock = threading.Lock()


class RetraceSentinel:
    """Signature tracker for one jitted callable.

    Args:
      name: label for logs/metrics (``retrace.<name>.*`` in the
        registry).
      bucketed: argument names/paths whose SHAPE legitimately varies
        (prefill length buckets) — shape-only changes there are
        expected compiles.
      optional: argument names whose PRESENCE may vary (the optional
        segment-id arg: None and array each compile once, expected).
      strict: True/False, or None to follow the global
        `set_strict_retrace` toggle.
    """

    def __init__(self, name, bucketed=(), optional=(), strict=None,
                 registry=None):
        self.name = name
        self.bucketed = tuple(bucketed)
        self.optional = tuple(optional)
        self.strict = strict
        self._registry = registry if registry is not None else _registry()
        self._lock = threading.Lock()
        self._keys = {}          # signature key -> index
        # index -> {leaf path: leaf sig}: small strings/tuples only —
        # holding the args themselves would pin every model/state array
        # the step was ever called with
        self._pathmaps = []
        self.calls = 0
        self.hits = 0
        self.unexpected = 0
        self.events = []
        with _sentinel_lock:
            _all_sentinels.append(weakref.ref(self))

    # -- probe surface ---------------------------------------------------
    @property
    def signatures(self):
        """Distinct signatures seen = expected executable count."""
        return len(self._keys)

    def stats(self):
        return {"name": self.name, "calls": self.calls,
                "signatures": self.signatures, "hits": self.hits,
                "unexpected": self.unexpected,
                "events": list(self.events)}

    # -- the per-call check ----------------------------------------------
    def observe(self, args, names=None):
        """Record one dispatch of the watched callable with ``args``
        (any pytree; typically the exact tuple passed to the jitted
        function). Returns the retrace event dict for a new signature
        (None on a cache hit)."""
        if not enabled():
            return None
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(args)
        key = (treedef, tuple(_leaf_sig(l) for l in leaves))
        try:
            hash(key[1])
        except TypeError:      # unhashable sharding object: degrade
            key = (treedef, tuple(map(repr, key[1])))
        with self._lock:
            self.calls += 1
            if key in self._keys:
                self.hits += 1
                return None
            first = not self._keys
            self._keys[key] = len(self._keys)
        pathmap = {
            _format_path(p, names): _leaf_sig(l)
            for p, l in jax.tree_util.tree_flatten_with_path(args)[0]}
        with self._lock:
            self._pathmaps.append(pathmap)
        self._registry.gauge(f"retrace.{self.name}.signatures").set(
            self.signatures)
        if first:
            return None
        event = self._attribute(pathmap)
        if not event["expected"]:
            with self._lock:
                self.unexpected += 1
                self.events.append(event)
                del self.events[:-64]
            self._registry.counter(
                f"retrace.{self.name}.unexpected").inc()
            # a whole-state placement shift can touch hundreds of
            # leaves — log the first few, count the rest
            shown = event["changes"][:6]
            more = len(event["changes"]) - len(shown)
            msg = (f"unexpected recompile of {self.name} "
                   f"(signature #{self.signatures}): "
                   + "; ".join(shown)
                   + (f" (+{more} more changed leaves)" if more else ""))
            logger.warning(msg)
            try:
                from .flight_recorder import recorder

                recorder().note("retrace", name=self.name,
                                changes=event["changes"])
            except Exception:
                pass
            strict = self.strict if self.strict is not None else _strict
            if strict:
                # the dispatch is being REFUSED — unregister the bad
                # signature so a retry re-detects (and re-raises)
                # instead of counting as a cache hit and silently
                # compiling the drifted program
                with self._lock:
                    if self._keys.get(key) == len(self._keys) - 1:
                        del self._keys[key]
                        self._pathmaps.pop()
                self._registry.gauge(
                    f"retrace.{self.name}.signatures").set(
                    self.signatures)
                raise RetraceError(msg)
        else:
            with self._lock:
                self.events.append(event)
                del self.events[:-64]
        return event

    # -- attribution -----------------------------------------------------
    def _attribute(self, new_paths):
        """Diff the new signature against the closest seen one and name
        the changed leaves."""
        with self._lock:
            candidates = self._pathmaps[:-1]
        best = None
        for old_paths in candidates:
            diffs = self._diff(old_paths, new_paths)
            if best is None or len(diffs) < len(best):
                best = diffs
        diffs = best or []
        changes, expected = [], bool(diffs)
        for path, field, old, new in diffs:
            if len(changes) < 128:       # bound the stored event
                changes.append(f"{path}: {field} {old} -> {new}")
            head = path.split(".")[0].split("[")[0]
            if field == "presence" and head in self.optional:
                continue
            if field == "shape" and head in self.bucketed:
                continue
            expected = False
        return {"name": self.name, "signature_index": self.signatures,
                "changes": changes, "expected": expected}

    @staticmethod
    def _diff(old_paths, new_paths):
        diffs = []
        for path in sorted(set(old_paths) | set(new_paths)):
            o, n = old_paths.get(path), new_paths.get(path)
            if o is None or n is None:
                diffs.append((path, "presence",
                              "absent" if o is None else "present",
                              "present" if o is None else "absent"))
                continue
            if o == n:
                continue
            od, nd = _describe(o), _describe(n)
            before = len(diffs)
            for f in _FIELDS:
                if od[f] != nd[f]:
                    diffs.append((path, f, od[f], nd[f]))
            if len(diffs) == before:
                # signatures differ but every described field matches
                # (e.g. distinct-but-equivalent sharding objects)
                diffs.append((path, "placement",
                              repr(o)[:120], repr(n)[:120]))
        return diffs


def retrace_summary():
    """{sentinel name: stats} over every live sentinel — the one-call
    clean-run receipt the selftest lanes record (total unexpected must
    be 0)."""
    out, total = {}, 0
    with _sentinel_lock:
        refs = list(_all_sentinels)
    for ref in refs:
        s = ref()
        if s is None:
            continue
        st = s.stats()
        st.pop("events", None)
        # several instances may share a class name (one per engine)
        key = st["name"]
        if key in out:
            for f in ("calls", "signatures", "hits", "unexpected"):
                out[key][f] += st[f]
        else:
            out[key] = st
        total += st["unexpected"]
    return {"sentinels": out, "total_unexpected": total}
