"""Core framework: dtype, device, Tensor, autograd, RNG.

The TPU-native analog of paddle/phi/core + paddle/fluid/eager.
"""
import jax as _jax

# float64 / int64 support (Paddle defaults python ints to int64); TPU code
# paths stay bf16/f32 by construction (creation ops default to float32).
_jax.config.update("jax_enable_x64", True)

# True-f32 dot/conv accumulation: jax's "default" precision lowers f32 matmul
# to one-pass bf16 on MXU-class hardware, which breaks Paddle f32 semantics.
# bf16 inputs (the AMP/bench path) are unaffected by this setting.
_jax.config.update("jax_default_matmul_precision", "float32")

from .dtype import (  # noqa: E402
    DType,
    bool_,
    uint8,
    int8,
    int16,
    int32,
    int64,
    float16,
    bfloat16,
    float32,
    float64,
    complex64,
    complex128,
    convert_dtype,
    to_jax_dtype,
    set_default_dtype,
    get_default_dtype,
)
from .device import (  # noqa: E402
    Place,
    CPUPlace,
    TPUPlace,
    CUDAPlace,
    CUDAPinnedPlace,
    set_device,
    get_device,
    current_place,
    default_jax_device,
    device_count,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
)
from .tensor import Tensor, to_tensor  # noqa: E402
from .autograd import (  # noqa: E402
    no_grad,
    enable_grad,
    is_grad_enabled,
    set_grad_enabled,
    run_backward,
    apply_op,
    GradNode,
)
from .random import (  # noqa: E402
    Generator,
    seed,
    get_rng_state,
    set_rng_state,
    default_generator,
)
