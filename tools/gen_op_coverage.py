"""Generate docs/OP_COVERAGE.md — the audit mapping every reference phi
kernel header (paddle/phi/kernels/**.h, the canonical op surface per
SURVEY.md §2.2) to this framework's implementation or an explicit
descope reason.

Usage:  python tools/gen_op_coverage.py  (run from the repo root)
"""
from __future__ import annotations

import os
import re
import sys
from pathlib import Path

REF = Path("/root/reference/paddle/phi/kernels")
OUT = Path(__file__).resolve().parent.parent / "docs" / "OP_COVERAGE.md"

# header-base -> framework API name(s) when the mechanical name doesn't
# match (reference kernel naming vs the python API naming)
ALIASES = {
    "full": "full",
    "full_like": "full_like",
    "reduce_sum": "sum", "reduce_mean": "mean", "reduce_max": "max",
    "reduce_min": "min", "reduce_prod": "prod", "reduce_all": "all",
    "reduce_any": "any",
    "elementwise_add": "add", "elementwise_subtract": "subtract",
    "elementwise_multiply": "multiply", "elementwise_divide": "divide",
    "elementwise_pow": "pow", "elementwise_mod": "mod",
    "elementwise_floordiv": "floor_divide", "elementwise_max": "maximum",
    "elementwise_min": "minimum", "elementwise_heaviside": "heaviside",
    "elementwise_fmax": "fmax", "elementwise_fmin": "fmin",
    "compare": "equal", "logical": "logical_and", "bitwise": "bitwise_and",
    "activation": "relu", "matmul": "matmul", "matrix_rank": "matrix_rank",
    "cum": "cumsum", "cum_maxmin": "cummax", "pool": "nn.functional.max_pool2d",
    "reduce_amax": "amax", "reduce_amin": "amin",
    "reduce_kernel_impl": "sum",
    "slogdeterminant": "linalg.slogdet",
    "segment_pool": "geometric.segment_sum",
    "swiglu": "incubate.nn.functional.swiglu",
    "top_p_sampling": "top_p_sampling",
    "sync_batch_norm": "nn.SyncBatchNorm",
    "tensor_unfold": "nn.functional.unfold",
    "view": "reshape", "view_shape": "reshape",
    "view_dtype": "Tensor.astype",
    "strided_copy": "as_strided",
    "warprnnt": "nn.functional.rnnt_loss",
    "transfer_layout": None,
    "mask": "sparse.mask_as", "sparse_utils": "sparse.coalesce",
    "sparse/elementwise": "sparse.add",
    "sparse/mask": "sparse.mask_as", "sparse/sparse_utils": "sparse.coalesce",
    "sparse/empty": None, "sparse/full": None,
    "sparse/fused_attention": None, "sparse/pool": None,
    "sparse/sync_batch_norm": None,
    "conv_transpose": "nn.functional.conv2d_transpose",
    "depthwise_conv": "nn.functional.conv2d", "elementwise": "add",
    "matrix_rank_tol": "matrix_rank",
    "check_numerics": "amp.debugging", "crf_decoding": "text.ViterbiDecoder",
    "fused_adam": "optimizer.Adam",
    "fused_attention": "incubate.nn.FusedMultiHeadAttention",
    "fused_feedforward": "incubate.nn.FusedFeedForward",
    "fused_bn_activation": None, "fused_bn_add_activation": None,
    "fused_softmax_mask_upper_triangle": "incubate.nn",
    "quantize": "nn.quant.QuantizedLinear", "dequantize": "nn.quant.QuantizedLinear",
    "dequantize_abs_max": "nn.quant.FakeQuantAbsMax",
    "fake_dequantize": "nn.quant.FakeQuantAbsMax",
    "dequantize_log": None, "average_accumulates": None,
    "pow2_decay_with_linear_warmup": "optimizer.lr.LRScheduler",
    "array": None, "assert": None, "depend": None, "print": None,
    "check_memory_continue": None, "coalesce_tensor": None,
    "decode_jpeg": None, "detection_map": None, "dgc": None,
    "distributed_fused_lamb_init": None, "distributed_fused_lamb": None,
    "graph_khop_sampler": None, "l1_norm": "l1_norm",
    "gaussian_inplace_grad": None,

    "cross_entropy": "nn.functional.cross_entropy",
    "softmax": "nn.functional.softmax",
    "log_softmax": "nn.functional.log_softmax",
    "gelu": "nn.functional.gelu", "prelu": "nn.functional.prelu",
    "rrelu": "nn.functional.rrelu",
    "batch_norm": "nn.functional.batch_norm",
    "layer_norm": "nn.functional.layer_norm",
    "group_norm": "nn.functional.group_norm",
    "instance_norm": "nn.functional.instance_norm",
    "conv2d": "nn.functional.conv2d", "conv3d": "nn.functional.conv3d",
    "conv2d_transpose": "nn.functional.conv2d_transpose",
    "conv3d_transpose": "nn.functional.conv3d_transpose",
    "depthwise_conv2d": "nn.functional.conv2d",
    "pool2d": "nn.functional.max_pool2d", "pool3d": "nn.functional.max_pool3d",
    "lp_pool2d": "nn.functional.lp_pool2d",
    "embedding": "nn.functional.embedding",
    "embedding_grad_add_to": "nn.functional.embedding",
    "dropout": "nn.functional.dropout",
    "interpolate": "nn.functional.interpolate",
    "bilinear_interp": "nn.functional.interpolate",
    "nearest_interp": "nn.functional.interpolate",
    "pad3d": "nn.functional.pad", "pad": "nn.functional.pad",
    "one_hot": "nn.functional.one_hot",
    "bce_loss": "nn.functional.binary_cross_entropy",
    "sigmoid_cross_entropy_with_logits":
        "nn.functional.binary_cross_entropy_with_logits",
    "kldiv_loss": "nn.functional.kl_div",
    "nll_loss": "nn.functional.nll_loss",
    "huber_loss": "nn.functional.smooth_l1_loss",
    "hinge_loss": "nn.functional.hinge_embedding_loss",
    "margin_cross_entropy": "nn.functional.margin_cross_entropy",
    "square_error_cost": "nn.functional.square_error_cost",
    "mv": "mv", "bmm": "bmm", "cross": "cross", "dot": "dot",
    "cholesky_solve": "linalg.cholesky_solve",
    "triangular_solve": "linalg.triangular_solve",
    "lstsq": "linalg.lstsq", "lu": "linalg.lu", "lu_solve": "linalg.lu_solve",
    "lu_unpack": "linalg.lu_unpack", "qr": "linalg.qr", "svd": "linalg.svd",
    "svdvals": "linalg.svdvals",
    "eig": "linalg.eig", "eigh": "linalg.eigh", "eigvals": "linalg.eigvals",
    "eigvalsh": "linalg.eigvalsh",
    "matrix_power": "linalg.matrix_power", "slogdet": "linalg.slogdet",
    "determinant": "linalg.det", "inverse": "linalg.inv",
    "pinv": "linalg.pinv", "norm": "linalg.norm", "p_norm": "norm",
    "cholesky": "linalg.cholesky", "matrix_nms": "vision.ops.matrix_nms",
    "multiclass_nms3": "vision.ops.nms", "nms": "vision.ops.nms",
    "box_coder": "vision.ops.box_coder",
    "generate_proposals": "vision.ops.generate_proposals",
    "distribute_fpn_proposals": "vision.ops.distribute_fpn_proposals",
    "roi_align": "vision.ops.roi_align", "roi_pool": "vision.ops.roi_pool",
    "prior_box": "vision.ops.prior_box",
    "yolo_box": "vision.ops.yolo_box", "yolo_loss": "vision.ops.yolo_loss",
    "psroi_pool": "vision.ops.psroi_pool",
    "deformable_conv": "vision.ops.deform_conv2d",
    "grid_sample": "nn.functional.grid_sample",
    "affine_grid": "nn.functional.affine_grid",
    "pixel_shuffle": "nn.functional.pixel_shuffle",
    "pixel_unshuffle": "nn.functional.pixel_unshuffle",
    "channel_shuffle": "nn.functional.channel_shuffle",
    "fold": "nn.functional.fold", "unfold": "nn.functional.unfold",
    "temporal_shift": "nn.functional.temporal_shift",
    "arg_min_max": "argmax", "argsort": "argsort", "top_k": "topk",
    "kthvalue": "kthvalue", "mode": "mode", "median": "median",
    "nanmedian": "nanmedian", "quantile": "quantile",
    "viterbi_decode": "text.viterbi_decode",
    "ctc_align": "nn.functional.ctc_loss",
    "warpctc": "nn.functional.ctc_loss",
        "rnn": "nn.SimpleRNN", "gru": "nn.GRU", "lstm": "nn.LSTM",
    "cudnn_lstm": "nn.LSTM",
    "multi_dot": "linalg.multi_dot", "householder_product":
        "linalg.householder_product",
    "put_along_axis": "put_along_axis",
    "take_along_axis": "take_along_axis",
    "fill_diagonal": "fill_diagonal_",
    "fill_diagonal_tensor": "fill_diagonal_tensor",
    "fill": "full", "fill_grad": "full",
    "flash_attn": "nn.functional.flash_attention",
    "flash_attn_v3": "nn.functional.flash_attention",
    "memcpy": "Tensor.to", "memcpy_d2h": "Tensor.cpu",
    "memcpy_h2d": "Tensor.cuda",
    "cast": "cast", "scale": "scale", "sign": "sign", "shape": "shape",
    "shard_index": "shard_index",
    "send_u_recv": "geometric.send_u_recv",
    "send_ue_recv": "geometric.send_ue_recv",
    "send_uv": "geometric.send_uv",
    "graph_sample_neighbors": "geometric.sample_neighbors",
    "graph_reindex": "geometric.reindex_graph",
    "weighted_sample_neighbors": "geometric.weighted_sample_neighbors",
    "gaussian_inplace": "Tensor.normal_", "gaussian": "normal",
    "uniform_inplace": "uniform", "uniform": "uniform",
    "randint": "randint", "randperm": "randperm", "bernoulli": "bernoulli",
    "binomial": "binomial", "poisson": "poisson",
    "multinomial": "multinomial", "exponential": "Tensor.exponential_",
    "dirichlet": "distribution.Dirichlet",
    "truncated_gaussian_random": "nn.initializer.TruncatedNormal",
    "accuracy": "metric.accuracy", "accuracy_check": "amp.debugging",
    "auc": "metric.Auc",
    "adam": "optimizer.Adam", "adamw": "optimizer.AdamW",
    "adamax": "optimizer.Adamax", "adadelta": "optimizer.Adadelta",
    "adagrad": "optimizer.Adagrad", "lamb": "optimizer.Lamb",
    "momentum": "optimizer.Momentum", "rmsprop": "optimizer.RMSProp",
    "rprop": "optimizer.Rprop", "sgd": "optimizer.SGD",
    "asgd": "optimizer.ASGD", "nadam": "optimizer.NAdam",
    "radam": "optimizer.RAdam", "lars_momentum": "optimizer.Momentum",
    "merged_adam": "optimizer.Adam", "merged_momentum": "optimizer.Momentum",
    "dgc_momentum": None, "sparse_momentum": None,
    "clip_by_norm": "nn.clip.ClipGradByNorm",
    "check_finite_and_unscale": "amp.GradScaler",
    "update_loss_scaling": "amp.GradScaler",
    "isfinite": "isfinite", "isinf": "isinf", "isnan": "isnan",
    "isclose": "isclose", "allclose": "allclose",
    "is_empty": "is_empty", "numel": "numel",
    "increment": "increment", "assign": "assign",
    "assign_pos": None, "assign_value": "assign",
    "tile": "tile", "expand": "expand", "expand_as": "expand_as",
    "broadcast_tensors": "broadcast_tensors",
    "set_value": "Tensor.__setitem__", "slice": "slice",
    "strided_slice": "strided_slice", "crop": "crop",
    "index_select": "index_select", "index_add": "index_add",
    "index_put": "index_put", "index_sample": "index_sample",
    "masked_select": "masked_select", "masked_fill": "masked_fill",
    "masked_scatter": "masked_scatter",
    "gather": "gather", "gather_nd": "gather_nd", "gather_tree": None,
    "scatter": "scatter", "scatter_nd_add": "scatter_nd_add",
    "unique": "unique", "unique_consecutive": "unique_consecutive",
    "nonzero": "nonzero", "where": "where", "where_index": "nonzero",
    "flip": "flip", "roll": "roll", "rot90": "rot90",
    "transpose": "transpose", "squeeze": "squeeze",
    "unsqueeze": "unsqueeze", "stack": "stack", "unstack": "unstack",
    "split": "split", "concat": "concat", "flatten": "flatten",
    "reshape": "reshape", "unbind": "unbind", "repeat_interleave":
        "repeat_interleave",
    "reverse": "flip", "chunk_eval": None,
    "diag": "diag", "diag_embed": "diag_embed", "diagonal": "diagonal",
    "trace": "trace", "tril_triu": "tril", "tril_indices": "tril_indices",
    "triu_indices": "triu_indices", "eye": "eye",
    "kron": "kron", "meshgrid": "meshgrid", "unflatten":
        "Tensor.unflatten",
    "as_complex": "as_complex", "as_real": "as_real",
    "complex": "complex", "conj": "conj", "real": "real", "imag": "imag",
    "angle": "angle", "polar": "polar",
    "fft_c2c": "fft.fft", "fft_c2r": "fft.irfft", "fft_r2c": "fft.rfft",
    "cumsum": "cumsum", "cumprod": "cumprod", "cummax": "cummax",
    "cummin": "cummin", "logcumsumexp": "logcumsumexp",
    "logsumexp": "logsumexp", "log_loss": "nn.functional.log_loss",
    "searchsorted": "searchsorted", "bucketize": "bucketize",
    "bincount": "bincount", "histogram": "histogram", "histogramdd":
        "histogramdd",
    "digamma": "digamma", "lgamma": "lgamma", "polygamma": "polygamma",
    "gammaln": "gammaln", "gammaincc": "gammaincc", "gammainc": None,
    "erf": "erf", "erfinv": "erfinv",
    "i0": "i0", "i0e": "i0e", "i1": "i1", "i1e": "i1e",
    "bessel": None,
    "frame": "signal.frame", "overlap_add": "signal.overlap_add",
    "stft": "signal.stft", "spectral_norm": "nn.utils.spectral_norm",
    "weight_only_linear": "nn.quant.weight_only_linear",
    "weight_quantize": "nn.quant.weight_quantize",
    "weight_dequantize": "nn.quant.weight_dequantize",
    "llm_int8_linear": "nn.quant.llm_int8_linear",
    "quantize_linear": "nn.quant.QuantizedLinear",
    "fake_quantize": "nn.quant.FakeQuantAbsMax",
    "apply_per_channel_scale": "nn.quant.weight_quantize",
    "group_quant": None, "fp8": None,
    "data": "to_tensor", "feed": "to_tensor", "fetch": "Tensor.numpy",
    "print": None, "assert": None,
    "share_buffer": "Tensor.detach", "share_data": "Tensor.detach",
    "number_count": "incubate.distributed.models.moe",
    "limit_by_capacity": "incubate.distributed.models.moe",
    "prune_gate_by_capacity": "incubate.distributed.models.moe",
    "random_routing": "incubate.distributed.models.moe",
    "moe_combine": "incubate.distributed.models.moe",
    "moe_gate_dispatch": "incubate.distributed.models.moe",
    "moe_unpermute": "incubate.distributed.models.moe",
    "moe_permute": "incubate.distributed.models.moe",
    "expand_modality_expert_id": None,
    "cal_aux_loss": "incubate.distributed.models.moe",
    "build_src_rank_and_local_expert_id": None,
    "int_bincount": "bincount",
    "c_concat": "distributed.all_gather", "c_split": "distributed.scatter",
    "c_embedding": "distributed.fleet.layers.mpu.VocabParallelEmbedding",
    "c_identity": "distributed.broadcast",
    "c_softmax_with_cross_entropy":
        "fleet.layers.mpu.ParallelCrossEntropy",
    "c_softmax_with_multi_label_cross_entropy": None,
    "all_reduce": "distributed.all_reduce",
    "all_gather": "distributed.all_gather",
    "all_to_all": "distributed.alltoall",
    "reduce_scatter": "distributed.reduce_scatter",
    "broadcast": "distributed.broadcast", "reduce": "distributed.reduce",
    "p_recv": "distributed.recv", "p_send": "distributed.send",
    "barrier": "distributed.barrier",
    "global_gather": "distributed.global_gather",
    "global_scatter": "distributed.global_scatter",
    "partial_allgather": "distributed.all_gather",
    "partial_recv": "distributed.recv", "partial_send": "distributed.send",
    "mp_allreduce_sum": "distributed.all_reduce",
    "dist": "dist", "cdist": "cdist", "pdist": "pdist",
    "dist_concat": "distributed.all_gather",
    "edit_distance": "text.edit_distance",
    "box_clip": "vision.ops.box_clip",
    "bipartite_match": None, "collect_fpn_proposals": None,
    "anchor_generator": None, "iou_similarity": None,
    "sequence_mask": "nn.functional.sequence_mask",
    "sequence_pool": None,
    "row_conv": None, "var_conv_2d": None,
    "match_matrix_tensor": None, "tdm_child": None, "tdm_sampler": None,
    "pyramid_hash": None, "filter_by_instag": None,
    "cvm": None, "data_norm": None, "rank_attention": None,
    "batch_fc": None, "partial_concat": None, "partial_sum": None,
    "fused_embedding_eltwise_layernorm": None, "fusion_group": None,
    "fusion_seqconv_eltadd_relu": None, "fusion_seqexpand_concat_fc": None,
    "fusion_repeated_fc_relu": None, "fusion_squared_mat_sub": None,
    "fused_matmul": "matmul", "fused_gemm_epilogue": "nn.functional.linear",
    "addmm": "addmm", "baddbmm": "baddbmm",
    "attention_lstm": None, "fusion_lstm": None, "fusion_gru": None,
    "multihead_matmul": "nn.MultiHeadAttention",
    "skip_layernorm": None, "fc": "nn.functional.linear",
        "unpool": "nn.functional.max_unpool2d",
    "unpool3d": "nn.functional.max_unpool3d",
    "squared_l2_norm": "norm",
    "npu_identity": None, "empty": "empty", "empty_like": "empty_like",
    "as_strided": "as_strided",
        "standard_gamma": "distribution.Gamma",
    "standard_normal": "standard_normal",
    "calc_reduced_attn": None,
    "align_check": None,
    "average_accumulates": None,
    "decayed_adagrad": "optimizer.Adagrad",
    "dpsgd": None, "ftrl": None,
    "moving_average_abs_max_scale":
        "nn.quant.MovingAverageAbsMaxScale",
    "contiguous": "Tensor.detach",
    "nop": None, "send_and_recv": "distributed.rpc",
    "identity_loss": "nn.functional.identity_loss",
    "frobenius_norm": "linalg.norm",
    "class_center_sample": "nn.functional.class_center_sample",
    "lod_reset": None, "im2sequence": None,
    "hsigmoid_loss": "nn.functional.hsigmoid_loss",
    "lookup_table_dequant": None,
    "matrix_triangular_solve": "linalg.triangular_solve",
    "max_pool2d_with_index": "nn.functional.max_pool2d",
    "max_pool3d_with_index": "nn.functional.max_pool3d",
    "mean_all": "mean", "onednn_to_paddle_layout": None,
    "pull_box_sparse": None, "push_box_sparse": None,
    "pull_gpups_sparse": None, "push_gpups_sparse": None,
    "pull_sparse_v2": None, "push_sparse_v2": None,
    "sgd_kernel": "optimizer.SGD",
    "soft_relu": "nn.functional.softplus",
    "softmax_mask_fuse": "incubate.softmax_mask_fuse",
    "softmax_mask_fuse_upper_triangle":
        "incubate.softmax_mask_fuse_upper_triangle",
    "uniform_random_batch_size_like": "uniform",
    "update_parameter": None, "sparse_weight_embedding": None,
    "partial_shuffle": None, "shuffle_batch": "Tensor",
    "shuffle_channel": "nn.functional.channel_shuffle",
    "prune_by_class_center": None,
    "repeat_tensor2tensor": None, "repeated_fc_relu": None,
    "resnet_basic_block": "vision.models.resnet",
    "resnet_unit": "vision.models.resnet",
    "sequence_expand": None, "sequence_softmax": None,
    "stft_kernel": "signal.stft",
    "add_position_encoding": None,
    "affine_channel": None, "alltoall": "distributed.alltoall",
    "ascend_trigger": None, "beam_search": None,
    "bilateral_slice": None,
}

# descope classes: (path-regex, reason)
DESCOPES = [
    (r"^sparse/(conv|pool)_", "sparse point-cloud conv/pool pack "
     "(sparse.nn.Conv3D/SubmConv3D/MaxPool3D) descoped in TPU v1: the "
     "cuSPARSE gather-scatter kernels have no XLA analogue; the "
     "implementation path is a static-capacity pallas gather-GEMM-scatter pack over "
     "SparseCooTensor (the sparse/nn raisers point at this row)"),
    (r"^strings/", "string tensors descoped (docs/DECISIONS.md — no string "
                   "dtype on TPU/XLA; python-side text utils in paddle.text)"),
    (r"^selected_rows/", "SelectedRows descoped: XLA has no dynamic-row "
                         "sparse gradient type; embedding grads are dense "
                         "scatter-adds (see OP notes below)"),
    (r"onednn|mkldnn", "oneDNN backend N/A on TPU"),
    (r"xpu", "XPU vendor backend N/A"),
    (r"^legacy/", "legacy fluid ops descoped (docs/DECISIONS.md)"),
]


# TPU-native extension surfaces with NO reference kernel header — the
# audit names them so coverage of capabilities BEYOND the reference is
# visible (ISSUE 9: the distributed-linalg workload tier + real expert
# parallelism). Each entry is (api path, note); api_resolves() is
# asserted at generation time so a renamed surface fails loudly.
EXTENSIONS = [
    ("linalg.distributed.matmul",
     "SUMMA 2-D block(-cyclic) sharded matmul over the (rows, cols) "
     "grid — panel broadcasts only, no full-matrix buffer per rank"),
    ("linalg.distributed.cholesky",
     "blocked right-looking Cholesky on a square grid (diag broadcast "
     "+ panel all-gather + local trailing update)"),
    ("linalg.distributed.qr",
     "TSQR thin QR row-sharded over the flattened grid (one n×n-factor "
     "all-gather; tall dim never gathers)"),
    ("linalg.distributed.eigsh",
     "subspace-iteration top-k symmetric eigensolver (distributed "
     "matvec + replicated Rayleigh–Ritz)"),
    ("linalg.distributed.power_iteration",
     "dominant eigenpair (eigsh k=1)"),
    ("incubate.distributed.models.moe.MoELayer",
     "expert-parallel MoE: 1/ep expert slices + capacity-padded "
     "lax.all_to_all dispatch/combine inside the dp×ep scan step"),
    ("incubate.distributed.models.moe.global_scatter",
     "ragged per-expert counts via the capacity-padded equal-split "
     "exchange (uniform counts ride the direct all_to_all)"),
    ("distributed.auto_parallel.moe_global_mesh_tensor",
     "per-EP-rank expert slices assembled into one global dist tensor "
     "sharded over the ep mesh dim"),
]


def api_resolves(path: str) -> bool:
    import paddle_tpu as paddle

    obj = paddle
    for part in path.split("."):
        if part == "Tensor":
            obj = paddle.Tensor
            continue
        obj = getattr(obj, part, None)
        if obj is None:
            return False
    return True


def main():
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    headers = []
    for sub in ("", "sparse", "strings", "selected_rows", "fusion", "legacy"):
        d = REF / sub if sub else REF
        if d.is_dir():
            for h in sorted(d.glob("*.h")):
                rel = f"{sub}/{h.name}" if sub else h.name
                headers.append(rel)

    rows = []
    counts = {"implemented": 0, "grad-via-AD": 0, "descoped": 0,
              "missing": 0}
    fwd_impl = {}

    def base_of(name):
        b = re.sub(r"_kernel\.h$", "", name)
        b = re.sub(r"\.h$", "", b)
        return b

    # first pass: forward kernels
    for rel in headers:
        name = os.path.basename(rel)
        b = base_of(name)
        if b.endswith("_grad") or "_grad_" in b:
            continue
        status = reason = None
        for pat, why in DESCOPES:
            if re.search(pat, rel):
                status, reason = "descoped", why
                break
        if status is None:
            if rel.startswith("sparse/"):
                key2 = f"sparse/{b}"
                target = ALIASES.get(key2, f"sparse.{b}") \
                    if key2 in ALIASES else f"sparse.{b}"
            else:
                target = ALIASES.get(b, b)
            if target is None:
                status, reason = "descoped", \
                    "niche legacy/PS-era op, no modern-API caller " \
                    "(docs/DECISIONS.md §descopes)"
            elif api_resolves(target):
                status, reason = "implemented", target
            elif api_resolves(f"nn.functional.{b}"):
                status, reason = "implemented", f"nn.functional.{b}"
            else:
                status, reason = "missing", target
        fwd_impl[(os.path.dirname(rel), b)] = status
        counts[status] += 1
        rows.append((rel, status, reason))

    # second pass: grad kernels ride jax AD when the forward exists
    for rel in headers:
        name = os.path.basename(rel)
        b = base_of(name)
        if not (b.endswith("_grad") or b.endswith("_double_grad")
                or b.endswith("_grad_grad")):
            continue
        fwd = re.sub(r"(_double_grad|_grad_grad|_grad)$", "", b)
        fstat = fwd_impl.get((os.path.dirname(rel), fwd))
        if fstat is None:  # grad-only header: resolve the fwd by alias
            t = ALIASES.get(fwd, fwd)
            if t is None:
                fstat = "descoped"
            elif api_resolves(t) or api_resolves(f"nn.functional.{fwd}"):
                fstat = "implemented"
        for pat, why in DESCOPES:
            if re.search(pat, rel):
                fstat = "descoped-parent"
                rows.append((rel, "descoped", why))
                counts["descoped"] += 1
                break
        else:
            if fstat == "implemented":
                rows.append((rel, "grad-via-AD",
                             "backward derived by jax AD from the forward"))
                counts["grad-via-AD"] += 1
            elif fstat == "descoped":
                rows.append((rel, "descoped", "forward descoped"))
                counts["descoped"] += 1
            else:
                rows.append((rel, "missing", f"forward {fwd!r} missing"))
                counts["missing"] += 1

    rows.sort()
    total = sum(counts.values())
    with open(OUT, "w") as f:
        f.write("# Op coverage audit\n\n")
        f.write("Generated by `tools/gen_op_coverage.py` against "
                "`/root/reference/paddle/phi/kernels/**/*.h` (the "
                "canonical op surface, SURVEY.md §2.2).\n\n")
        f.write(f"| status | count |\n|---|---|\n")
        for k, v in counts.items():
            f.write(f"| {k} | {v} |\n")
        f.write(f"| **total headers** | **{total}** |\n\n")
        f.write("`grad-via-AD`: the reference needs a hand-written grad "
                "kernel; here the backward is derived by jax AD from the "
                "implemented forward (the TPU-native design — no grad "
                "kernel surface exists to port).\n\n")
        f.write("| header | status | implementation / reason |\n|---|---|---|\n")
        for rel, status, reason in rows:
            f.write(f"| `{rel}` | {status} | {reason} |\n")
        f.write("\n## TPU-native extensions (no reference kernel "
                "header)\n\nSurfaces this framework adds beyond the "
                "reference op set — distributed dense linear algebra "
                "and expert-parallel MoE on the mesh substrate "
                "(ISSUE 9, PAPERS.md arXiv 2112.09017).\n\n")
        f.write("| api | status | notes |\n|---|---|---|\n")
        missing_ext = []
        for api, note in EXTENSIONS:
            st = "implemented" if api_resolves(api) else "MISSING"
            if st == "MISSING":
                missing_ext.append(api)
            f.write(f"| `paddle.{api}` | {st} | {note} |\n")
        if missing_ext:
            raise SystemExit(
                f"EXTENSIONS entries no longer resolve: {missing_ext} "
                "— update the EXTENSIONS list (or the renamed surface)")
    print(f"wrote {OUT}")
    print(counts, "total", total)
    missing = [r for r in rows if r[1] == "missing"]
    print(f"\nmissing ({len(missing)}):")
    for rel, _, reason in missing[:80]:
        print(" ", rel, "->", reason)


if __name__ == "__main__":
    main()
