"""ServingEngine: the continuous-batching loop over the compiled steps.

One engine owns one (model, PagedKVCache) pair and exactly TWO compiled
programs in steady state: a `ServeDecodeStep` over the full slot batch
(traced once — admissions, preemptions and retirements only refresh its
inputs) and a `ChunkPrefillStep` per chunk bucket (a handful of
power-of-two sizes). Every `step()`:

1. **admit** — the scheduler moves queue-head requests into free slots
   (capacity probed via `can_allocate` before commit);
2. **chunk-prefill** — at most `prefill_chunks_per_step` bounded chunks
   of the oldest resident prompt run between decode steps, so TTFT for
   new arrivals stays bounded while resident sequences keep streaming;
3. **decode** — one token for every decode-active slot (per-slot RNG
   streams keyed on (request seed, context length): a request's tokens
   never depend on its batch neighbours);
4. **stream/retire** — tokens push to handles (callback / poll /
   `stream()` iterator); EOS or token-budget retirement frees pages
   immediately.

The cache's device state threads functionally through the steps with
the KV pools donated (HBM-neutral steady state); the host bookkeeping
(page tables, active flags, free lists) is refreshed into the step
inputs each call — an input refresh, never a retrace.
"""
from __future__ import annotations

import time

import numpy as np

from ..inference.kv_cache import PagedKVCache
from ..jit.decode_step import (ChunkPrefillStep, ServeDecodeStep,
                               _split_state)
from ..jit.train_step import _tree_data
from .metrics import ServingMetrics
from .request import FinishReason, Request, RequestHandle, RequestState
from .scheduler import RequestScheduler

__all__ = ["ServingEngine"]


class ServingEngine:
    def __init__(self, model, max_slots=8, max_len=256, page_size=16,
                 num_pages=None, chunk_size=64,
                 prefill_chunks_per_step=1, prefill_batch=4,
                 decode_burst=1, do_sample=False, top_k=0, top_p=1.0,
                 temperature=1.0, compiled=True, cache_dtype=None,
                 donate=True, admit_watermark="auto",
                 clock=time.perf_counter):
        import jax.numpy as jnp

        cfg = model.config
        model.gpt._check_decodable()
        if max_len > cfg.max_position_embeddings:
            raise ValueError(
                f"max_len={max_len} exceeds max_position_embeddings="
                f"{cfg.max_position_embeddings}")
        self.model = model
        self.kind = "paged"
        self.max_slots = int(max_slots)
        self.max_len = int(max_len)
        self.page_size = int(page_size)
        self.chunk_size = int(chunk_size)
        self.prefill_chunks_per_step = int(prefill_chunks_per_step)
        # one chunk-prefill call advances up to this many prompts at
        # once (fixed batch dim, dummy rows masked to the trash page) —
        # amortizes the per-call cost that otherwise serializes
        # admissions under a deep queue
        self.prefill_batch = max(1, min(int(prefill_batch),
                                        self.max_slots))
        # decode_burst > 1 fuses that many decode steps INSIDE the
        # compiled ServeDecodeStep: one dispatch + one host sync per k
        # tokens (multi-step scheduling) — the host loop's per-call
        # cost is what dominates small decode steps. Streaming and
        # admission granularity coarsen to k steps; tokens a request
        # samples past its EOS/budget inside a burst are discarded.
        self.decode_burst = max(1, int(decode_burst))
        self.do_sample = bool(do_sample)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.temperature = float(temperature)
        self.compiled = bool(compiled)
        self.clock = clock
        self._cache_dtype = cache_dtype or jnp.float32
        self.pages_per_seq = -(-self.max_len // self.page_size)
        # full provisioning by default; pass a smaller pool to
        # oversubscribe (preemption reclaims pages under pressure)
        self.num_pages = int(num_pages or
                             1 + self.max_slots * self.pages_per_seq)
        self._params = list(model.parameters())
        self.cache = self._make_cache()
        self.metrics = ServingMetrics(clock=clock)
        self.scheduler = RequestScheduler(
            self.cache, self.metrics, admit_watermark=admit_watermark)
        self.prefill_step = ChunkPrefillStep(self, donate_cache=donate)
        self.decode_step = ServeDecodeStep(self, donate_cache=donate)
        bkts, b = [], 8
        while b < self.chunk_size:
            bkts.append(b)
            b *= 2
        self.chunk_buckets = tuple(bkts) + (self.chunk_size,)
        self._buffers, _ = _split_state(
            "paged", _tree_data(self.cache.state()))
        # per-slot host mirrors refreshed every step (plain input data)
        self._tokens = np.zeros((self.max_slots,), np.int32)
        self._seeds = np.zeros((self.max_slots,), np.uint32)
        self._rid = 0

    def _make_cache(self):
        cfg = self.model.config
        nh = cfg.num_attention_heads
        return PagedKVCache(
            cfg.num_layers, nh, cfg.hidden_size // nh,
            num_pages=self.num_pages, page_size=self.page_size,
            max_slots=self.max_slots, pages_per_seq=self.pages_per_seq,
            dtype=self._cache_dtype)

    # -- client surface ---------------------------------------------------
    def submit(self, prompt, max_new_tokens, priority=0,
               eos_token_id=None, seed=None, on_token=None
               ) -> RequestHandle:
        """Queue a request; returns a streaming handle immediately.
        Tokens arrive as the engine steps (`step()`/`run()`/`stream()`).
        """
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = int(prompt.size) + int(max_new_tokens)
        if total > self.max_len:
            raise ValueError(
                f"prompt {prompt.size} + {max_new_tokens} new tokens "
                f"exceeds the engine max_len {self.max_len}")
        if self.cache.pages_needed(total) > self.num_pages - 1:
            raise ValueError(
                f"request needs {self.cache.pages_needed(total)} pages "
                f"but the pool only has {self.num_pages - 1}")
        rid = self._rid
        self._rid += 1
        req = Request(rid, prompt, int(max_new_tokens),
                      priority=int(priority), eos_token_id=eos_token_id,
                      seed=int(seed) if seed is not None else rid)
        handle = RequestHandle(req, on_token=on_token)
        handle.arrival_seq = rid
        handle.submit_time = self.clock()
        self.scheduler.enqueue(handle)
        self.metrics.on_submit()
        return handle

    def step(self) -> bool:
        """One scheduler iteration: admit, <=N prefill chunks, one
        decode for all running sequences. Returns False when idle."""
        sched = self.scheduler
        try:
            for h in sched.admit():
                # full-width uint32: distinct seeds stay distinct
                # streams (per_slot_keys folds the raw 32-bit value)
                self._seeds[h.slot] = np.uint32(
                    h.request.seed & 0xFFFFFFFF)
            worked = False
            for _ in range(self.prefill_chunks_per_step):
                heads = sched.prefill_heads(self.prefill_batch)
                if not heads:
                    break
                self._run_prefill_chunk(heads)
                worked = True
            if sched.decode_slots():
                worked |= self._run_decode()
        except BaseException:
            self._recover()
            raise
        self.metrics.observe(len(sched.waiting), len(sched.running))
        return worked

    def run(self, max_steps=1_000_000):
        """Drive the loop until every submitted request finished."""
        steps = 0
        while self.scheduler.has_work():
            self.step()
            steps += 1
            if steps >= max_steps:
                raise RuntimeError(
                    f"serving loop did not drain in {max_steps} steps")
        return self.metrics.snapshot()

    def stream(self, handle: RequestHandle):
        """Generator yielding `handle`'s tokens as they are produced,
        stepping the engine (and every other resident request) along."""
        while True:
            for t in handle.new_tokens():
                yield t
            if handle.done:
                return
            if not self.scheduler.has_work():
                raise RuntimeError("request is not resident and the "
                                   "engine is idle")
            self.step()

    def compile_counts(self) -> dict:
        """Retrace probe surface: decode must stay at ONE trace across
        arbitrary admit/preempt/retire churn; prefill at most one trace
        per chunk bucket."""
        return {
            "decode_traces": self.decode_step.trace_count,
            "decode_executables": self.decode_step.cache_size(),
            "prefill_traces": self.prefill_step.trace_count,
            "prefill_executables": self.prefill_step.cache_size(),
            "chunk_buckets": list(self.chunk_buckets),
        }

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()

    def metrics_text(self) -> str:
        """Prometheus text exposition of this engine's metrics — the
        scrape endpoint body (ISSUE 12): counters/gauges plus TTFT and
        inter-token-latency summaries with p50/p90/p99 quantiles."""
        return self.metrics.expose()

    def retrace_stats(self) -> dict:
        """Sentinel receipts for both serving step programs."""
        return {"decode": self.decode_step.retrace_stats(),
                "prefill": self.prefill_step.retrace_stats()}

    def reset_metrics(self):
        """Fresh counters (e.g. after a compile warmup run) — the bench
        lanes measure steady-state serving, not trace time."""
        self.metrics = ServingMetrics(clock=self.clock)
        self.scheduler.metrics = self.metrics

    def warmup(self):
        """Compile every program the serving loop can hit — the decode
        step and one prefill program per chunk bucket — then reset the
        counters, so a measured window never eats a trace. Buckets warm
        one at a time (a joint batch would only compile the largest)."""
        for b in self.chunk_buckets:
            plen = max(1, min(b, self.max_len - 2))
            self.submit(np.ones((plen,), np.int32), 2)
            self.run()
        self.reset_metrics()
        return self

    # -- step mechanics ---------------------------------------------------
    def _param_data(self):
        return [p._data for p in self._params]

    def _meta(self):
        c = self.cache
        return _tree_data({"page_tables": c.page_tables,
                           "seq_lens": c.seq_lens,
                           "active": c.active})

    def _commit(self, buffers, meta):
        self._buffers = buffers
        self.cache.load_state({**buffers, **meta})

    def _chunk_bucket(self, n):
        for b in self.chunk_buckets:
            if b >= n:
                return b
        return self.chunk_buckets[-1]

    def _run_prefill_chunk(self, heads: list):
        """One compiled call advances the next chunk of up to
        `prefill_batch` prompts. Rows beyond `len(heads)` are dummies:
        their slot id is max_slots (out of bounds — the seq_lens
        scatter drops, the page-table gather clamps harmlessly) and
        their zero-length chunk routes every write to the trash page.
        """
        B = self.prefill_batch
        heads = heads[:B]
        chunks = [h.pending[h.prefill_pos:
                            h.prefill_pos + self.chunk_size]
                  for h in heads]
        bucket = self._chunk_bucket(max(len(c) for c in chunks))
        ids = np.zeros((B, bucket), np.int32)
        slot_ids = np.full((B,), self.max_slots, np.int32)
        start = np.zeros((B,), np.int32)
        lens_new = np.zeros((B,), np.int32)
        seeds = np.zeros((B,), np.uint32)
        for j, (h, chunk) in enumerate(zip(heads, chunks)):
            ids[j, :len(chunk)] = chunk
            slot_ids[j] = h.slot
            start[j] = h.prefill_pos
            lens_new[j] = h.prefill_pos + len(chunk)
            seeds[j] = self._seeds[h.slot]
        ids_next, _logits, buffers, meta = self.prefill_step(
            self._param_data(), self._buffers, self._meta(),
            ids, slot_ids, start, lens_new, seeds)
        self._commit(buffers, meta)
        tok = None
        for j, (h, chunk) in enumerate(zip(heads, chunks)):
            self.metrics.prefill_chunks += 1
            h.prefill_pos += len(chunk)
            if h.prefill_pos < len(h.pending):
                continue
            # prompt fully cached: the sampled token is the request's
            # next real token (its FIRST on a fresh admission -> TTFT)
            if tok is None:
                tok = np.asarray(ids_next)
            self.cache.set_active(h.slot, True)
            h.state = RequestState.RUNNING
            token = int(tok[j])
            self._tokens[h.slot] = token
            self._emit(h, token)

    def _run_decode(self) -> bool:
        sched = self.scheduler
        # highest priority first so page pressure lands on the lowest
        order = sorted(sched.decode_slots(),
                       key=lambda s: sched._key(sched.running[s]))
        # burst length k is uniform, but the PAGE lookahead is capped
        # per slot by the request's remaining token budget (and the
        # engine window): tokens a request samples past its budget
        # inside a burst are garbage the host discards, and their
        # writes land on the trash page (unmapped page-table entries
        # are 0) — reserving real pages for them could force a
        # preemption purely to hold discarded tokens
        k = self.decode_burst
        live = []
        for slot in order:
            h = sched.running.get(slot)
            if h is None or h.state is not RequestState.RUNNING:
                continue   # preempted as a victim earlier in this loop
            remaining = h.request.max_new_tokens - len(h.output_tokens)
            ahead = max(1, min(k, remaining,
                               self.max_len - sched._context_len(h)))
            if sched.ensure_token_capacity(slot, lookahead=ahead):
                live.append(slot)
        # a slot approved early can still be sacrificed to a later
        # (higher-priority-tied) slot's reservation — keep only slots
        # that survived the whole capacity pass
        live = [s for s in live
                if sched.running.get(s) is not None
                and sched.running[s].state is RequestState.RUNNING]
        if not live:
            return False
        out, _logits, buffers, meta = self.decode_step(
            self._param_data(), self._buffers, self._meta(),
            self._tokens, self._seeds)
        self._commit(buffers, meta)
        # ONE host sync per burst: [k, b] sampled ids (the in-graph
        # burst re-feeds them without the host round-trip)
        step_tokens = np.asarray(out)
        self.metrics.decode_steps += k
        for tok in step_tokens:
            for slot in live:
                handle = sched.running.get(slot)
                if (handle is None
                        or handle.state is not RequestState.RUNNING):
                    continue   # retired earlier in this burst
                token = int(tok[slot])
                self._tokens[slot] = token
                self._emit(handle, token)
        return True

    def _emit(self, handle: RequestHandle, token: int):
        now = self.clock()
        handle._push_token(token, now)
        self.metrics.on_token()
        req = handle.request
        if (req.eos_token_id is not None
                and token == req.eos_token_id):
            self.scheduler.retire(handle.slot, FinishReason.EOS, now)
        elif len(handle.output_tokens) >= req.max_new_tokens:
            self.scheduler.retire(handle.slot, FinishReason.LENGTH, now)

    def _recover(self):
        """A failed step leaves donated buffers dead — rebuild the cache
        pristine and requeue every resident request for resume. The
        flight recorder keeps the black box of what led here (ISSUE
        12); the dump itself happens at the raise site/excepthook."""
        from ..observability import recorder

        recorder().note("serving_recover",
                        running=len(self.scheduler.running),
                        waiting=len(self.scheduler.waiting))
        self.scheduler.abort_all()
        self.cache = self._make_cache()
        self.scheduler.cache = self.cache
        self._buffers, _ = _split_state(
            "paged", _tree_data(self.cache.state()))

    # -- introspection ----------------------------------------------------
    def leak_check(self) -> dict:
        """Post-drain invariant surface: every page and slot is back in
        the pool once no request is resident."""
        c = self.cache
        return {
            "free_pages": c.free_page_count,
            "total_pages": self.num_pages - 1,   # page 0 is trash
            "free_slots": c.free_slot_count,
            "total_slots": self.max_slots,
            "resident_slot_pages": len(c._slot_pages),
        }
