"""Elastic training manager.

Reference parity: fleet/elastic/manager.py:125 — nodes register with a
leased key + heartbeat (:248-261), the manager watches the node set and on
change rebuilds DISTRIBUTED_TRAINER_ENDPOINTS and relaunches within
PADDLE_ELASTIC_TIMEOUT (:37,143); `--nnodes lo:hi` ranges (elastic.py:61).

TPU-first: the etcd role is played by the TCPStore (control plane only —
the data plane re-forms when jax.distributed re-initializes after
relaunch). Generation counters namespace each incarnation so stale nodes
from generation g never pollute generation g+1's rendezvous.
"""
from __future__ import annotations

import os
import time

from ...launch.controllers.master import Master
from ...launch.controllers.watcher import Watcher


def parse_np_range(np_spec) -> tuple[int, int]:
    """'2:4' -> (2, 4); '3' -> (3, 3) (reference elastic.py:61-64)."""
    s = str(np_spec)
    if ":" in s:
        lo, hi = s.split(":", 1)
        lo, hi = int(lo), int(hi)
    else:
        lo = hi = int(s)
    if lo <= 0 or hi < lo:
        raise ValueError(f"bad nnodes range {np_spec!r}")
    return lo, hi


class ElasticManager:
    """Drives register → watch → (on change) re-rendezvous cycles."""

    def __init__(self, endpoint: str, rank: int, np_spec="1",
                 elastic_timeout: float = None,
                 heartbeat_interval: float = 2.0,
                 stale_after: float = 10.0):
        self.min_np, self.max_np = parse_np_range(np_spec)
        self.rank = rank
        self.elastic_timeout = elastic_timeout if elastic_timeout is not None \
            else float(os.environ.get("PADDLE_ELASTIC_TIMEOUT", "120"))
        self.master = Master(endpoint, rank, self.max_np,
                             timeout=self.elastic_timeout)
        self.gen = 0
        self._watcher = None
        self._interval = heartbeat_interval
        self._stale = stale_after

    def register_and_sync(self, my_endpoint: str) -> list[str]:
        """Join generation `gen`: register, wait for at least min_np nodes
        (up to elastic_timeout for more, bounded by max_np), return peers."""
        ns = f"gen{self.gen}"
        self.master.store.set(f"{ns}/node/{self.rank}", my_endpoint.encode())
        self.master.store.add(f"{ns}/registered", 1)
        import struct

        deadline = time.monotonic() + self.elastic_timeout
        best = 0
        while time.monotonic() < deadline:
            raw = self.master.store.get(f"{ns}/registered")
            n = struct.unpack("<q", raw)[0] if len(raw) == 8 else 0
            best = max(best, n)
            if best >= self.max_np:
                break
            if best >= self.min_np and time.monotonic() > deadline - \
                    self.elastic_timeout * 0.5:
                break  # settle for a partial (elastic) world
            time.sleep(0.1)
        if best < self.min_np:
            raise TimeoutError(
                f"elastic: only {best}/{self.min_np} nodes joined")
        peers = []
        for r in range(self.max_np):
            try:
                v = self.master.store._get_once(f"{ns}/node/{r}")
            except ConnectionError:
                v = None
            if v is not None:
                peers.append(v.decode())
        os.environ["DISTRIBUTED_TRAINER_ENDPOINTS"] = ",".join(peers)
        os.environ["PADDLE_TRAINERS_NUM"] = str(len(peers))
        return peers

    def start_watch(self):
        self._watcher = Watcher(self.master, interval=self._interval,
                                stale_after=self._stale, gen=self.gen)
        self._watcher.start()
        return self._watcher

    def world_changed(self) -> bool:
        return self._watcher is not None and self._watcher.peer_failed.is_set()

    def mark_completed(self, drain_timeout: float = 30.0):
        """Publish clean completion so peers' watchers don't read our
        heartbeat stopping as a crash. Best-effort on non-master ranks: a
        master that is already gone means rank 0 completed — exactly the
        state this mark exists to advertise. The MASTER waits (bounded)
        for every registered peer's done mark before returning, so its
        shutdown() can't tear the store from under slower peers."""
        try:
            self.master.store.set(f"gen{self.gen}/done/{self.rank}", b"1")
        except (ConnectionError, RuntimeError, OSError):
            if self.rank == 0:
                raise
            return
        if self.rank == 0:
            # drain by the ORIGINAL rank ids that actually registered this
            # generation (node keys) — a shrunken elastic world has sparse
            # survivors, so dense range(1, n) would stall on dead ranks
            # and never cover live ones
            peers = []
            for r in range(1, self.max_np):
                try:
                    if self.master.store._get_once(
                            f"gen{self.gen}/node/{r}") is not None:
                        peers.append(r)
                except (ConnectionError, RuntimeError, OSError):
                    return
            deadline = time.monotonic() + drain_timeout
            for r in peers:
                while time.monotonic() < deadline:
                    try:
                        if self.master.store._get_once(
                                f"gen{self.gen}/done/{r}") is not None:
                            break
                    except (ConnectionError, RuntimeError, OSError):
                        break
                    time.sleep(0.2)

    def next_generation(self):
        """Close the watch and bump the namespace for re-rendezvous."""
        if self._watcher is not None:
            self._watcher.stop()
            self._watcher = None
        self.gen += 1

    def shutdown(self):
        if self._watcher is not None:
            self._watcher.stop()
        self.master.shutdown()
