"""Master rendezvous over the TCPStore.

Reference parity: launch/controllers/master.py:73 (HTTPMaster.sync_peers)
/ :186 (ETCDMaster) — every node publishes its endpoint, rank 0 hosts the
store, all nodes block until the full peer list is known, then read back
identical ordered endpoints. Generation ("gen") keys let elastic restarts
re-rendezvous with a fresh namespace.
"""
from __future__ import annotations

import os
import socket
import time

from ...store import TCPStore


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class Master:
    def __init__(self, endpoint: str, rank: int, nnodes: int,
                 timeout: float = 300.0):
        self.rank = rank
        self.nnodes = nnodes
        if endpoint.startswith(("http://", "https://", "etcd://")):
            # external KV rendezvous (reference ETCDMaster :186): the
            # store outlives every node, so killing rank 0 mid-run does
            # not take the control plane down — the fault-injection test
            # in tests/test_store_launch.py proves the recovery
            from ..kv import HttpKVStore

            url = endpoint.replace("etcd://", "http://", 1)
            self.store = HttpKVStore(url, timeout=timeout)
        else:
            host, _, port = endpoint.partition(":")
            self.store = TCPStore(host or "127.0.0.1", int(port or 8765),
                                  world_size=nnodes, is_master=(rank == 0),
                                  timeout=timeout)

    def sync_peers(self, my_endpoint: str, gen: int = 0) -> list[str]:
        """Publish my endpoint; block until all nnodes registered; return
        the rank-ordered endpoint list (identical on every node)."""
        ns = f"gen{gen}"
        self.store.set(f"{ns}/node/{self.rank}", my_endpoint.encode())
        self.store.add(f"{ns}/registered", 1)
        deadline = time.monotonic() + self.store.timeout
        while True:
            # counter equality is the barrier; re-read until complete
            import struct

            raw = self.store.get(f"{ns}/registered")
            n = struct.unpack("<q", raw)[0] if len(raw) == 8 else 0
            if n >= self.nnodes:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"rendezvous: {n}/{self.nnodes} nodes after timeout")
            time.sleep(0.05)
        return [self.store.get(f"{ns}/node/{r}").decode()
                for r in range(self.nnodes)]

    def heartbeat(self, gen: int = 0):
        self.store.set(f"gen{gen}/beat/{self.rank}",
                       str(time.time()).encode())

    def peer_beats(self, gen: int = 0) -> dict[int, float]:
        out = {}
        for r in range(self.nnodes):
            try:
                val = self.store._get_once(f"gen{gen}/beat/{r}")
            except ConnectionError:
                val = None
            if val is not None:
                out[r] = float(val)
        return out

    def shutdown(self):
        self.store.shutdown()


def rendezvous_from_env(gen: int = 0) -> list[str]:
    """Build the env-contract peer list (reference sync_peers usage):
    publishes this host's coordinator endpoint, returns all, and exports
    DISTRIBUTED_TRAINER_ENDPOINTS."""
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    nnodes = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    master = os.environ.get("PADDLE_MASTER") or (
        f"{os.environ.get('MASTER_ADDR', '127.0.0.1')}:"
        f"{os.environ.get('MASTER_PORT', '8765')}")
    me = f"{socket.gethostbyname(socket.gethostname())}:{_free_port()}"
    m = Master(master, rank, nnodes)
    peers = m.sync_peers(me, gen=gen)
    os.environ["DISTRIBUTED_TRAINER_ENDPOINTS"] = ",".join(peers)
    os.environ["PADDLE_TRAINER_ENDPOINTS"] = ",".join(peers)
    return peers
