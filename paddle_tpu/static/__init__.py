"""paddle.static — the static-graph surface, subsumed by jit/to_static.

Reference parity: python/paddle/static/ — Program/Executor graph
building. TPU-first this whole layer is jaxpr/XLA (SURVEY §2.4 "PIR /
static IR: subsumed"): `paddle.jit.to_static` + `paddle.jit.save` are
the program-capture path. What remains here is the API surface ported
scripts actually touch: InputSpec, name/device guards (no-op context
managers — tracing owns scoping), Program objects with the attributes
training loops read (random_seed), and `data()` which returns an
InputSpec-like placeholder for to_static signatures. Graph-editing
calls raise with guidance.
"""
from __future__ import annotations

import contextlib

from ..hapi.model import InputSpec  # noqa: F401  (reference static.InputSpec)

__all__ = ["InputSpec", "Program", "default_main_program",
           "default_startup_program", "program_guard", "name_scope",
           "device_guard", "data", "py_func", "gradients", "nn",
           "cpu_places", "cuda_places", "Executor"]


class Program:
    """Attribute shell (reference framework Program): scripts set
    .random_seed and compare identities; the graph lives in XLA."""

    def __init__(self):
        self.random_seed = 0

    def global_block(self):
        raise RuntimeError(
            "static graph blocks do not exist on the TPU backend; the "
            "program is captured by paddle.jit.to_static (jaxpr/XLA)")

    def clone(self, for_test=False):
        return self


_main = Program()
_startup = Program()


def default_main_program():
    return _main


def default_startup_program():
    return _startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    yield


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


@contextlib.contextmanager
def device_guard(device=None):
    yield


def data(name, shape, dtype="float32", lod_level=0):
    """Placeholder (reference static.data) -> InputSpec for to_static."""
    return InputSpec(shape=shape, dtype=dtype, name=name)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    raise RuntimeError(
        "static.py_func builds graph nodes; in eager/to_static code just "
        "call the function (jax.pure_callback handles host calls under jit)")


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference static.gradients — route to the eager engine."""
    import paddle_tpu as paddle

    return paddle.grad(targets, inputs, grad_outputs=target_gradients,
                       allow_unused=True)


def cpu_places(device_count=None):
    import jax

    from ..framework.device import CPUPlace

    n = device_count or len(jax.devices("cpu"))
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    return []


class Executor:
    def __init__(self, place=None):
        raise RuntimeError(
            "static.Executor does not exist on the TPU backend: compiled "
            "execution is paddle.jit.to_static / TrainStep (one fused XLA "
            "program per step)")


class nn:
    """static.nn namespace: the dygraph functional ops serve both modes."""

    def __getattr__(self, name):
        import paddle_tpu.nn.functional as F

        return getattr(F, name)


nn = nn()
