"""Op correctness via the OpTest harness (numpy refs + finite-diff grads).

Covers the highest-traffic op families the way the reference's
test/legacy_test does per-op (OpTest subclass per op, SURVEY.md §4).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

from op_test import OpTest


class TestMatmul(OpTest):
    def op(self, x, y):
        return paddle.matmul(x, y)

    def ref(self, x, y):
        return x @ y

    def inputs(self, rng):
        return [rng.standard_normal((4, 6)).astype("float32"),
                rng.standard_normal((6, 5)).astype("float32")]

    def test(self):
        self.check_output()
        self.check_grad(wrt=(0, 1))


class TestSoftmax(OpTest):
    def op(self, x):
        return F.softmax(x, axis=-1)

    def ref(self, x):
        e = np.exp(x - x.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)

    def inputs(self, rng):
        return [rng.standard_normal((4, 8)).astype("float32")]

    def test(self):
        self.check_output()
        self.check_grad()


class TestGelu(OpTest):
    def op(self, x):
        return F.gelu(x)

    def ref(self, x):
        from scipy.special import erf

        return 0.5 * x * (1 + erf(x / np.sqrt(2.0)))

    def inputs(self, rng):
        return [rng.standard_normal((6, 6)).astype("float32")]

    def test(self):
        self.check_output()
        self.check_grad()


class TestLayerNorm(OpTest):
    def op(self, x, w, b):
        return F.layer_norm(x, (8,), weight=w, bias=b)

    def ref(self, x, w, b):
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        return (x - mu) / np.sqrt(var + 1e-5) * w + b

    def inputs(self, rng):
        return [rng.standard_normal((4, 8)).astype("float32"),
                rng.standard_normal((8,)).astype("float32"),
                rng.standard_normal((8,)).astype("float32")]

    def test(self):
        self.check_output()
        self.check_grad(wrt=(0, 1, 2))


class TestMeanVarReductions(OpTest):
    def op(self, x):
        return [x.mean(), x.sum(axis=0), x.max(axis=1), x.min()]

    def ref(self, x):
        return [x.mean(), x.sum(axis=0), x.max(axis=1), x.min()]

    def inputs(self, rng):
        return [rng.standard_normal((5, 7)).astype("float32")]

    def test(self):
        self.check_output()


class TestTranspose(OpTest):
    def op(self, x):
        return paddle.transpose(x, [1, 0, 2])

    def ref(self, x):
        return np.transpose(x, (1, 0, 2))

    def inputs(self, rng):
        return [rng.standard_normal((3, 4, 5)).astype("float32")]

    def test(self):
        self.check_output()
        self.check_grad()


class TestConcatSplit(OpTest):
    def op(self, x, y):
        c = paddle.concat([x, y], axis=1)
        a, b = paddle.split(c, 2, axis=1)
        return [c, a, b]

    def ref(self, x, y):
        c = np.concatenate([x, y], axis=1)
        a, b = np.split(c, 2, axis=1)
        return [c, a, b]

    def inputs(self, rng):
        return [rng.standard_normal((2, 3)).astype("float32"),
                rng.standard_normal((2, 3)).astype("float32")]

    def test(self):
        self.check_output()
        self.check_grad(wrt=(0, 1))


class TestSigmoidTanh(OpTest):
    def op(self, x):
        return [F.sigmoid(x), paddle.tanh(x)]

    def ref(self, x):
        return [1 / (1 + np.exp(-x)), np.tanh(x)]

    def inputs(self, rng):
        return [rng.standard_normal((4, 4)).astype("float32")]

    def test(self):
        self.check_output()
        self.check_grad()


class TestCrossEntropy(OpTest):
    tols = {"bfloat16": dict(rtol=5e-2, atol=5e-2)}

    def op(self, logits):
        labels = paddle.to_tensor(np.array([0, 2, 1, 3]), dtype="int64")
        return F.cross_entropy(logits, labels)

    def ref(self, logits):
        labels = np.array([0, 2, 1, 3])
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        return -np.log(p[np.arange(4), labels]).mean()

    def inputs(self, rng):
        return [rng.standard_normal((4, 5)).astype("float32")]

    def test(self):
        self.check_output()
        self.check_grad()


class TestEmbedding(OpTest):
    def op(self, w):
        ids = paddle.to_tensor(np.array([[0, 2], [1, 1]]), dtype="int64")
        return F.embedding(ids, w)

    def ref(self, w):
        return w[np.array([[0, 2], [1, 1]])]

    def inputs(self, rng):
        return [rng.standard_normal((4, 6)).astype("float32")]

    def test(self):
        self.check_output()
        self.check_grad()


class TestConv2D(OpTest):
    tols = {"bfloat16": dict(rtol=6e-2, atol=6e-2)}

    def op(self, x, w):
        return F.conv2d(x, w, stride=1, padding=1)

    def ref(self, x, w):
        import scipy.signal

        n, cin, hh, ww = x.shape
        cout = w.shape[0]
        out = np.zeros((n, cout, hh, ww), np.float32)
        for i in range(n):
            for o in range(cout):
                acc = np.zeros((hh, ww), np.float32)
                for c in range(cin):
                    acc += scipy.signal.correlate2d(
                        x[i, c], w[o, c], mode="same")
                out[i, o] = acc
        return out

    def inputs(self, rng):
        return [rng.standard_normal((2, 3, 6, 6)).astype("float32"),
                rng.standard_normal((4, 3, 3, 3)).astype("float32")]

    def test(self):
        self.check_output()
        self.check_grad(wrt=(0, 1), max_probe=8)


class TestWhereClipExp(OpTest):
    def op(self, x):
        return [paddle.clip(x, -0.5, 0.5), paddle.exp(x),
                paddle.where(x > 0, x, paddle.zeros_like(x))]

    def ref(self, x):
        return [np.clip(x, -0.5, 0.5), np.exp(x), np.where(x > 0, x, 0)]

    def inputs(self, rng):
        return [rng.standard_normal((4, 4)).astype("float32")]

    def test(self):
        self.check_output()


class TestBatchNormInference(OpTest):
    def op(self, x):
        import paddle_tpu.nn as nn

        bn = nn.BatchNorm2D(3)
        bn.eval()
        return bn(x)

    def ref(self, x):
        return x / np.sqrt(1.0 + 1e-5)  # mean 0 var 1 init stats

    def inputs(self, rng):
        return [rng.standard_normal((2, 3, 4, 4)).astype("float32")]

    def test(self):
        self.check_output()


class TestVarlenAttention:
    def test_matches_per_sequence_attention(self):
        """flash_attn_unpadded == per-sequence full attention."""
        rng = np.random.default_rng(0)
        lens = [3, 5, 4]
        total = sum(lens)
        h, d = 2, 8
        q = rng.standard_normal((total, h, d)).astype("float32")
        k = rng.standard_normal((total, h, d)).astype("float32")
        v = rng.standard_normal((total, h, d)).astype("float32")
        cu = np.cumsum([0] + lens).astype("int32")
        out, _ = F.flash_attn_unpadded(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            paddle.to_tensor(cu), paddle.to_tensor(cu),
            max(lens), max(lens), scale=1.0 / np.sqrt(d), causal=True)
        out = out.numpy()
        for i in range(len(lens)):
            s, e = cu[i], cu[i + 1]
            qi, ki, vi = q[s:e], k[s:e], v[s:e]
            logits = np.einsum("qhd,khd->hqk", qi, ki) / np.sqrt(d)
            L = e - s
            mask = np.tril(np.ones((L, L), bool))
            logits = np.where(mask[None], logits, -np.inf)
            p = np.exp(logits - logits.max(-1, keepdims=True))
            p = p / p.sum(-1, keepdims=True)
            ref = np.einsum("hqk,khd->qhd", p, vi)
            np.testing.assert_allclose(out[s:e], ref, atol=1e-5)

    def test_grad_flows(self):
        rng = np.random.default_rng(1)
        q = paddle.to_tensor(
            rng.standard_normal((8, 2, 4)).astype("float32"),
            stop_gradient=False)
        k = paddle.to_tensor(
            rng.standard_normal((8, 2, 4)).astype("float32"),
            stop_gradient=False)
        v = paddle.to_tensor(
            rng.standard_normal((8, 2, 4)).astype("float32"),
            stop_gradient=False)
        cu = paddle.to_tensor(np.array([0, 4, 8], np.int32))
        out, _ = F.flash_attn_unpadded(q, k, v, cu, cu, 4, 4, scale=0.5,
                                       causal=False)
        out.sum().backward()
        assert q.grad is not None and k.grad is not None
