"""AOT memory diagnosis of the fused-scan 1.3b step: lower+compile the
program and print the XLA buffer-assignment stats (argument/output/temp/
alias sizes) WITHOUT executing — the way to see whether donation aliased
the state through the scan carries and where the peak lives, without
paying an on-chip OOM each probe.

Usage: python tools/diag_fused_mem.py [model] [batch]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    model_name = sys.argv[1] if len(sys.argv) > 1 else "gpt3-1.3b"
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    seq = int(os.environ.get("SEQ", "1024"))

    import jax
    import jax.numpy as jnp
    import numpy as np

    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as popt
    from paddle_tpu.jit import FusedScanTrainStep
    from paddle_tpu.models import GPTForCausalLM, gpt_config

    cfg = gpt_config(model_name, max_position_embeddings=seq,
                     hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                     scan_layers=True)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    compute_dtype = None
    if os.environ.get("FP32_STORE", "1") == "1":
        compute_dtype = "bfloat16"      # fp32-stored params, bf16 compute
        opt = popt.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                         moment_dtype="bfloat16")
    else:
        model.bfloat16()
        opt = popt.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                         multi_precision=True, moment_dtype="bfloat16")
    step = FusedScanTrainStep(
        model, opt, fused_head=os.environ.get("FUSED_HEAD", "0") == "1",
        compute_dtype=compute_dtype,
        layer_chunk=int(os.environ.get("LAYER_CHUNK", "1")))
    step.ensure_built()
    state = step._extract_state()
    lr = jnp.asarray(1e-4, jnp.float32)
    ids = jnp.asarray(np.zeros((batch, seq), np.int32))
    labels = jnp.asarray(np.zeros((batch, seq), np.int32))
    lowered = step._jitted.lower(state, lr, ids, labels)
    compiled = lowered.compile()
    ma = compiled.memory_analysis()
    G = 1 << 30
    print(f"model={model_name} batch={batch} seq={seq}")
    try:
        print(f"  argument_size   {ma.argument_size_in_bytes / G:.2f} G")
        print(f"  output_size     {ma.output_size_in_bytes / G:.2f} G")
        print(f"  temp_size       {ma.temp_size_in_bytes / G:.2f} G")
        print(f"  alias_size      {ma.alias_size_in_bytes / G:.2f} G")
        print(f"  peak (arg+out+temp-alias) "
              f"{(ma.argument_size_in_bytes + ma.output_size_in_bytes + ma.temp_size_in_bytes - ma.alias_size_in_bytes) / G:.2f} G")
    except AttributeError:
        print(" ", ma)


if __name__ == "__main__":
    main()
