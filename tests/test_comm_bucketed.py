"""Bucketed / quantized gradient collectives (ISSUE 1 tentpole).

CPU multi-device parity on the conftest 8-device host mesh: bucketed
reduce_scatter == per-param reduce_scatter == single-process grads (the
two distributed modes bit-for-bit; single-process to reduction-order
tolerance), the int8-compressed path within tolerance and OFF by default,
the backward collective-count bound, the stage-2 layout check with
bucketing on, and the accumulation comm boundary.
"""
import math

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as popt
from paddle_tpu.distributed import env as denv
from paddle_tpu.distributed.comm_bucketer import (
    MB, build_buckets, bucketed_all_reduce, bucketed_reduce_scatter,
    count_hlo_collectives,
)
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.utils import flags as _flags


@pytest.fixture(autouse=True)
def reset_env():
    yield
    denv.reset()
    _flags.set_flags({"FLAGS_comm_bucket_mb": 25, "FLAGS_comm_quant": ""})
    import paddle_tpu.distributed.collective as coll

    coll._default_group = None


def cpu8():
    return jax.devices("cpu")[:8]


def mesh8(axis="sharding"):
    mesh = Mesh(np.asarray(cpu8()), (axis,))
    denv.set_mesh(mesh)
    return mesh


class TestBucketAssignment:
    def test_deterministic_packing_and_padding(self):
        shapes = [("a", (1024,), jnp.float32), ("b", (512, 2), jnp.float32),
                  ("c", (7, 3), jnp.float32), ("d", (33,), jnp.float32)]
        asn = build_buckets(shapes, bucket_bytes=8192, pad_multiple=8)
        # 1024*4 = 4096 bytes, +1024*4 = 8192 fits; "c" would exceed
        assert [b.keys for b in asn.buckets] == [["a", "b"], ["c", "d"]]
        for b in asn.buckets:
            assert b.numel % 8 == 0
        bkt, entry = asn.bucket_of("d")
        assert bkt.index == 1 and entry.offset == 21 and entry.numel == 33
        # same input -> same assignment (determinism is the scatter-back
        # contract)
        asn2 = build_buckets(shapes, bucket_bytes=8192, pad_multiple=8)
        assert asn2 == asn

    def test_dtype_splits_buckets(self):
        shapes = [("a", (8,), jnp.float32), ("b", (8,), jnp.bfloat16),
                  ("c", (8,), jnp.bfloat16)]
        asn = build_buckets(shapes, bucket_bytes=1 << 20)
        assert [b.keys for b in asn.buckets] == [["a"], ["b", "c"]]

    def test_oversized_param_gets_own_bucket(self):
        shapes = [("big", (4096,), jnp.float32), ("s", (4,), jnp.float32)]
        asn = build_buckets(shapes, bucket_bytes=1024)
        assert [b.keys for b in asn.buckets] == [["big"], ["s"]]


class TestBucketedCollectiveParity:
    """Satellite: bucketed == per-param == single-process in fp32."""

    def test_reduce_scatter_bitwise_vs_per_param(self):
        mesh8()
        group = dist.get_group()
        rng = np.random.default_rng(0)
        shapes = [(64, 16), (16,), (16, 8), (7, 5), (33,)]  # odd ones too
        grads = [rng.standard_normal(s).astype(np.float32) for s in shapes]
        ts = [Tensor(jnp.asarray(g)) for g in grads]
        bucketed_reduce_scatter(ts, group=group)
        for g, t in zip(grads, ts):
            got = np.asarray(t._data)
            if g.size % 8 == 0:
                per = np.asarray(dist.reduce_scatter(
                    None, Tensor(jnp.asarray(g.reshape(-1))),
                    axis=0)._data).reshape(g.shape)
                np.testing.assert_array_equal(got, per)
            # every shape (odd ones only the bucket path can scatter):
            # value == the sum of 8 replicated rank copies
            np.testing.assert_allclose(got, g * 8, rtol=1e-6)

    def test_all_reduce_bitwise_vs_per_param(self):
        mesh8("dp")
        rng = np.random.default_rng(1)
        grads = [rng.standard_normal(s).astype(np.float32)
                 for s in [(32, 8), (11,), (3, 5)]]
        ts = [Tensor(jnp.asarray(g)) for g in grads]
        bucketed_all_reduce(ts)
        for g, t in zip(grads, ts):
            per = dist.all_reduce(Tensor(jnp.asarray(g)))
            np.testing.assert_array_equal(np.asarray(t._data),
                                          np.asarray(per._data))

    def test_int8_within_tolerance_and_off_by_default(self):
        mesh8("dp")
        # off by default: flag empty, all_reduce_quantized falls back to
        # the exact path bit-for-bit
        assert _flags.get_flag("FLAGS_comm_quant") == ""
        x = jnp.asarray(np.random.default_rng(2)
                        .standard_normal(256), jnp.float32)
        exact = dist.all_reduce(Tensor(x))
        dflt = dist.all_reduce_quantized(Tensor(x))
        np.testing.assert_array_equal(np.asarray(dflt._data),
                                      np.asarray(exact._data))
        # int8 path: rel error < 1e-2 (the EQuARX-style two-sided scales)
        rep = dist.comm_quant_selftest(qformat="int8")
        assert rep["pass"], rep
        # non-32-aligned sizes must hold the contract too (payload pads
        # to whole scaling blocks; a chunk-sized fallback scale would
        # reintroduce the outlier floor)
        rep = dist.comm_quant_selftest(qformat="int8", numel=1000)
        assert rep["pass"], rep
        # and it rides the bucketed path via the flag
        _flags.set_flags({"FLAGS_comm_quant": "int8"})
        ts = [Tensor(x)]
        bucketed_all_reduce(ts)
        rel = (np.max(np.abs(np.asarray(ts[0]._data)
                             - np.asarray(exact._data)))
               / np.max(np.abs(np.asarray(exact._data))))
        assert rel < 1e-2, rel

    def test_bf16_compressed_path(self):
        mesh8("dp")
        rep = dist.comm_quant_selftest(qformat="bf16")
        assert rep["pass"], rep

    def test_quantized_rejects_non_sum(self):
        mesh8("dp")
        with pytest.raises(ValueError, match="SUM"):
            dist.all_reduce_quantized(Tensor(jnp.ones(8)),
                                      op=dist.ReduceOp.MAX, qformat="int8")


class TestBackwardCollectiveCount:
    """Acceptance: a ~1M-param model's backward + bucketed sync emits
    <= ceil(total_grad_bytes / bucket_size) collective ops, vs
    one-per-parameter before (HLO op-count probe)."""

    def _model_and_batch(self):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(512, 1024), nn.GELU(),
                              nn.Linear(1024, 512))
        params = [p for p in model.parameters() if p.trainable]
        n = sum(int(np.prod(p.shape)) for p in params)
        assert n > 1_000_000, n
        x = jnp.asarray(np.random.default_rng(0)
                        .standard_normal((8, 512)), jnp.float32)
        y = jnp.asarray(np.random.default_rng(1)
                        .standard_normal((8, 512)), jnp.float32)
        return model, params, n, x, y

    def test_bucketed_backward_meets_bound(self):
        mesh8()
        group = dist.get_group()
        model, params, n_params, x, y = self._model_and_batch()
        total_bytes = n_params * 4
        bound = math.ceil(total_bytes / (25 * MB))

        def f(xd, yd):
            loss = ((model(Tensor._wrap(xd))
                     - Tensor._wrap(yd)) ** 2).mean()
            loss.backward()
            gs = [p.grad for p in params]
            bucketed_reduce_scatter(gs, group=group)
            return [g._data for g in gs]

        try:
            counts = count_hlo_collectives(f, x, y)
        finally:
            for p in params:
                p.clear_grad()
        assert counts["reduce_scatter"] <= bound, (counts, bound)
        assert counts["reduce_scatter"] >= 1
        assert counts["all_reduce"] == 0, counts

    def test_per_param_backward_is_one_per_parameter(self):
        mesh8()
        group = dist.get_group()
        model, params, _, x, y = self._model_and_batch()

        def f(xd, yd):
            loss = ((model(Tensor._wrap(xd))
                     - Tensor._wrap(yd)) ** 2).mean()
            loss.backward()
            outs = []
            for p in params:
                outs.append(dist.reduce_scatter(
                    None, Tensor._wrap(p.grad._data.reshape(-1)),
                    group=group, axis=0)._data)
            return outs

        try:
            counts = count_hlo_collectives(f, x, y)
        finally:
            for p in params:
                p.clear_grad()
        # the "before" this PR replaces: one collective per parameter
        assert counts["reduce_scatter"] == len(params), counts


class TestStage2Bucketed:
    """Stage-2 ("os_g") with the bucketer: parity with per-param mode
    bit-for-bit, with single-process to reduction-order tolerance; the
    layout check of tests/test_distributed.py still holds with bucketing
    on (grads materialize reduce-scattered, never all-reduce-replicated)."""

    def _grads(self, mode):
        """mode: None=single-process, 0=per-param stage2, 25=bucketed."""
        from paddle_tpu.distributed.sharding import group_sharded_parallel

        denv.reset()
        if mode is not None:
            mesh8()
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(64, 128), nn.GELU(),
                              nn.Linear(128, 64))
        params = list(model.parameters())
        mw = model
        if mode is not None:
            _flags.set_flags({"FLAGS_comm_bucket_mb": mode})
            mw, _, _ = group_sharded_parallel(
                model, popt.AdamW(learning_rate=1e-3,
                                  parameters=model.parameters()),
                level="os_g")
        x = jnp.asarray(np.random.default_rng(0)
                        .standard_normal((32, 64)), jnp.float32)
        y = jnp.asarray(np.random.default_rng(1)
                        .standard_normal((32, 64)), jnp.float32)
        if mode is not None:
            x = jax.device_put(x, NamedSharding(
                denv.get_mesh(), P("sharding", None)))

        def f(xd, yd):
            loss = ((mw(Tensor._wrap(xd)) - Tensor._wrap(yd)) ** 2).mean()
            loss.backward()
            if hasattr(mw, "apply_collective_grads"):
                mw.apply_collective_grads()
            return [p.grad._data for p in params]

        try:
            return [np.asarray(g) for g in jax.jit(f)(x, y)]
        finally:
            for p in params:
                p.clear_grad()

    def test_bucketed_grads_bitwise_vs_per_param_and_single(self):
        single = self._grads(None)
        per_param = self._grads(0)
        bucketed = self._grads(25)
        for a, b in zip(per_param, bucketed):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(single, bucketed):
            # cross-replica reduction tree != single-matmul order: exact
            # to fp32 reduction-order noise
            np.testing.assert_allclose(a, b, atol=1e-7)

    def test_training_parity_and_bucketer_engaged(self):
        from paddle_tpu.distributed.sharding import group_sharded_parallel
        from paddle_tpu.jit import TrainStep

        def train(bucket_mb):
            denv.reset()
            mesh8()
            _flags.set_flags({"FLAGS_comm_bucket_mb": bucket_mb})
            paddle.seed(0)
            model = nn.Linear(16, 8)
            opt = popt.AdamW(learning_rate=0.01,
                             parameters=model.parameters())
            mw, ow, _ = group_sharded_parallel(model, opt, level="os_g")
            x = paddle.to_tensor(np.random.RandomState(0).randn(8, 16)
                                 .astype(np.float32))
            y = paddle.to_tensor(np.random.RandomState(1).randn(8, 8)
                                 .astype(np.float32))

            def lf(m, a, b):
                d = m(a) - b
                return (d * d).mean()

            step = TrainStep(mw, lf, ow)
            losses = [float(step(x, y)) for _ in range(3)]
            return losses, mw, model

        l_bucket, mw, model = train(25)
        assert mw._bucketer is not None and mw._bucketer.num_buckets >= 1
        # the sharded optimizer records the deterministic assignment for
        # the scatter-back
        asn = mw._opt.grad_bucket_assignment()
        assert asn is not None and asn is mw._bucketer.assignment
        l_pp, mw_pp, _ = train(0)
        assert mw_pp._bucketer is None
        np.testing.assert_allclose(l_bucket, l_pp, rtol=1e-6)

    def test_eager_layout_check_with_bucketing_on(self):
        """The tests/test_distributed.py stage-2 layout assert, with
        bucketing explicitly ON: the eager backward still leaves grads
        reduce-scattered (sharded over the axis), never replicated."""
        from paddle_tpu.distributed.sharding import group_sharded_parallel

        mesh8()
        assert _flags.get_flag("FLAGS_comm_bucket_mb") > 0
        paddle.seed(0)
        model = nn.Linear(16, 8)
        opt = popt.AdamW(learning_rate=0.01,
                         parameters=model.parameters())
        mw, _, _ = group_sharded_parallel(model, opt, level="os_g")
        x = paddle.to_tensor(np.random.RandomState(0).randn(8, 16)
                             .astype(np.float32))
        y = paddle.to_tensor(np.random.RandomState(1).randn(8, 8)
                             .astype(np.float32))
        d = mw(x) - y
        (d * d).mean().backward()
        g = model.weight.grad
        assert g is not None
        assert any(a == "sharding" for a in (g._data.sharding.spec or ())), \
            f"grad not reduce-scattered: {g._data.sharding}"


class TestAccumulationBoundary:
    """Acceptance: TrainStep(accum_steps=4) grads bit-identical in fp32
    to 4 summed single-microbatch backwards (momentum velocity after one
    step IS the accumulated grad, so it is the exact probe)."""

    def _build(self):
        paddle.seed(3)
        m = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 8))
        o = popt.Momentum(learning_rate=0.1, momentum=0.9,
                          parameters=m.parameters())
        return m, o

    def test_accum4_bit_identical_to_summed_backwards(self):
        from paddle_tpu.jit import TrainStep

        x = paddle.to_tensor(np.random.default_rng(0)
                             .standard_normal((8, 16)).astype(np.float32))
        y = paddle.to_tensor(np.random.default_rng(1)
                             .standard_normal((8, 8)).astype(np.float32))

        def lf(m, a, b):
            return ((m(a) - b) ** 2).mean()

        m1, o1 = self._build()
        TrainStep(m1, lf, o1, accum_steps=4)(x, y)
        v_fused = list(o1._accumulators["velocity"].values())

        m2, o2 = self._build()
        for i in range(4):
            xs = Tensor._wrap(x._data[i * 2:(i + 1) * 2])
            ys = Tensor._wrap(y._data[i * 2:(i + 1) * 2])
            (lf(m2, xs, ys) * 0.25).backward()
        o2.step()
        v_eager = list(o2._accumulators["velocity"].values())
        assert len(v_fused) == len(v_eager) >= 4
        for a, b in zip(v_fused, v_eager):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_accum_steps_alias_and_conflict(self):
        from paddle_tpu.jit import TrainStep

        m, o = self._build()
        step = TrainStep(m, lambda mm, a, b: ((mm(a) - b) ** 2).mean(), o,
                         accum_steps=2)
        assert step.accumulate_steps == 2
        with pytest.raises(ValueError, match="conflicting"):
            TrainStep(m, lambda mm, a, b: ((mm(a) - b) ** 2).mean(), o,
                      accumulate_steps=2, accum_steps=4)

    def test_stage2_accum_syncs_once_at_boundary(self):
        """With accum_steps=k the bucket collectives issue ONCE, at the
        comm boundary after the k-th microbatch backward — not once per
        microbatch: the traced step invokes the bucketer's sync exactly
        one time (and the hooks marked grads pending every microbatch),
        and the losses match the per-param mode."""
        from paddle_tpu.distributed.sharding import group_sharded_parallel
        from paddle_tpu.jit import TrainStep

        def run(bucket_mb, sync_log=None):
            denv.reset()
            mesh8()
            _flags.set_flags({"FLAGS_comm_bucket_mb": bucket_mb})
            paddle.seed(0)
            model = nn.Linear(16, 8)
            opt = popt.AdamW(learning_rate=0.01,
                             parameters=model.parameters())
            mw, ow, _ = group_sharded_parallel(model, opt, level="os_g")
            if sync_log is not None:
                bucketer = mw._bucketer
                orig = bucketer.sync_pending

                def counted():
                    issued = orig()
                    if issued:
                        sync_log.append(issued)
                    return issued

                bucketer.sync_pending = counted
            x = paddle.to_tensor(np.random.RandomState(0).randn(8, 16)
                                 .astype(np.float32))
            y = paddle.to_tensor(np.random.RandomState(1).randn(8, 8)
                                 .astype(np.float32))
            step = TrainStep(mw, lambda m, a, b: ((m(a) - b) ** 2).mean(),
                             ow, accum_steps=4)
            return float(step(x, y))

        log = []
        l_bucket = run(25, log)
        # one sync (of >=1 buckets) per traced step — the boundary, not 4
        assert len(log) == 1, log
        l_pp = run(0)
        np.testing.assert_allclose(l_bucket, l_pp, rtol=1e-6)


class TestPartialGradExplicitSync:
    """The explicit bucketed path for grads tagged partial (per-rank
    producers): DataParallel.apply_collective_grads and
    fused_allreduce_gradients coalesce them into one all-reduce per
    bucket instead of one per parameter."""

    def _partial_grad_model(self):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 16), nn.GELU(),
                              nn.Linear(16, 8))
        for p in model.parameters():
            g = Tensor(jnp.asarray(
                np.random.default_rng(hash(p.name) % 2**31)
                .standard_normal(p.shape).astype(np.float32)))
            g._is_partial_grad = True
            p.grad = g
        return model

    def test_dataparallel_apply_collective_grads(self):
        mesh8("dp")
        model = self._partial_grad_model()
        want = {p.name: np.asarray(p.grad._data) * 8
                for p in model.parameters()}
        dp = dist.DataParallel(model)
        dp.apply_collective_grads()
        for p in model.parameters():
            assert not getattr(p.grad, "_is_partial_grad", False)
            np.testing.assert_allclose(np.asarray(p.grad._data),
                                       want[p.name], rtol=1e-6)
        # untagged grads are untouched (GSPMD already reduced them)
        before = np.asarray(model[0].weight.grad._data).copy()
        dp.apply_collective_grads()
        np.testing.assert_array_equal(
            np.asarray(model[0].weight.grad._data), before)

    def test_dp_sync_uses_dp_axis_on_hybrid_mesh(self):
        """group=None on a dp×mp mesh must reduce over dp ONLY (the
        world group would sum unrelated model-parallel slices)."""
        mesh = Mesh(np.asarray(cpu8()).reshape(4, 2), ("dp", "mp"))
        denv.set_mesh(mesh)
        model = self._partial_grad_model()
        want = {p.name: np.asarray(p.grad._data) * 4   # dp degree, NOT 8
                for p in model.parameters()}
        dist.DataParallel(model).apply_collective_grads()
        for p in model.parameters():
            np.testing.assert_allclose(np.asarray(p.grad._data),
                                       want[p.name], rtol=1e-6)

    def test_bucket_flag_zero_restores_per_param(self):
        """FLAGS_comm_bucket_mb=0: every tensor becomes its own bucket
        (the documented per-parameter escape hatch) on both the flag-
        defaulted and the DataParallel comm_buffer_size paths."""
        mesh8("dp")
        _flags.set_flags({"FLAGS_comm_bucket_mb": 0})
        grads = [np.ones((4,), np.float32), np.ones((6,), np.float32)]
        asn = build_buckets([(i, g.shape, g.dtype)
                             for i, g in enumerate(grads)])
        assert len(asn.buckets) == len(grads)
        model = self._partial_grad_model()
        want = {p.name: np.asarray(p.grad._data) * 8
                for p in model.parameters()}
        dist.DataParallel(model).apply_collective_grads()
        for p in model.parameters():
            np.testing.assert_allclose(np.asarray(p.grad._data),
                                       want[p.name], rtol=1e-6)

    def test_bare_stage2_wrapper_keeps_traced_per_param_pins(self):
        """GroupShardedStage2 WITHOUT a flush-capable sharding optimizer
        (bare wrapper in a user jit, no apply_collective_grads call) must
        not defer pins it cannot flush — grads still come out sharded."""
        from paddle_tpu.distributed.sharding import GroupShardedStage2

        mesh8()
        paddle.seed(0)
        model = nn.Linear(16, 8)
        mw = GroupShardedStage2(model)          # sharding_optimizer=None
        assert not mw._defer_ok
        params = list(model.parameters())

        def f(xd, yd):
            loss = ((mw(Tensor._wrap(xd)) - Tensor._wrap(yd)) ** 2).mean()
            loss.backward()
            return [p.grad._data for p in params]

        x = jnp.asarray(np.random.RandomState(0).randn(8, 16), jnp.float32)
        y = jnp.asarray(np.random.RandomState(1).randn(8, 8), jnp.float32)
        try:
            txt = jax.jit(f).lower(x, y).compile().as_text()
        finally:
            for p in params:
                p.clear_grad()
        # the per-param sharding constraints must still be in the program
        # (sharded grad layout, not lost to an unflushed bucket)
        assert "sharding={devices=" in txt

    def test_fused_allreduce_gradients_bucketed(self):
        from paddle_tpu.distributed.fleet.utils.hybrid_parallel_util import (
            fused_allreduce_gradients,
        )

        mesh8("dp")
        model = self._partial_grad_model()
        want = {p.name: np.asarray(p.grad._data) * 8
                for p in model.parameters()}
        fused_allreduce_gradients(list(model.parameters()))
        for p in model.parameters():
            np.testing.assert_allclose(np.asarray(p.grad._data),
                                       want[p.name], rtol=1e-6)


class TestSatelliteFixes:
    def test_rope_half_style_derived_table(self):
        """Regression (ADVICE r5): use_neox_rotary_style=False with
        sin/cos omitted must pair position j with freq j (table
        [freqs, freqs]), matching both the numpy reference and an
        explicitly passed table."""
        from paddle_tpu.incubate.nn.functional import (
            fused_rotary_position_embedding,
        )

        rng = np.random.default_rng(0)
        b, s, h, d = 2, 6, 2, 8
        q = rng.standard_normal((b, s, h, d)).astype(np.float32)
        inv = 1.0 / (10000.0 ** (np.arange(0, d, 2) / d))
        freqs = np.outer(np.arange(s), inv).astype(np.float32)
        half = d // 2
        x1, x2 = q[..., :half], q[..., half:]
        s1 = np.sin(freqs)[None, :, None, :]
        c1 = np.cos(freqs)[None, :, None, :]
        want = np.concatenate([x1 * c1 - x2 * s1,
                               x2 * c1 + x1 * s1], -1)
        derived, _, _ = fused_rotary_position_embedding(
            paddle.to_tensor(q), use_neox_rotary_style=False)
        np.testing.assert_allclose(np.asarray(derived._data), want,
                                   rtol=1e-5, atol=1e-5)
        # consistency with an explicit [freqs, freqs] table
        table = np.concatenate([freqs, freqs], -1)
        explicit, _, _ = fused_rotary_position_embedding(
            paddle.to_tensor(q), sin=paddle.to_tensor(np.sin(table)),
            cos=paddle.to_tensor(np.cos(table)),
            use_neox_rotary_style=False)
        np.testing.assert_allclose(np.asarray(derived._data),
                                   np.asarray(explicit._data), atol=1e-6)

    def test_vector_norm_keepdim_axis_none(self):
        x = paddle.to_tensor(np.random.default_rng(0)
                             .standard_normal((3, 4, 5)).astype(np.float32))
        out = paddle.linalg.vector_norm(x, p=2.0, axis=None, keepdim=True)
        assert tuple(out.shape) == (1, 1, 1)
        np.testing.assert_allclose(
            float(np.asarray(out._data).reshape(())),
            np.linalg.norm(np.asarray(x._data).reshape(-1)), rtol=1e-6)
        # keepdim=False unchanged: scalar
        flat = paddle.linalg.vector_norm(x, p=2.0)
        assert tuple(flat.shape) == ()
