"""Sparse tensors (paddle.sparse parity: reference python/paddle/sparse/
creation.py, unary.py, binary.py, multiary.py, nn/).

TPU-first design: XLA has no dynamic-nnz sparse kernels, so sparsity is
represented with STATIC-shape index/value arrays (COO: indices [ndim, nnz],
values [nnz, ...]; CSR: crows/cols/values) and every op is expressed as
gathers, scatters and segment-sums — all jit/grad/shard-friendly at fixed
nnz. Pattern-changing conversions (`Tensor.to_sparse_coo`, `nonzero`) are
eager-only, like every framework's sparse construction path.

  - elementwise unary ops run on `values` only (sparsity preserved)
  - sparse+sparse binary ops align the two patterns with sorted-id
    searchsorted lookups over the union (static nnz1+nnz2 bound)
  - matmul(sparse, dense) = gather rows + segment_sum — the MXU-friendly
    formulation of SpMM
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor

from . import nn  # noqa: E402,F401

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "sin", "tan", "asin", "atan", "sinh", "tanh",
    "asinh", "atanh", "sqrt", "square", "log1p", "abs", "pow", "cast",
    "neg", "deg2rad", "rad2deg", "expm1", "isnan", "coalesce", "sum",
    "transpose", "reshape", "add", "subtract", "multiply", "divide",
    "matmul", "mv", "masked_matmul", "addmm", "mask_as", "is_same_shape",
    "slice", "pca_lowrank",
]


def _as_jnp(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x)


class SparseCooTensor:
    """COO tensor: indices [sparse_dim, nnz] int64, values [nnz, *dense_dims].

    Reference: paddle's sparse Tensor with coo layout
    (paddle/phi/core/sparse_coo_tensor.h)."""

    def __init__(self, indices, values, shape, coalesced=False):
        self._indices = _as_jnp(indices).astype(jnp.int64)
        self._values = _as_jnp(values)
        self._shape = tuple(int(s) for s in shape)
        self._coalesced = bool(coalesced)

    # -- bookkeeping ----------------------------------------------------
    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        from ..framework.dtype import from_jax_dtype

        return from_jax_dtype(self._values.dtype)

    def sparse_dim(self):
        return int(self._indices.shape[0])

    def dense_dim(self):
        return self._values.ndim - 1

    def nnz(self):
        return int(self._indices.shape[1])

    def indices(self):
        return Tensor._wrap(self._indices)

    def values(self):
        return Tensor._wrap(self._values)

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    # -- conversion -----------------------------------------------------
    def _linear_ids(self):
        """Row-major linearized index of each nonzero over the sparse dims."""
        strides = np.cumprod((self._shape[:self._indices.shape[0]] + (1,))[::-1])[::-1][1:]
        s = jnp.asarray(strides.copy(), jnp.int64)
        return (self._indices * s[:, None]).sum(0)

    def to_dense(self):
        sd = self.sparse_dim()
        out = jnp.zeros(self._shape[:sd] + self._values.shape[1:],
                        self._values.dtype)
        out = out.at[tuple(self._indices)].add(self._values)
        return Tensor._wrap(out)

    def to_sparse_csr(self):
        if self.sparse_dim() != 2 or self.dense_dim() != 0:
            raise ValueError("to_sparse_csr needs a 2-D sparse matrix")
        c = coalesce(self)
        rows, cols = c._indices[0], c._indices[1]
        crows = jnp.zeros((self._shape[0] + 1,), jnp.int64).at[rows + 1].add(1)
        crows = jnp.cumsum(crows)
        return SparseCsrTensor(crows, cols, c._values, self._shape)

    # -- arithmetic sugar ----------------------------------------------
    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __neg__(self):
        return neg(self)

    def matmul(self, other):
        return matmul(self, other)


class SparseCsrTensor:
    """CSR matrix: crows [rows+1], cols [nnz], values [nnz]
    (paddle/phi/core/sparse_csr_tensor.h)."""

    def __init__(self, crows, cols, values, shape):
        self._crows = _as_jnp(crows).astype(jnp.int64)
        self._cols = _as_jnp(cols).astype(jnp.int64)
        self._values = _as_jnp(values)
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        from ..framework.dtype import from_jax_dtype

        return from_jax_dtype(self._values.dtype)

    def nnz(self):
        return int(self._cols.shape[0])

    def crows(self):
        return Tensor._wrap(self._crows)

    def cols(self):
        return Tensor._wrap(self._cols)

    def values(self):
        return Tensor._wrap(self._values)

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def _row_ids(self):
        # expand crows to one row id per nonzero: rows[i] = #crows <= i
        return (jnp.searchsorted(self._crows, jnp.arange(self.nnz()),
                                 side="right") - 1).astype(jnp.int64)

    def to_sparse_coo(self, sparse_dim=2):
        idx = jnp.stack([self._row_ids(), self._cols])
        # cols are not guaranteed sorted within a row (user-built CSR), so
        # the COO view may not have sorted linear ids: let consumers
        # coalesce (which sorts) rather than claim it here
        return SparseCooTensor(idx, self._values, self._shape,
                               coalesced=False)

    def to_dense(self):
        return self.to_sparse_coo().to_dense()

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------

def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """Reference sparse/creation.py sparse_coo_tensor."""
    idx = _as_jnp(indices).astype(jnp.int64)
    vals = _as_jnp(values)
    if dtype is not None:
        from ..framework.dtype import to_jax_dtype

        vals = vals.astype(to_jax_dtype(dtype))
    if shape is None:
        if idx.shape[1] == 0:
            raise ValueError(
                "shape is required for an empty sparse_coo_tensor (no "
                "indices to infer it from)")
        sparse_shape = tuple(int(m) + 1 for m in np.asarray(idx.max(axis=1)))
        shape = sparse_shape + vals.shape[1:]
    return SparseCooTensor(idx, vals, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    """Reference sparse/creation.py sparse_csr_tensor."""
    vals = _as_jnp(values)
    if dtype is not None:
        from ..framework.dtype import to_jax_dtype

        vals = vals.astype(to_jax_dtype(dtype))
    return SparseCsrTensor(_as_jnp(crows), _as_jnp(cols), vals, shape)


def _coo(x):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    if not isinstance(x, SparseCooTensor):
        raise TypeError(f"expected a sparse tensor, got {type(x)}")
    return x


def _rewrap(x, coo_out):
    """Return in the caller's layout (csr in -> csr out)."""
    if isinstance(x, SparseCsrTensor):
        return coo_out.to_sparse_csr()
    return coo_out


# ---------------------------------------------------------------------------
# unary: values-only (sparsity-preserving) ops — reference sparse/unary.py
# ---------------------------------------------------------------------------

def _first_slot_mask(c):
    """Bool [nnz]: True on the first slot of each duplicate-coordinate run
    of a COALESCED tensor (static coalesce keeps duplicate slots with zero
    values — see coalesce). Value-transforming ops must only touch first
    slots, since f(0) != 0 ops would otherwise resurrect the zero fillers."""
    if c.nnz() == 0:
        return jnp.zeros((0,), bool)
    ids = c._linear_ids()
    return jnp.concatenate([jnp.ones((1,), bool), ids[1:] != ids[:-1]])


def _apply_values(x, fn):
    """Coalesce, apply fn to the (summed) values, and keep duplicate filler
    slots at zero — so duplicate-index inputs behave like their dense
    equivalent."""
    c = coalesce(_coo(x))
    vals = fn(c._values)
    first = _first_slot_mask(c)
    shape = (-1,) + (1,) * (vals.ndim - 1)
    vals = jnp.where(first.reshape(shape), vals, jnp.zeros_like(vals))
    return _rewrap(x, SparseCooTensor(c._indices, vals, c._shape,
                                      coalesced=True))


def _unary(fn):
    def op(x, name=None):
        return _apply_values(x, fn)

    return op


sin = _unary(jnp.sin)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
tanh = _unary(jnp.tanh)
asinh = _unary(jnp.arcsinh)
atanh = _unary(jnp.arctanh)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
log1p = _unary(jnp.log1p)
abs = _unary(jnp.abs)
neg = _unary(jnp.negative)
deg2rad = _unary(jnp.deg2rad)
rad2deg = _unary(jnp.rad2deg)
expm1 = _unary(jnp.expm1)
isnan = _unary(jnp.isnan)


def pow(x, factor, name=None):
    return _apply_values(x, lambda v: jnp.power(v, factor))


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..framework.dtype import to_jax_dtype

    c = _coo(x)
    idx = c._indices
    vals = c._values
    if index_dtype is not None:
        idx = idx.astype(to_jax_dtype(index_dtype))
    if value_dtype is not None:
        vals = vals.astype(to_jax_dtype(value_dtype))
    return _rewrap(x, SparseCooTensor(idx, vals, c._shape,
                                      coalesced=c._coalesced))


def coalesce(x, name=None):
    """Sort indices and sum duplicates. Static-shape form: nnz is
    preserved; each duplicate run keeps its coordinates but carries the
    run's sum in its FIRST slot and zeros in the rest, so ids stay sorted
    (a requirement of the searchsorted alignment in binary ops) and
    `to_dense` is exact."""
    c = _coo(x)
    if c._coalesced or c.nnz() == 0:
        return _rewrap(x, c)
    ids = c._linear_ids()
    order = jnp.argsort(ids)
    ids_s = ids[order]
    vals_s = c._values[order]
    idx_s = c._indices[:, order]
    first = jnp.concatenate([jnp.ones((1,), bool), ids_s[1:] != ids_s[:-1]])
    seg = jnp.cumsum(first) - 1
    n = c.nnz()
    summed = jax.ops.segment_sum(vals_s, seg, num_segments=n)
    vals_new = jnp.where(
        first.reshape((-1,) + (1,) * (vals_s.ndim - 1)), summed[seg],
        jnp.zeros_like(vals_s))
    return _rewrap(x, SparseCooTensor(idx_s, vals_new, c._shape,
                                      coalesced=True))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    """Reduction over the stored values only — never densifies the full
    tensor (an axis reduction scatter-adds values into the REDUCED dense
    shape, which is what the caller receives anyway)."""
    c = _coo(x)
    if c.dense_dim() != 0:
        raise NotImplementedError("sum of hybrid sparse tensors")
    if axis is None:
        out = jnp.sum(c._values)
        if keepdim:
            out = out.reshape((1,) * len(c._shape))
    else:
        ax = axis % len(c._shape)
        keep = [d for d in range(len(c._shape)) if d != ax]
        red_shape = tuple(c._shape[d] for d in keep)
        out = jnp.zeros(red_shape, c._values.dtype)
        out = out.at[tuple(c._indices[d] for d in keep)].add(c._values)
        if keepdim:
            out = jnp.expand_dims(out, ax)
    if dtype is not None:
        from ..framework.dtype import to_jax_dtype

        out = out.astype(to_jax_dtype(dtype))
    return Tensor._wrap(out)


def transpose(x, perm, name=None):
    c = _coo(x)
    if c.dense_dim() != 0:
        raise NotImplementedError("transpose of hybrid sparse tensors")
    idx = jnp.stack([c._indices[p] for p in perm])
    shape = tuple(c._shape[p] for p in perm)
    return _rewrap(x, SparseCooTensor(idx, c._values, shape))


def reshape(x, shape, name=None):
    c = _coo(x)
    if c.dense_dim() != 0:
        raise NotImplementedError("reshape of hybrid sparse tensors")
    new_shape = tuple(int(s) for s in shape)
    if int(np.prod(new_shape)) != int(np.prod(c._shape)):
        raise ValueError(f"cannot reshape {c._shape} to {new_shape}")
    lin = c._linear_ids()
    strides = np.cumprod((new_shape + (1,))[::-1])[::-1][1:]
    s = jnp.asarray(strides.copy(), jnp.int64)
    idx = (lin[None, :] // s[:, None]) % jnp.asarray(
        np.asarray(new_shape, np.int64))[:, None]
    return _rewrap(x, SparseCooTensor(idx, c._values, new_shape))


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


def mask_as(x, mask, name=None):
    """Pick values of dense `x` at `mask`'s sparsity pattern
    (reference sparse/binary.py mask_as). Duplicate mask slots gather the
    dense value once (fillers stay zero) so to_dense matches x*pattern."""
    m = coalesce(_coo(mask))
    xd = _as_jnp(x)
    vals = xd[tuple(m._indices)]
    first = _first_slot_mask(m)
    vals = jnp.where(first.reshape((-1,) + (1,) * (vals.ndim - 1)), vals,
                     jnp.zeros_like(vals))
    return _rewrap(mask, SparseCooTensor(m._indices, vals, m._shape,
                                         coalesced=True))


# ---------------------------------------------------------------------------
# binary — union/intersection alignment via sorted-id searchsorted
# ---------------------------------------------------------------------------

def _aligned_binary(a, b, fn):
    ca, cb = coalesce(_coo(a)), coalesce(_coo(b))
    if ca._shape != cb._shape:
        raise ValueError(f"shape mismatch {ca._shape} vs {cb._shape}")
    ids_a, ids_b = ca._linear_ids(), cb._linear_ids()
    # union pattern: concatenated (static nnz_a + nnz_b), re-coalesced
    idx_u = jnp.concatenate([ca._indices, cb._indices], axis=1)
    ids_u = jnp.concatenate([ids_a, ids_b])
    order = jnp.argsort(ids_u)
    ids_s = ids_u[order]
    idx_s = idx_u[:, order]

    def lookup(ids_sorted, vals, q):
        if vals.shape[0] == 0:   # empty operand contributes only zeros
            return jnp.zeros(q.shape + vals.shape[1:], vals.dtype)
        pos = jnp.searchsorted(ids_sorted, q)
        pos = jnp.clip(pos, 0, vals.shape[0] - 1)
        hit = ids_sorted[pos] == q
        v = vals[pos]
        return jnp.where(hit, v, jnp.zeros_like(v))

    va = lookup(ids_a, ca._values, ids_s)
    vb = lookup(ids_b, cb._values, ids_s)
    out_vals = fn(va, vb)
    # zero out duplicate union slots (keep first occurrence only)
    first = jnp.concatenate([jnp.ones((1,), bool), ids_s[1:] != ids_s[:-1]])
    out_vals = jnp.where(first, out_vals, jnp.zeros_like(out_vals))
    out = SparseCooTensor(idx_s, out_vals, ca._shape, coalesced=False)
    return _rewrap(a, out)


def add(x, y, name=None):
    if isinstance(y, Tensor):         # sparse + dense -> dense (reference)
        return Tensor._wrap(_coo(x).to_dense()._data + y._data)
    return _aligned_binary(x, y, jnp.add)


def subtract(x, y, name=None):
    if isinstance(y, Tensor):
        return Tensor._wrap(_coo(x).to_dense()._data - y._data)
    return _aligned_binary(x, y, jnp.subtract)


def multiply(x, y, name=None):
    if isinstance(y, (int, float)):
        c = _coo(x)
        return _rewrap(x, SparseCooTensor(c._indices, c._values * y,
                                          c._shape, c._coalesced))
    if isinstance(y, Tensor):         # sparse * dense: gather pattern
        c = _coo(x)
        vals = c._values * y._data[tuple(c._indices)]
        return _rewrap(x, SparseCooTensor(c._indices, vals, c._shape,
                                          c._coalesced))
    return _aligned_binary(x, y, jnp.multiply)


def divide(x, y, name=None):
    if isinstance(y, (int, float)):
        return multiply(x, 1.0 / y)
    if isinstance(y, Tensor):
        c = _coo(x)
        vals = c._values / y._data[tuple(c._indices)]
        return _rewrap(x, SparseCooTensor(c._indices, vals, c._shape,
                                          c._coalesced))
    return _aligned_binary(x, y, jnp.divide)


# ---------------------------------------------------------------------------
# matmul family — gather + segment_sum SpMM
# ---------------------------------------------------------------------------

def matmul(x, y, name=None):
    """sparse [M, N] @ dense [N, K] -> dense [M, K] (reference
    sparse/binary.py matmul over cusparse SpMM): one gather of y's rows at
    the nonzero columns and one segment-sum over rows — both native XLA."""
    c = coalesce(_coo(x))
    if c.sparse_dim() != 2 or c.dense_dim() != 0:
        raise NotImplementedError("matmul supports 2-D sparse matrices")
    yd = _as_jnp(y)
    rows, cols = c._indices[0], c._indices[1]
    contrib = c._values[:, None] * yd[cols]          # [nnz, K]
    out = jax.ops.segment_sum(contrib, rows, num_segments=c._shape[0])
    return Tensor._wrap(out)


def mv(x, vec, name=None):
    """sparse [M, N] @ dense [N] -> dense [M]."""
    c = coalesce(_coo(x))
    vd = _as_jnp(vec)
    rows, cols = c._indices[0], c._indices[1]
    return Tensor._wrap(jax.ops.segment_sum(
        c._values * vd[cols], rows, num_segments=c._shape[0]))


def masked_matmul(x, y, mask, name=None):
    """(dense @ dense) sampled at `mask`'s pattern (SDDMM — reference
    sparse/binary.py masked_matmul): per-nonzero row/col gathers + a
    contraction, never materializing the dense product."""
    m = _coo(mask)
    xd, yd = _as_jnp(x), _as_jnp(y)
    rows, cols = m._indices[0], m._indices[1]
    vals = (xd[rows] * yd[:, cols].T).sum(-1)
    return _rewrap(mask, SparseCooTensor(m._indices, vals, m._shape,
                                         coalesced=m._coalesced))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta * input + alpha * (x @ y) with sparse x (reference
    sparse/multiary.py addmm)."""
    prod = matmul(x, y)
    return Tensor._wrap(beta * _as_jnp(input) + alpha * prod._data)


# ---------------------------------------------------------------------------
# dense Tensor -> sparse conversions (eager-only: nnz is data-dependent)
# ---------------------------------------------------------------------------

def _tensor_to_sparse_coo(self, sparse_dim=None):
    """Dense -> COO (reference Tensor.to_sparse_coo). Eager-only: the
    nonzero pattern is data-dependent, so this cannot run under jit —
    construct sparse tensors outside traced code (as with every framework)."""
    a = np.asarray(self._data)
    sd = int(sparse_dim) if sparse_dim is not None else a.ndim
    if sd == a.ndim:
        reduced = a
    else:
        reduced = np.abs(a).sum(axis=tuple(range(sd, a.ndim)))
    nz = np.nonzero(reduced)
    idx = np.stack(nz).astype(np.int64)
    values = a[nz]
    return SparseCooTensor(idx, values, a.shape, coalesced=True)


def _tensor_to_sparse_csr(self):
    return _tensor_to_sparse_coo(self).to_sparse_csr()


Tensor.to_sparse_coo = _tensor_to_sparse_coo
Tensor.to_sparse_csr = _tensor_to_sparse_csr


def slice(x, axes, starts, ends, name=None):
    """Sparse slice (reference sparse/unary.py:1017 — sparse_slice
    kernels): keep entries whose coordinates fall inside
    [start, end) per sliced axis, shifting indices by the starts.
    Pattern-changing → eager-only, like construction (module docstring).
    Negative starts/ends wrap per dense-slice semantics."""
    import builtins

    coo = x if x.is_sparse_coo() else x.to_sparse_coo()
    idx = np.asarray(coo._indices)
    vals = np.asarray(coo._values)
    shape = builtins.list(coo.shape)
    axes = [int(a) for a in np.asarray(axes).reshape(-1)]
    starts = [int(s) for s in np.asarray(starts).reshape(-1)]
    ends = [int(e) for e in np.asarray(ends).reshape(-1)]
    keep = np.ones(idx.shape[1], bool)
    new_shape = builtins.list(shape)
    for a in axes:
        if a >= coo.sparse_dim():
            raise NotImplementedError(
                f"sparse.slice over dense (hybrid) dim {a} is not "
                f"supported (sparse_dim={coo.sparse_dim()})")
    for a, s, e in zip(axes, starts, ends):
        dim = shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        e = max(e, s)
        keep &= (idx[a] >= s) & (idx[a] < e)
        new_shape[a] = e - s
    idx = idx[:, keep]
    vals = vals[keep]
    for a, s, e in zip(axes, starts, ends):
        dim = shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        idx[a] = idx[a] - s
    out = SparseCooTensor(jnp.asarray(idx), jnp.asarray(vals),
                          tuple(new_shape), coalesced=coo._coalesced)
    return out if x.is_sparse_coo() else out.to_sparse_csr()


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """PCA of a sparse matrix (reference sparse/multiary.py pca_lowrank):
    returns (U, S, V) with x ~ U diag(S) V^T. The factorization itself
    is dense linear algebra (the reference calls svd_lowrank on a dense
    product too); the sparse input is densified once — at the static-nnz
    scales this backend targets that is the honest formulation."""
    d = x.to_dense() if hasattr(x, "to_dense") else x
    a = d._data if isinstance(d, Tensor) else jnp.asarray(d)
    m, n = a.shape
    if q is None:
        q = min(6, m, n)
    a = a.astype(jnp.float32)
    if center:
        a = a - jnp.mean(a, axis=0, keepdims=True)
    u, s, vt = jnp.linalg.svd(a, full_matrices=False)
    return (Tensor._wrap(u[:, :q]), Tensor._wrap(s[:q]),
            Tensor._wrap(vt[:q].T))
