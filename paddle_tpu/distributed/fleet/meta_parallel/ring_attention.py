"""Ring attention — exact long-context attention over a sequence-parallel
mesh axis.

Beyond-reference (SURVEY.md §5.7): the reference snapshot has only SEP
data-style sequence sharding (segment_parallel.py:26) and Megatron-SP; it
has NO ring/blockwise context parallelism. Here each device holds one
sequence block of q/k/v; k/v blocks rotate around the ring via
`ppermute` while an online-softmax accumulator (flash-attention math)
folds in one block per tick — memory O(seq/n) per device, comms riding
the ICI ring, and compute/transfer overlapped by XLA. The backward is the
reverse ring, derived by jax AD through the scan + ppermute (no
hand-written p2p bookkeeping).

Layout contract: q/k/v are [batch, seq, heads, head_dim] global arrays
sharded P(None, axis) on the sequence dim (SegmentParallel's layout).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

_NEG_INF = -1e30  # finite mask value: keeps exp/where AD clean vs real -inf


def _block_attend(q, k, v, row0, col0, scale, causal):
    """One q-block × kv-block flash step.

    q: [b, sq, h, d], k/v: [b, sk, h, d]; row0/col0: global offsets of the
    blocks on the sequence axis. Returns (scores_max m [b,h,sq], partial
    numerator acc [b,sq,h,d], partial denominator l [b,h,sq]).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        rows = row0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        cols = col0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    m = jnp.max(s, axis=-1)                                   # [b,h,q]
    p = jnp.exp(s - m[..., None])
    # fully-masked rows: m == NEG_INF -> p would be exp(0)=1; zero them
    alive = (m > _NEG_INF / 2)[..., None]
    p = jnp.where(alive, p, 0.0)
    l = jnp.sum(p, axis=-1)                                   # [b,h,q]
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return m, acc, l


def ring_attention(q, k, v, *, mesh, axis="sep", causal=True, scale=None):
    """Exact attention with q/k/v sequence-sharded over `axis`.

    Returns [batch, seq, heads, head_dim] with the same sharding as q.
    Differentiable (AD reverses the ring). Requires seq % mesh.shape[axis]
    == 0.
    """
    b, s, h, d = q.shape
    n = int(mesh.shape[axis])
    if s % n:
        raise ValueError(f"ring size {n} must divide seq {s}")
    blk = s // n
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def local(qb, kb, vb):
        # local blocks [b, blk, h, d]; manual over `axis` only
        idx = jax.lax.axis_index(axis)
        row0 = idx * blk

        m0 = jnp.full((b, h, blk), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, blk), jnp.float32)
        a0 = jnp.zeros((b, blk, h, d), jnp.float32)

        def tick(carry, t):
            m_run, l_run, acc_run, kv = carry
            kt, vt = kv
            src = (idx - t) % n             # whose block we hold this tick
            m_b, acc_b, l_b = _block_attend(qb, kt, vt, row0, src * blk,
                                            scale, causal)
            m_new = jnp.maximum(m_run, m_b)
            c_run = jnp.exp(m_run - m_new)      # [b,h,q]
            c_b = jnp.exp(m_b - m_new)
            l_new = l_run * c_run + l_b * c_b
            acc_new = (acc_run * jnp.transpose(c_run, (0, 2, 1))[..., None]
                       + acc_b * jnp.transpose(c_b, (0, 2, 1))[..., None])
            kv = jax.lax.ppermute((kt, vt), axis, perm)
            return (m_new, l_new, acc_new, kv), None

        (m_f, l_f, acc_f, _), _ = jax.lax.scan(
            tick, (m0, l0, a0, (kb, vb)), jnp.arange(n))
        l_safe = jnp.maximum(l_f, 1e-30)
        out = acc_f / jnp.transpose(l_safe, (0, 2, 1))[..., None]
        return out.astype(qb.dtype)

    spec = P(None, axis, None, None)
    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names=frozenset({axis}),
        check_vma=False,
    )(q, k, v)


def sep_sharding(mesh, axis="sep"):
    """The NamedSharding ring_attention expects on q/k/v."""
    return NamedSharding(mesh, P(None, axis, None, None))
