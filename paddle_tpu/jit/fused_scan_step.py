"""Memory-bounded whole-step training for scan-layers GPT models.

The generic TrainStep differentiates the whole scanned stack with jax.grad,
so the backward scan materializes EVERY layer's gradient before the
optimizer consumes any of them — measured to exceed a 16G chip by ~1.8G at
gpt3-1.3b (docs/DECISIONS.md §7). This module is the round-5 answer: a
manual, layer-at-a-time reverse scan with the Adam/AdamW update fused into
the scan carry, so exactly ONE layer's gradient is live at any point and
the program XLA compiles/loads is one block, not num_layers inlined copies.

Structure of the compiled step (all one jitted XLA program, donated state):

  forward:   x0 = embed(ids);  (xL, xs) = lax.scan(block, x0, P)
             — xs saves only each layer's INPUT (bf16, [L, b, s, h]);
             block intermediates die inside the scan step (manual remat).
  head:      loss, head_vjp = jax.vjp(ln_f ∘ lm_head ∘ CE);  dxL = vjp(1)
  backward:  carry = (dy, P, M1, M2, MASTER); reverse scan over (xs, i):
               p_i   = dynamic_index_in_dim(P, i)         (read old slice)
               dp,dx = vjp(block)(p_i, x_i)(dy)           (recompute fwd)
               adam  = Optimizer._adam_math(...)          (shared rule)
               P,M,V,MASTER updated at slot i via dynamic_update_index —
               the in-place pattern XLA aliases through while-loop carries,
               so the donated input stacks and the outputs share buffers.
  outer:     embedding/ln_f/head params update from head_vjp + embed vjp
             (tied embeddings sum both contributions, like the tape).

Why this fits: state floor (bf16 params 2x + fp32 masters 4x + bf16
moments 4x ≈ 10 bytes/param) plus ONE layer's grads and the [L,b,s,h]
bf16 input stash — vs the generic scan path's +2 bytes/param all-grads
set. And why it loads fast: the program is O(1 block) — the axon remote
program-load that costs ~40 min for the 24-layer unrolled 1.3b step
(memory: axon-tunnel-quirks) is minutes here, which is what lets the
north-star metric run LIVE inside the driver's bench window.

Reference parity: the roles of Paddle's gradient-merge + sharded optimizer
fusion passes (python/paddle/distributed/passes/auto_parallel_gradient_merge.py,
fuse_optimizer passes) — done here as one functional scan instead of IR
surgery. The update math is Optimizer._adam_math, the same single source
the eager and multi-tensor paths use, so parity with TrainStep is exact
in fp32 (tests/test_fused_scan_step.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.tensor import Tensor
from ..framework.autograd import no_grad
from ..framework import random as _random
from ..observability import RetraceSentinel
from ..profiler import RecordEvent
from .train_step import _commit_uncommitted

# PRNG draws reserved per layer forward (2 hidden dropouts + attention
# dropout + slack). The per-layer offset scheme below is
#   offset = ((step * num_layers + layer) * nranks + rank) * _RNG_SLOTS
# — collision-free across (step, layer, rank) until int32 wrap (~10^6
# steps at 24 layers), and identical between the forward trace and the
# backward's vjp recompute, which is what makes dropout legal inside the
# manually-rematerialized scan.
_RNG_SLOTS = 8


def _key(p):
    return p.name or str(id(p))


def _act_stats(in_fin, h_out):
    """Per-chunk activation health (ISSUE 15): ([sum(out²), count,
    origin], out_finite) where origin = input finite AND output
    non-finite — the forward provenance of a NaN (the chunk whose math
    broke, not the chunks its output then poisoned). ONE pass over the
    chunk output: finiteness is derived from the fp32 square-sum
    (NaN/inf propagate through it; the only false positive is a
    legitimately finite activation beyond ~1.8e19 whose square
    overflows fp32 — training is numerically dead long before that),
    and the input flag is the previous chunk's output flag threaded
    through the scan carry rather than a second pass."""
    o32 = h_out.astype(jnp.float32)
    sq = jnp.sum(jnp.square(o32))
    out_fin = jnp.isfinite(sq)
    return jnp.stack([sq, jnp.float32(o32.size),
                      (in_fin & ~out_fin).astype(jnp.float32)]), out_fin


def _donate_argnums():
    """State donation is a pure perf lever — forced off on the legacy
    jaxlib (0.4.x CPU corrupts donated buffers under scan-sized
    programs: NaN losses then hard aborts; the TrainStep guard)."""
    import sys as _sys

    legacy = getattr(_sys.modules.get("paddle_tpu"),
                     "jax_compat_legacy", False)
    return () if legacy else (0,)


class FusedScanTrainStep:
    """One-XLA-program train step for a scan_layers GPTForCausalLM (or any
    model with the same stacked-blocks shape) + Adam/AdamW.

    Usage matches TrainStep::

        step = FusedScanTrainStep(model, opt)   # model: scan_layers=True
        loss = step(ids, labels)                # one fused launch

    Constraints (asserted): Adam/AdamW without amsgrad/offload (pinned-host
    offload was measured counterproductive, docs/DECISIONS.md §8).

    Grad clip: ClipGradByValue applies elementwise inside the scan (free);
    ClipGradByGlobalNorm runs a DEFERRED-NORM two-pass backward — pass 1
    re-scans the vjp accumulating only the squared norm in the carry (each
    layer's grad still dies inside its iteration), pass 2 applies the
    clipped update. ~2x backward FLOPs, still O(1 layer) grad memory; the
    sharded step (jit/sharded_scan.py) gets the same clip for one scalar
    all-reduce instead, because its 1/N grad shards DO fit. Per-tensor
    ClipGradByNorm would need a whole stacked [L, ...] leaf's grad at
    once — unsupported here, use ClipGradByGlobalNorm or the sharded step.

    Dropout: supported. Each layer's dropout keys derive from
    (step, layer, rank) via a generator offset bound inside the scan body
    (_RNG_SLOTS scheme above), so the backward's recompute of layer i's
    forward draws exactly the masks the forward used.
    """

    def __init__(self, model, optimizer, criterion=None, fused_head=False,
                 compute_dtype=None, layer_chunk=1, scan_unroll=1,
                 scaler=None, guard_nonfinite=None, numerics=None):
        from ..models.gpt import GPTStackedBlocks, GPTPretrainingCriterion
        from ..optimizer import Adam
        from .nonfinite_guard import GuardSpec

        # in-graph non-finite guard: found_inf rides the backward pass as
        # a running scalar folded per layer chunk (alongside the squared
        # norm when clipping); all updates are where-gated so a NaN step
        # leaves params/moments/step bit-identical. Without a global-norm
        # clip the guard forces the same two-pass backward the clip uses
        # (grads must be inspected before the in-scan update consumes
        # them) — docs/DECISIONS.md §13.
        self._guard = (GuardSpec(scaler)
                       if (scaler is not None or guard_nonfinite)
                       else None)

        self.model = model
        blocks = model.gpt.blocks
        if not isinstance(blocks, GPTStackedBlocks):
            raise ValueError(
                "FusedScanTrainStep needs GPTConfig(scan_layers=True) "
                "(stacked [L, ...] block params); got an unrolled model — "
                "use jit.TrainStep there")
        self.optimizer = optimizer
        opt = optimizer
        seen = set()
        while hasattr(opt, "_inner_opt") and id(opt) not in seen:
            seen.add(id(opt))
            opt = opt._inner_opt
        if not isinstance(opt, Adam):
            raise ValueError("fused scan step supports Adam/AdamW only")
        self._clip_global = None      # ClipGradByGlobalNorm clip_norm
        self._clip_value = None       # ClipGradByValue (min, max)
        clip = opt._grad_clip
        if clip is not None:
            from ..nn.clip import (
                ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue,
            )

            if type(clip) is ClipGradByGlobalNorm:
                self._clip_global = float(clip.clip_norm)
            elif type(clip) is ClipGradByValue:
                self._clip_value = (float(clip.min), float(clip.max))
            elif isinstance(clip, ClipGradByNorm):
                raise ValueError(
                    "ClipGradByNorm clips each tensor by its OWN norm, "
                    "which for a stacked [L, ...] leaf needs all L "
                    "layers' grads at once — exactly what this step "
                    "never materializes. Use ClipGradByGlobalNorm "
                    "(deferred-norm two-pass here, one scalar "
                    "all-reduce in ShardedFusedScanTrainStep) or "
                    "ClipGradByValue (elementwise, free in-scan)")
            else:
                raise ValueError(
                    f"unsupported grad_clip {type(clip).__name__}: the "
                    "fused scan step supports ClipGradByGlobalNorm and "
                    "ClipGradByValue (subclasses with custom semantics "
                    "would be silently miscomputed, so they are "
                    "rejected)")
        if opt._amsgrad:
            raise ValueError("amsgrad moment2_max not supported")
        if opt._offload_masters:
            raise ValueError(
                "master offload defeats the in-scan update (measured "
                "worse, docs/DECISIONS.md §8)")
        cfg = model.config
        # dropout is legal here: the per-layer PRNG offset binding
        # (_RNG_SLOTS scheme) makes the backward's block recompute draw
        # the same masks the forward did
        self._dropout_active = bool(
            getattr(cfg, "hidden_dropout_prob", 0.0)
            or getattr(cfg, "attention_dropout_prob", 0.0))
        self._opt = opt
        self._crit = criterion or GPTPretrainingCriterion()
        # fused_head=True routes the LM head through the chunked-logsumexp
        # fused CE (F.fused_linear_cross_entropy) instead of dense logits +
        # criterion: the dense head's [tokens, vocab] logits + fp32 CE
        # residuals are ~2.5G of the 1.3b step's temps — the measured
        # difference between fitting 16G HBM and not (tools/diag_fused_mem).
        # Numerically equal to the criterion path (models/gpt.fused_lm_loss).
        self._fused_head = bool(fused_head)
        # compute_dtype="bfloat16" with FP32-STORED params is the
        # memory-optimal single-chip AMP-O2 layout: rather than keeping a
        # bf16 param stack AND an fp32 master stack (2+4 bytes/param),
        # store only fp32 and materialize the bf16 view per layer inside
        # the scan (transient ~one layer). Identical math — the bf16 copy
        # the masters scheme computes with IS cast(master) at all times —
        # but 2 bytes/param less HBM: at 1.3b that is the 2.45G between
        # the 15.3G measured-OOM peak and a fitting 12.9G
        # (tools/diag_fused_mem.py).
        from ..framework.dtype import to_jax_dtype

        self._compute_dtype = (to_jax_dtype(compute_dtype)
                               if compute_dtype is not None else None)
        self._blocks = blocks
        self._template = blocks._template
        self._t_leaves = [p for _, p in self._template.named_parameters()]
        # MoE blocks (ISSUE 9): the template's MoE layers publish a
        # load-balance aux loss per forward; it rides the scan as a ys
        # output and is folded into the training loss with weight
        # moe_aux_weight/num_layers (the model-level layer mean), with
        # matching cotangents injected into every chunk vjp
        from ..incubate.distributed.models.moe.moe_layer import MoELayer

        self._aux_layers = [
            s for _, s in self._template.named_sublayers(
                include_self=True) if isinstance(s, MoELayer)]
        self._aux_active = bool(self._aux_layers)
        self._aux_weight = (float(getattr(cfg, "moe_aux_weight", 0.0))
                            if self._aux_active else 0.0)
        self._s_params = [blocks._parameters[flat]
                          for flat, _ in blocks._stacked_names]
        self._o_params = [(n, p) for n, p in model.named_parameters()
                          if "blocks__" not in n and p.trainable]
        self._buffers = list(model.buffers())
        # scan-over-chunks: unroll `layer_chunk` layers inside each scan
        # step. One scan iteration per layer serializes at every layer
        # boundary (the iteration barrier stops XLA from overlapping one
        # layer's optimizer slices/HBM traffic with the next layer's
        # compute — measured 7% under the unrolled program at 1.3b);
        # unrolling K layers per step restores intra-chunk overlap while
        # keeping the program O(K blocks) and the simultaneous-grad set
        # O(K layers). Memory cost ≈ K× the per-layer vjp residuals.
        # scan_unroll: lax.scan-native iteration unrolling — K iterations
        # merged per while-loop step, so XLA can overlap adjacent layers'
        # optimizer traffic with compute WITHOUT changing the per-layer
        # vjp/remat structure (unlike layer_chunk, whose K-layer vjp was
        # measured slower at 1.3b: 10.7k vs 12.0k tok/s).
        self._scan_unroll = int(scan_unroll)
        n_layers = model.config.num_layers
        self._layer_chunk = int(layer_chunk)
        if self._layer_chunk < 1 or n_layers % self._layer_chunk:
            raise ValueError(
                f"layer_chunk {layer_chunk} must divide num_layers "
                f"{n_layers}")
        if self._compute_dtype is not None:
            for p in self._s_params + [p for _, p in self._o_params]:
                if p._data.dtype != jnp.float32:
                    raise ValueError(
                        "compute_dtype expects fp32-stored params (the "
                        f"param IS the master); got {p._data.dtype}")
        # training-numerics observatory (ISSUE 15): per-layer-chunk
        # grad/param/update/activation stats ride the scans as one
        # fixed-shape [chunks+1, k] block (the trailing row is the
        # outer embed/ln_f/head group), consumed lazily by the monitor
        # — default ON (FLAGS_numerics_monitor; DECISIONS §21)
        from ..observability.numerics import (
            NumericsMonitor, monitor_enabled,
        )

        self._numerics = None
        if (bool(numerics) if numerics is not None
                else monitor_enabled()):
            K0 = self._layer_chunk
            C0 = n_layers // K0
            labels = [(f"chunk{c}(layer {c * K0})" if K0 == 1 else
                       f"chunk{c}(layers {c * K0}-{(c + 1) * K0 - 1})")
                      for c in range(C0)] + ["outer"]
            self._numerics = NumericsMonitor(
                type(self).__name__, C0 + 1, row_labels=labels)
        self._jitted = None
        # retrace sentinel (ISSUE 12): the optional segment-id arg is a
        # declared presence-varying signature (None and seg each
        # compile once); anything else that recompiles is attributed
        self._sentinel = RetraceSentinel(type(self).__name__,
                                         optional=("segment_ids",))
        self._canon_done = False   # one-time layout canon at first call
        # adopt the optimizer's existing step count: continuing a run
        # that already trained under TrainStep must not reset the Adam
        # bias corrections to t=1 (r5 review finding)
        self._step_count = int(opt._step_count)

    # -- input pipeline -------------------------------------------------
    def input_sharding(self):
        """Single-chip step: None → default-device placement (identical
        to `paddle.to_tensor`, so prefetched batches hit the same
        executable). ShardedFusedScanTrainStep overrides with its
        dp-sharded batch spec."""
        return None

    def prefetch(self, loader, depth=2, **kw):
        """Wrap `loader` in an `io.DevicePrefetcher` bound to this step's
        input sharding (see TrainStep.prefetch)."""
        from ..io.device_prefetcher import DevicePrefetcher

        kw.setdefault("sharding", self.input_sharding())
        return DevicePrefetcher(loader, depth=depth, **kw)

    # -- per-layer PRNG plumbing (dropout inside the scan) --------------
    # the sharded subclass overrides these with the dp-axis rank so every
    # rank draws distinct masks for its own batch rows
    _rng_nranks = 1

    def _rng_rank(self):
        return 0

    def _rng_base(self, t32, layer):
        """Traced generator offset for `layer` at step t32 (int32); slot
        `num_layers` is the embedding dropout. None when the model has no
        dropout."""
        if not self._dropout_active:
            return None
        n_slots = self.model.config.num_layers + 1
        return ((t32 * n_slots + layer) * self._rng_nranks
                + self._rng_rank()) * _RNG_SLOTS

    def _rng_chunk_base(self, t32, chunk_i):
        if not self._dropout_active:
            return None
        return self._rng_base(t32, chunk_i * self._layer_chunk)

    def _chunk_apply(self, chunk_leaves, h, rng0=None):
        """layer_chunk layers unrolled: chunk_leaves are [K, ...]
        slices; rng0 is the chunk's first-layer PRNG offset (None
        without dropout). Shared by the single-device and sharded
        builds — the rng stride here and _rng_base are one scheme.
        MoE templates return (h, aux_sum) — the chunk's summed
        load-balance loss rides alongside the activations."""
        stride = self._rng_nranks * _RNG_SLOTS
        if not self._aux_active:
            for j in range(self._layer_chunk):
                off = None if rng0 is None else rng0 + j * stride
                h = self._block_fn([a[j] for a in chunk_leaves], h,
                                   rng_off=off)
            return h
        aux = jnp.float32(0.0)
        for j in range(self._layer_chunk):
            off = None if rng0 is None else rng0 + j * stride
            h, a = self._block_fn([a2[j] for a2 in chunk_leaves], h,
                                  rng_off=off)
            aux = aux + a
        return h, aux

    # -- pure functional views over the live layers ---------------------
    def _bind(self, params, datas):
        saved = [p._data for p in params]
        for p, d in zip(params, datas):
            p._data = d
        return saved

    def _cc(self, datas):
        """The compute-dtype view of fp32-stored params (identity when
        compute_dtype is unset). Differentiable: the cast's vjp upcasts
        the bf16 cotangent, exactly what the masters scheme feeds Adam."""
        if self._compute_dtype is None:
            return datas
        return [d.astype(self._compute_dtype) for d in datas]

    def _block_fn(self, leaf_datas, x, rng_off=None):
        """One decoder block as a pure jax function of (leaves, x).

        `rng_off` (traced int32 or None) pins the global generator's
        offset for the duration of the block, so every dropout draw
        inside is a pure function of (seed, rng_off, draw index) — the
        backward's vjp recompute passes the SAME rng_off and reproduces
        the forward's masks exactly."""
        tmpl = self._template
        gen = _random.default_generator()
        with no_grad():
            saved = self._bind(self._t_leaves, self._cc(leaf_datas))
            saved_off = gen._offset
            if rng_off is not None:
                gen._offset = rng_off
            try:
                # train() (not just .training=True): the template is no
                # registered sublayer, so its Dropout children only see
                # the mode set this way
                tmpl.train()
                out = tmpl._inner(Tensor._wrap(x))._data
                if self._aux_active:
                    aux = self._aux_layers[0].l_aux._data
                    for lyr in self._aux_layers[1:]:
                        aux = aux + lyr.l_aux._data
                    return out, aux.astype(jnp.float32)
                return out
            finally:
                gen._offset = saved_off
                self._bind(self._t_leaves, saved)

    def _embed_fn(self, o_datas, ids, pos, rng_off=None):
        m = self.model
        gen = _random.default_generator()
        with no_grad():
            saved = self._bind([p for _, p in self._o_params],
                               self._cc(o_datas))
            saved_off = gen._offset
            if rng_off is not None:
                gen._offset = rng_off
            try:
                x = m.gpt.wte(Tensor._wrap(ids)) + m.gpt.wpe(
                    Tensor._wrap(pos))
                if self._dropout_active:
                    # the eager forward applies embedding dropout
                    # (GPTModel.forward: self.drop) — keep parity
                    m.gpt.drop.training = True
                    x = m.gpt.drop(x)
                return x._data
            finally:
                gen._offset = saved_off
                self._bind([p for _, p in self._o_params], saved)

    def _head_fn(self, o_datas, xL, labels):
        """ln_f + LM head + criterion as a pure function of ALL outer
        params (unused ones get zero cotangents — that is how tied/untied
        heads are handled uniformly)."""
        m = self.model
        from .. import ops

        with no_grad():
            saved = self._bind([p for _, p in self._o_params],
                               self._cc(o_datas))
            try:
                h = m.gpt.ln_f(Tensor._wrap(xL))
                yT = Tensor._wrap(labels)
                if m.lm_head is None:
                    w, t_y = m.gpt.wte.weight, True
                else:
                    w, t_y = m.lm_head.weight, False
                if self._fused_head:
                    from ..models.gpt import fused_lm_loss

                    loss = fused_lm_loss(h, w, t_y, yT)
                else:
                    if m.lm_head is None:
                        logits = ops.matmul(h, m.gpt.wte.weight,
                                            transpose_y=True)
                    else:
                        logits = m.lm_head(h)
                    loss = self._crit(logits, yT)
                if getattr(m, "draft_heads", None) is not None:
                    # self-spec draft heads (ISSUE 20): same aux CE the
                    # eager loss() adds — heads are outer params, so
                    # their grads ride the o-param cotangents
                    from ..models.gpt import draft_head_loss

                    loss = loss + m.config.draft_head_loss_weight \
                        * draft_head_loss(m, h, w, t_y, yT)
                return loss._data
            finally:
                self._bind([p for _, p in self._o_params], saved)

    # -- state plumbing --------------------------------------------------
    def _extract_state(self):
        opt = self._opt
        m1 = opt._accumulators["moment1"]
        m2 = opt._accumulators["moment2"]

        def pack(params):
            return {
                "p": [p._data for p in params],
                "m": [m1[_key(p)] for p in params],
                "v": [m2[_key(p)] for p in params],
                "mw": [opt._master_weights.get(_key(p)) for p in params],
            }

        # the optimizer owns the step count: a checkpoint restore writes
        # opt._step_count (load_opt_state_pytree) and this read is what
        # makes the next compiled step see it
        self._step_count = opt._step_count
        state = {
            "s": pack(self._s_params),
            "o": pack([p for _, p in self._o_params]),
            "buf": [b._data for b in self._buffers],
            "step": jnp.asarray(self._step_count, jnp.int32),
        }
        if self._guard is not None:
            state["guard"] = self._guard.init_state()
        return state

    def _inject_state(self, state):
        opt = self._opt

        def unpack(params, st):
            for p, d, m, v, mw in zip(params, st["p"], st["m"], st["v"],
                                      st["mw"]):
                p._data = d
                opt._accumulators["moment1"][_key(p)] = m
                opt._accumulators["moment2"][_key(p)] = v
                if mw is not None:
                    opt._master_weights[_key(p)] = mw

        unpack(self._s_params, state["s"])
        unpack([p for _, p in self._o_params], state["o"])
        for b, d in zip(self._buffers, state["buf"]):
            b._data = d
        opt._step_count = state["step"]
        self._step_count = state["step"]
        if self._guard is not None and "guard" in state:
            self._guard.writeback(state["guard"])

    # -- the compiled step ----------------------------------------------
    def _build(self):
        opt = self._opt
        # per-param host-side hyperparameters (static in the trace)
        def hyper(p):
            return (float(opt._decoupled_wd(p)), float(opt._l2_coeff(p)),
                    float(opt._param_lr_scale(p)))

        s_hyp = [hyper(p) for p in self._s_params]
        o_hyp = [hyper(p) for _, p in self._o_params]
        n_leaves = len(self._s_params)
        K = self._layer_chunk
        chunk_apply = self._chunk_apply

        def adam(pv, g32, m, v, lr, tf, wd, l2):
            if l2:
                g32 = g32 + l2 * pv.astype(jnp.float32)
            return opt._adam_math(pv, g32, m, v, None, lr, tf, wd)

        cv = self._clip_value
        guard = self._guard
        scaling = guard is not None and guard.scaling
        nm = self._numerics is not None
        aux_active = self._aux_active
        # per-chunk aux cotangent: total loss adds
        # (moe_aux_weight / L) * sum(per-layer aux)
        aux_w = self._aux_weight / self.model.config.num_layers

        def clip_g32(g32, p):
            """The per-grad transforms that are legal inside the scan:
            elementwise value clip, and the deferred global-norm scale
            (traced scalar, resolved before the update scan runs)."""
            if cv is not None and getattr(p, "need_clip", True):
                g32 = jnp.clip(g32, cv[0], cv[1])
            return g32

        def scaled(g32, p, scale):
            if scale is not None and getattr(p, "need_clip", True):
                g32 = g32 * scale
            return g32

        from ..nn.functional.flash_attention import attention_segments

        def step_fn(state, lr, ids, labels, seg=None):
            s, o = state["s"], state["o"]
            saved_buf = self._bind(self._buffers, state["buf"])
            # publish packed-sequence segment ids to every attention
            # layer traced in this step (forward scan, the norm/guard
            # pre-pass, and the backward recompute all see the same
            # traced value — the vjp replays attention with the same
            # mask the forward used)
            seg_ctx = attention_segments(seg)
            seg_ctx.__enter__()
            try:
                gst = state.get("guard")
                # loss-scale: seed the head cotangent with the traced
                # scale instead of 1.0 — every grad in both backward
                # passes comes out scaled, the loss itself stays unscaled
                inv_s = (1.0 / gst["scale"]) if scaling else None
                t = state["step"] + 1
                tf = t.astype(jnp.float32)
                b, seq = ids.shape
                pos = jnp.arange(seq, dtype=ids.dtype)[None, :]

                t32 = t.astype(jnp.int32)
                n_layers = self.model.config.num_layers

                # ---- forward: embed + scan over chunks of K layers,
                # saving only each CHUNK's input
                x0 = self._embed_fn(o["p"], ids, pos,
                                    rng_off=self._rng_base(t32, n_layers))
                sp_c = tuple(a.reshape((a.shape[0] // K, K)
                                       + tuple(a.shape[1:]))
                             for a in s["p"])
                sm_c = tuple(a.reshape((a.shape[0] // K, K)
                                       + tuple(a.shape[1:]))
                             for a in s["m"])
                sv_c = tuple(a.reshape((a.shape[0] // K, K)
                                       + tuple(a.shape[1:]))
                             for a in s["v"])
                smw_c = tuple(a.reshape((a.shape[0] // K, K)
                                        + tuple(a.shape[1:]))
                              if a is not None else None
                              for a in s["mw"])

                C = sp_c[0].shape[0]

                def fwd_body(carry, scanned):
                    h, h_fin = carry if nm else (carry, None)
                    p_chunk, i = scanned
                    rng0 = self._rng_chunk_base(t32, i)
                    if aux_active:
                        h2, aux = chunk_apply(p_chunk, h, rng0)
                    else:
                        h2, aux = chunk_apply(p_chunk, h, rng0), None
                    ys = {"x": h}
                    if aux_active:
                        ys["aux"] = aux
                    if not nm:
                        return h2, ys
                    ys["act"], out_fin = _act_stats(h_fin, h2)
                    return (h2, out_fin), ys

                fwd0 = ((x0, jnp.isfinite(x0).all()) if nm else x0)
                fwd_c, ys = lax.scan(
                    fwd_body, fwd0, (sp_c, jnp.arange(C)),
                    unroll=self._scan_unroll)
                xL = fwd_c[0] if nm else fwd_c
                xs, auxs = ys["x"], ys.get("aux")
                act_cols = ys.get("act")           # [C, 3] when nm

                # ---- head (+ its whole vjp: small params, one buffer)
                loss, head_vjp = jax.vjp(
                    lambda od, x: self._head_fn(od, x, labels), o["p"], xL)
                ct = (gst["scale"].astype(loss.dtype) if scaling
                      else jnp.ones((), loss.dtype))
                d_o_head, dxL = head_vjp(ct)
                aux_ct = None
                if aux_active:
                    # total loss = CE + (w/L) * sum(aux); the chunk vjps
                    # below receive the matching (loss-scaled) cotangent
                    loss = loss + jnp.float32(aux_w) * jnp.sum(auxs)
                    aux_ct = jnp.float32(aux_w) * ct.astype(jnp.float32)

                # ---- deferred global-norm clip / non-finite pre-pass
                # (pass 1 of 2): re-scan the vjp accumulating ONLY
                # scalars in the carry — the squared grad norm (clip)
                # and the finiteness fold (guard) — each layer's grad
                # still dies inside its iteration, so the memory plan is
                # unchanged; cost is a second backward
                # (docs/DECISIONS.md §12, §13). The embed-side outer
                # grads fall out of this pass's dx0 and are reused by
                # the update below (their math is identical).
                scale = None
                d_o_emb = None
                found = None
                grad_rows = None       # [C, 3] (sq, bad, origin) — nm
                if self._clip_global is not None or guard is not None:
                    from .nonfinite_guard import all_finite

                    want_norm = self._clip_global is not None

                    def norm_body(carry, scanned):
                        dy, sq, fin = carry
                        x_i, i = scanned
                        p_i = tuple(
                            lax.dynamic_index_in_dim(a, i, keepdims=False)
                            for a in P0)
                        rng0 = self._rng_chunk_base(t32, i)
                        _, vjp = jax.vjp(
                            lambda pl, xx: chunk_apply(pl, xx, rng0),
                            p_i, x_i)
                        dp, dx = vjp((dy, aux_ct) if aux_active else dy)
                        c_fin = None
                        if guard is not None:
                            # the guard's fold stays an EXACT isfinite
                            # (its skip decision must not inherit the
                            # square-sum overflow caveat)
                            c_fin = all_finite(
                                [dp[j] for j in range(n_leaves)
                                 if self._s_params[j].trainable])
                            fin = fin & c_fin
                        # the clip carry and the monitor's per-chunk
                        # grad sq-norm share one set of per-leaf
                        # reductions (ISSUE 15 dedup: the monitor
                        # reads the clip's terms when clipping is on,
                        # computes them only when off)
                        c_sq = jnp.float32(0.0)
                        for j in range(n_leaves):
                            p = self._s_params[j]
                            if not p.trainable:
                                continue
                            clipped = want_norm and getattr(
                                p, "need_clip", True)
                            if not (clipped or nm):
                                continue
                            s_j = jnp.sum(jnp.square(
                                dp[j].astype(jnp.float32)))
                            if clipped:
                                sq = sq + s_j
                            if nm:
                                c_sq = c_sq + s_j
                        row = None
                        if nm:
                            # without a guard the finite flag derives
                            # from the sq-norm (NaN/inf propagate) —
                            # no extra pass over the grads
                            if c_fin is None:
                                c_fin = jnp.isfinite(c_sq)
                            row = jnp.stack([
                                c_sq, (~c_fin).astype(jnp.float32),
                                jnp.float32(0.0)])
                        return (dx, sq, fin), row

                    P0 = sp_c
                    (dx0, sq, fin), grad_rows = lax.scan(
                        norm_body,
                        (dxL, jnp.float32(0.0), jnp.bool_(True)),
                        (xs, jnp.arange(C)), reverse=True,
                        unroll=self._scan_unroll)
                    _, emb_vjp = jax.vjp(
                        lambda od: self._embed_fn(
                            od, ids, pos,
                            rng_off=self._rng_base(t32, n_layers)),
                        o["p"])
                    (d_o_emb,) = emb_vjp(dx0)
                    o_g32 = [(d_o_head[j].astype(jnp.float32)
                              + d_o_emb[j].astype(jnp.float32))
                             for j in range(len(o["p"]))]
                    if guard is not None:
                        found = ~(fin & all_finite(o_g32))
                    if want_norm:
                        for j in range(len(o["p"])):
                            if not getattr(self._o_params[j][1],
                                           "need_clip", True):
                                continue
                            sq = sq + jnp.sum(jnp.square(o_g32[j]))
                        # grads (hence sq) carry the loss scale: the
                        # true norm is sqrt(sq)/loss_scale
                        gnorm = jnp.sqrt(sq)
                        if inv_s is not None:
                            gnorm = gnorm * inv_s
                        scale = jnp.minimum(
                            jnp.float32(self._clip_global)
                            / jnp.maximum(gnorm, 1e-12), 1.0)

                # ---- reverse scan: vjp one CHUNK, update its slices
                def bwd_body(carry, scanned):
                    dy, P, M, V, MW = carry
                    x_i, i = scanned
                    p_i = tuple(
                        lax.dynamic_index_in_dim(a, i, keepdims=False)
                        for a in P)          # [K, ...] slices
                    rng0 = self._rng_chunk_base(t32, i)
                    _, vjp = jax.vjp(
                        lambda pl, xx: chunk_apply(pl, xx, rng0), p_i, x_i)
                    dp, dx = vjp((dy, aux_ct) if aux_active else dy)
                    ys_b = {}
                    p_sq = u_sq = None
                    if nm:
                        p_sq = jnp.float32(0.0)
                        u_sq = jnp.float32(0.0)
                        if grad_rows is None:
                            # no clip/guard pre-pass ran: the monitor's
                            # grad stats come from THIS backward's dp
                            # (finiteness derives from the sq-norm)
                            c_sq = jnp.float32(0.0)
                            for j in range(n_leaves):
                                if not self._s_params[j].trainable:
                                    continue
                                c_sq = c_sq + jnp.sum(jnp.square(
                                    dp[j].astype(jnp.float32)))
                            ys_b["g"] = jnp.stack([
                                c_sq,
                                (~jnp.isfinite(c_sq))
                                .astype(jnp.float32),
                                jnp.float32(0.0)])
                    nP, nM, nV, nMW = [], [], [], []
                    for j in range(n_leaves):
                        if not self._s_params[j].trainable:
                            # frozen stacked leaf: no update (XLA DCEs
                            # its unused dp slice); parity with the
                            # tape path's stop_gradient handling
                            nP.append(P[j])
                            nM.append(M[j])
                            nV.append(V[j])
                            nMW.append(MW[j])
                            continue
                        wd, l2, lrs = s_hyp[j]
                        m_j = lax.dynamic_index_in_dim(M[j], i,
                                                       keepdims=False)
                        v_j = lax.dynamic_index_in_dim(V[j], i,
                                                       keepdims=False)
                        mw_j = (lax.dynamic_index_in_dim(
                            MW[j], i, keepdims=False)
                            if MW[j] is not None else None)
                        pv = mw_j if mw_j is not None else p_i[j]
                        g32 = dp[j].astype(jnp.float32)
                        if inv_s is not None:
                            g32 = g32 * inv_s
                        g32 = scaled(clip_g32(g32, self._s_params[j]),
                                     self._s_params[j], scale)
                        out, mn, vn, _ = adam(
                            pv, g32, m_j, v_j,
                            lr * lrs, tf, jnp.float32(wd), l2)
                        if nm:
                            pv32 = pv.astype(jnp.float32)
                            d_upd = out.astype(jnp.float32) - pv32
                            if found is not None:
                                d_upd = jnp.where(
                                    found, jnp.zeros_like(d_upd), d_upd)
                            p_sq = p_sq + jnp.sum(jnp.square(pv32))
                            u_sq = u_sq + jnp.sum(jnp.square(d_upd))
                        out_p = out.astype(P[j].dtype)
                        mn_c = mn.astype(M[j].dtype)
                        vn_c = vn.astype(V[j].dtype)
                        if found is not None:
                            # bad step: every slot passes through
                            # bit-identical (selection, not arithmetic)
                            out_p = jnp.where(found, p_i[j], out_p)
                            mn_c = jnp.where(found, m_j, mn_c)
                            vn_c = jnp.where(found, v_j, vn_c)
                            if mw_j is not None:
                                out = jnp.where(found, mw_j, out)
                        nP.append(lax.dynamic_update_index_in_dim(
                            P[j], out_p, i, 0))
                        nM.append(lax.dynamic_update_index_in_dim(
                            M[j], mn_c, i, 0))
                        nV.append(lax.dynamic_update_index_in_dim(
                            V[j], vn_c, i, 0))
                        nMW.append(lax.dynamic_update_index_in_dim(
                            MW[j], out, i, 0)
                            if MW[j] is not None else None)
                    if nm:
                        ys_b["pu"] = jnp.stack([p_sq, u_sq])
                    return (dx, tuple(nP), tuple(nM), tuple(nV),
                            tuple(nMW)), ys_b

                carry0 = (dxL, sp_c, sm_c, sv_c, smw_c)
                (dx0, nP, nM, nV, nMW), bwd_ys = lax.scan(
                    bwd_body, carry0, (xs, jnp.arange(C)), reverse=True,
                    unroll=self._scan_unroll)
                # back to the [L, ...] stacked layout
                nP = [a.reshape((-1,) + tuple(a.shape[2:])) for a in nP]
                nM = [a.reshape((-1,) + tuple(a.shape[2:])) for a in nM]
                nV = [a.reshape((-1,) + tuple(a.shape[2:])) for a in nV]
                nMW = [a.reshape((-1,) + tuple(a.shape[2:]))
                       if a is not None else None for a in nMW]

                # ---- embedding-side grads for outer params + update
                # (already computed by the norm pass when clipping)
                if d_o_emb is None:
                    _, emb_vjp = jax.vjp(
                        lambda od: self._embed_fn(
                            od, ids, pos,
                            rng_off=self._rng_base(t32, n_layers)),
                        o["p"])
                    (d_o_emb,) = emb_vjp(dx0)
                new_o = {"p": [], "m": [], "v": [], "mw": []}
                if nm:
                    o_g_sq = jnp.float32(0.0)
                    o_p_sq = jnp.float32(0.0)
                    o_u_sq = jnp.float32(0.0)
                for j in range(len(o["p"])):
                    wd, l2, lrs = o_hyp[j]
                    g32 = (d_o_head[j].astype(jnp.float32)
                           + d_o_emb[j].astype(jnp.float32))
                    if nm:
                        # raw (still loss-scaled) grads — the inv_s²
                        # unscale is applied once at assembly below
                        o_g_sq = o_g_sq + jnp.sum(jnp.square(g32))
                    if inv_s is not None:
                        g32 = g32 * inv_s
                    g32 = scaled(clip_g32(g32, self._o_params[j][1]),
                                 self._o_params[j][1], scale)
                    pv = (o["mw"][j] if o["mw"][j] is not None
                          else o["p"][j])
                    out, mn, vn, _ = adam(pv, g32, o["m"][j], o["v"][j],
                                          lr * lrs, tf, jnp.float32(wd),
                                          l2)
                    if nm:
                        pv32 = pv.astype(jnp.float32)
                        d_upd = out.astype(jnp.float32) - pv32
                        if found is not None:
                            d_upd = jnp.where(
                                found, jnp.zeros_like(d_upd), d_upd)
                        o_p_sq = o_p_sq + jnp.sum(jnp.square(pv32))
                        o_u_sq = o_u_sq + jnp.sum(jnp.square(d_upd))
                    out_p = out.astype(o["p"][j].dtype)
                    mn_c = mn.astype(o["m"][j].dtype)
                    vn_c = vn.astype(o["v"][j].dtype)
                    if found is not None:
                        out_p = jnp.where(found, o["p"][j], out_p)
                        mn_c = jnp.where(found, o["m"][j], mn_c)
                        vn_c = jnp.where(found, o["v"][j], vn_c)
                        if o["mw"][j] is not None:
                            out = jnp.where(found, o["mw"][j], out)
                    new_o["p"].append(out_p)
                    new_o["m"].append(mn_c)
                    new_o["v"].append(vn_c)
                    new_o["mw"].append(out if o["mw"][j] is not None
                                       else None)

                new_state = {
                    "s": {"p": list(nP), "m": list(nM), "v": list(nV),
                          "mw": list(nMW)},
                    "o": new_o,
                    "buf": state["buf"],
                    "step": (t if found is None
                             else jnp.where(found, state["step"], t)),
                }
                if guard is not None:
                    new_state["guard"] = guard.update(gst, found)
                if not nm:
                    return loss, new_state
                # ---- the [C+1, NFIELDS] numerics block (ISSUE 15):
                # grad rows come from the clip/guard pre-pass when it
                # ran (shared reductions), else from the update
                # backward; act rows rode the forward scan's ys
                from ..observability import numerics as _num

                g_cols = (grad_rows if grad_rows is not None
                          else bwd_ys["g"])            # [C, 3]
                g_sq, g_bad, g_orig = (g_cols[:, 0], g_cols[:, 1],
                                       g_cols[:, 2])
                og_sq = o_g_sq
                if inv_s is not None:
                    s2 = inv_s * inv_s       # grads carried the scale
                    g_sq = g_sq * s2
                    og_sq = og_sq * s2
                stats = _num.assemble_stats(
                    g_sq, bwd_ys["pu"][:, 0], bwd_ys["pu"][:, 1],
                    act_cols[:, 0], act_cols[:, 1], g_bad,
                    act_cols[:, 2], g_orig,
                    outer=_num.outer_row(
                        og_sq, o_p_sq, o_u_sq,
                        (~jnp.isfinite(o_g_sq))
                        .astype(jnp.float32)))
                return loss, new_state, stats
            finally:
                seg_ctx.__exit__(None, None, None)
                self._bind(self._buffers, saved_buf)

        from .compile_cache import cached_jit

        self._jitted = cached_jit(step_fn,
                                  donate_argnums=_donate_argnums(),
                                  label=type(self).__name__)

    def _pre_step(self):
        """Hook: runs at the top of __call__, before state extraction.
        The sharded-parameter-storage step folds external `p._data`
        writes (checkpoint restore, test poking) back into its 1/N
        flat shards here."""

    def _step_guard(self):
        """Hook: context wrapping the compiled-step dispatch (and its
        first-call trace). The sharded-parameter-storage step returns
        its raw-access guard so `_bind`'s tracer shuffling through the
        live Parameters bypasses the lazy shard machinery."""
        import contextlib

        return contextlib.nullcontext()

    def ensure_built(self):
        """Create the Adam state and trace the step (idempotent). Split
        out so diagnostics can AOT-lower the program (memory_analysis)
        without executing a step. warmup_state's dry-run is NOT used: it
        would eagerly execute the whole layer-chunked update chain —
        ~1.7k pointless dispatches through the axon tunnel at 1.3b."""
        if self._jitted is not None:
            return
        opt = self._opt
        for p in self._s_params + [p for _, p in self._o_params]:
            if opt._use_master(p):
                opt._master_weight(p)
            opt._get_accumulator("moment1", p, dtype=opt._moment_dtype)
            opt._get_accumulator("moment2", p, dtype=opt._moment_dtype)
        self._build()
        # live-buffer attribution (ISSUE 14): weakly tracked provider
        from ..observability.memory import live_registry

        live_registry().track(self)

    # -- telemetry surface ----------------------------------------------
    def retrace_stats(self):
        """Sentinel receipt (see TrainStep.retrace_stats)."""
        return self._sentinel.stats()

    def _cost_axis_degrees(self):
        """Mesh {axis: degree} for the per-axis comm census (None on a
        single chip; the sharded subclass reports its mesh)."""
        return None

    def cost_analysis(self, ids, labels, segment_ids=None):
        """HLO-derived per-step accounting: ``compiled.cost_analysis``
        flops/bytes + per-mesh-axis collective byte census, published
        as ``hlo.*`` registry gauges (ISSUE 12)."""
        from ..observability.hlo_costs import cost_analysis_of

        ids_d = ids._data if isinstance(ids, Tensor) else ids
        lab_d = labels._data if isinstance(labels, Tensor) else labels
        seg_d = (segment_ids._data if isinstance(segment_ids, Tensor)
                 else segment_ids)
        self.ensure_built()
        self._pre_step()
        state = self._extract_state()
        lr = jnp.asarray(self._opt.get_lr(), jnp.float32)
        with self._step_guard():
            return cost_analysis_of(
                self._jitted, state, lr, ids_d, lab_d, seg_d,
                axis_degrees=self._cost_axis_degrees())

    def memory_profile(self, ids, labels, segment_ids=None, top_k=8,
                       publish=True):
        """Compiled-step HBM accounting (ISSUE 14): AOT buffer-
        assignment stats of THIS step's compiled program — peak /
        argument / output / temp / alias bytes plus the top-K largest
        buffers with shapes and op provenance — without executing a
        step (see TrainStep.memory_profile). Published as
        ``mem.compiled.<step>.*`` gauges."""
        from ..observability.memory import CompiledMemoryProfile

        ids_d = ids._data if isinstance(ids, Tensor) else ids
        lab_d = labels._data if isinstance(labels, Tensor) else labels
        seg_d = (segment_ids._data if isinstance(segment_ids, Tensor)
                 else segment_ids)
        self.ensure_built()
        self._pre_step()
        state = self._extract_state()
        lr = jnp.asarray(self._opt.get_lr(), jnp.float32)
        with self._step_guard():
            prof = CompiledMemoryProfile.from_jitted(
                self._jitted, state, lr, ids_d, lab_d, seg_d,
                top_k=top_k)
        if publish:
            prof.publish(name=type(self).__name__)
        return prof

    def _opt_state_arrays(self):
        """Every optimizer-state array this step's update touches
        (flat moment buckets + master weights) — ONE collection
        implementation shared by both storage modes' attribution."""
        opt = self._opt
        acc = []
        for store in opt._accumulators.values():
            acc.extend(store.values())
        acc.extend(v for v in opt._master_weights.values()
                   if v is not None)
        return acc

    def _mem_owners(self):
        """Live-buffer attribution providers (observability.memory):
        params, flat optimizer-state buckets, model buffers. The
        sharded-parameter-storage subclass overrides the param leg so
        a scrape never gathers a shard."""
        return {"params": [p._data for p in self._s_params]
                + [p._data for _, p in self._o_params],
                "buffers": [b._data for b in self._buffers],
                "opt_state": self._opt_state_arrays()}

    def __call__(self, ids, labels, segment_ids=None):
        ids_d = ids._data if isinstance(ids, Tensor) else ids
        lab_d = labels._data if isinstance(labels, Tensor) else labels
        seg_d = (segment_ids._data if isinstance(segment_ids, Tensor)
                 else segment_ids)
        if self._jitted is None:
            self.ensure_built()
        self._pre_step()
        if not self._canon_done:
            # first call AFTER any restore (ensure_built may predate it,
            # quickstart order): a restored checkpoint leaves the params
            # device-committed while fresh scalars are uncommitted, which
            # would key one extra executable on the second call
            # (train_step._commit_uncommitted)
            canon = _commit_uncommitted(self._extract_state())
            if canon is not None:
                self._inject_state(canon)
            self._canon_done = True
        state = self._extract_state()
        lr = jnp.asarray(self._opt.get_lr(), jnp.float32)
        self._sentinel.observe(
            (state, lr, ids_d, lab_d, seg_d),
            names=("state", "lr", "ids", "labels", "segment_ids"))
        from ..observability.memory import oom_guard as _oom_guard

        with RecordEvent("FusedScanTrainStep"), self._step_guard(), \
                _oom_guard(
                    step=type(self).__name__,
                    profile=lambda: self.memory_profile(
                        ids_d, lab_d, seg_d, publish=False)):
            out = self._jitted(state, lr, ids_d, lab_d, seg_d)
        if self._numerics is not None:
            loss, new_state, nstats = out
            # deferred: the device block is enqueued, never read here —
            # the readback happens at the next gauge/endpoint flush
            self._numerics.on_step(nstats)
        else:
            loss, new_state = out
        self._inject_state(new_state)
        sched = getattr(self._opt, "_learning_rate", None)
        if hasattr(sched, "step"):
            sched.step()
        return Tensor._wrap(loss)
