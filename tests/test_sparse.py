"""paddle.sparse parity tests (reference python/paddle/sparse): COO/CSR
construction, dense round-trips, values-only unary ops, pattern-aligned
binary ops, SpMM/SDDMM, and sparse softmax — numpy dense ops are the
oracle, as in the reference's own test_sparse_* suites."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse


def _rand_coo(shape=(4, 5), nnz=6, seed=0):
    rng = np.random.default_rng(seed)
    dense = np.zeros(shape, np.float32)
    flat = rng.choice(np.prod(shape), size=nnz, replace=False)
    dense.flat[flat] = rng.standard_normal(nnz).astype(np.float32)
    idx = np.stack(np.nonzero(dense)).astype(np.int64)
    vals = dense[tuple(idx)]
    return sparse.sparse_coo_tensor(idx, vals, shape), dense


class TestCreationAndConversion:
    def test_coo_to_dense_roundtrip(self):
        sp, dense = _rand_coo()
        np.testing.assert_allclose(np.asarray(sp.to_dense()._data), dense)
        back = paddle.to_tensor(dense).to_sparse_coo()
        np.testing.assert_allclose(np.asarray(back.to_dense()._data), dense)

    def test_coo_duplicate_indices_coalesce(self):
        idx = np.array([[0, 0, 1], [1, 1, 2]])
        vals = np.array([1.0, 2.0, 3.0], np.float32)
        sp = sparse.sparse_coo_tensor(idx, vals, (2, 3))
        d = np.asarray(sparse.coalesce(sp).to_dense()._data)
        assert d[0, 1] == 3.0 and d[1, 2] == 3.0

    def test_csr_roundtrip(self):
        sp, dense = _rand_coo((3, 4), 5, seed=1)
        csr = sp.to_sparse_csr()
        assert csr.is_sparse_csr()
        np.testing.assert_allclose(np.asarray(csr.to_dense()._data), dense)
        np.testing.assert_allclose(
            np.asarray(csr.to_sparse_coo().to_dense()._data), dense)

    def test_csr_direct_construction(self):
        # [[0, 1, 0], [2, 0, 3]]
        csr = sparse.sparse_csr_tensor([0, 1, 3], [1, 0, 2],
                                       [1.0, 2.0, 3.0], (2, 3))
        want = np.array([[0, 1, 0], [2, 0, 3]], np.float32)
        np.testing.assert_allclose(np.asarray(csr.to_dense()._data), want)

    def test_hybrid_dense_dim(self):
        dense = np.zeros((3, 4, 2), np.float32)
        dense[0, 1] = [1.0, 2.0]
        dense[2, 3] = [3.0, 4.0]
        sp = paddle.to_tensor(dense).to_sparse_coo(sparse_dim=2)
        assert sp.sparse_dim() == 2 and sp.dense_dim() == 1
        np.testing.assert_allclose(np.asarray(sp.to_dense()._data), dense)


class TestUnary:
    @pytest.mark.parametrize("name", ["sin", "tanh", "square", "abs",
                                      "expm1", "neg"])
    def test_values_ops_match_dense(self, name):
        sp, dense = _rand_coo(seed=2)
        out = getattr(sparse, name)(sp)
        ref = getattr(np, {"neg": "negative"}.get(name, name))(dense)
        # implicit zeros stay zero for these (f(0)=0 ops)
        np.testing.assert_allclose(np.asarray(out.to_dense()._data), ref,
                                   rtol=1e-6, atol=1e-6)

    def test_cast_and_pow(self):
        sp, dense = _rand_coo(seed=3)
        out = sparse.cast(sp, value_dtype="float64")
        assert "float64" in str(out.values().dtype)
        out2 = sparse.pow(sp, 2.0)
        np.testing.assert_allclose(np.asarray(out2.to_dense()._data),
                                   dense ** 2, rtol=1e-5, atol=1e-6)

    def test_transpose_reshape_sum(self):
        sp, dense = _rand_coo((3, 4), 5, seed=4)
        np.testing.assert_allclose(
            np.asarray(sparse.transpose(sp, [1, 0]).to_dense()._data),
            dense.T)
        np.testing.assert_allclose(
            np.asarray(sparse.reshape(sp, [4, 3]).to_dense()._data),
            dense.reshape(4, 3))
        np.testing.assert_allclose(np.asarray(sparse.sum(sp)._data),
                                   dense.sum(), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(sparse.sum(sp, axis=1)._data), dense.sum(1),
            rtol=1e-6)


class TestBinary:
    def test_add_subtract_different_patterns(self):
        a, da = _rand_coo(seed=5)
        b, db = _rand_coo(seed=6)
        np.testing.assert_allclose(
            np.asarray(sparse.add(a, b).to_dense()._data), da + db,
            rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(sparse.subtract(a, b).to_dense()._data), da - db,
            rtol=1e-6, atol=1e-6)

    def test_multiply_intersection(self):
        a, da = _rand_coo(seed=7)
        b, db = _rand_coo(seed=8)
        np.testing.assert_allclose(
            np.asarray(sparse.multiply(a, b).to_dense()._data), da * db,
            rtol=1e-6, atol=1e-6)

    def test_scalar_and_dense_operands(self):
        a, da = _rand_coo(seed=9)
        np.testing.assert_allclose(
            np.asarray(sparse.multiply(a, 2.5).to_dense()._data), da * 2.5,
            rtol=1e-6)
        dense_y = paddle.to_tensor(
            np.random.default_rng(10).standard_normal((4, 5)).astype(np.float32))
        got = sparse.add(a, dense_y)
        np.testing.assert_allclose(np.asarray(got._data),
                                   da + np.asarray(dense_y._data),
                                   rtol=1e-6)

    def test_mask_as_and_is_same_shape(self):
        a, da = _rand_coo(seed=11)
        x = np.random.default_rng(12).standard_normal((4, 5)).astype(np.float32)
        got = sparse.mask_as(paddle.to_tensor(x), a)
        mask = (da != 0).astype(np.float32)
        np.testing.assert_allclose(np.asarray(got.to_dense()._data),
                                   x * mask, rtol=1e-6)
        assert sparse.is_same_shape(a, a)


class TestMatmul:
    def test_spmm_matches_dense(self):
        sp, dense = _rand_coo((4, 5), 7, seed=13)
        y = np.random.default_rng(14).standard_normal((5, 3)).astype(np.float32)
        got = sparse.matmul(sp, paddle.to_tensor(y))
        np.testing.assert_allclose(np.asarray(got._data), dense @ y,
                                   rtol=1e-5, atol=1e-5)

    def test_mv(self):
        sp, dense = _rand_coo((4, 5), 6, seed=15)
        v = np.random.default_rng(16).standard_normal((5,)).astype(np.float32)
        got = sparse.mv(sp, paddle.to_tensor(v))
        np.testing.assert_allclose(np.asarray(got._data), dense @ v,
                                   rtol=1e-5, atol=1e-5)

    def test_masked_matmul_sddmm(self):
        mask, dmask = _rand_coo((4, 4), 5, seed=17)
        rng = np.random.default_rng(18)
        x = rng.standard_normal((4, 6)).astype(np.float32)
        y = rng.standard_normal((6, 4)).astype(np.float32)
        got = sparse.masked_matmul(paddle.to_tensor(x), paddle.to_tensor(y),
                                   mask)
        want = (x @ y) * (dmask != 0)
        np.testing.assert_allclose(np.asarray(got.to_dense()._data), want,
                                   rtol=1e-5, atol=1e-5)

    def test_addmm(self):
        sp, dense = _rand_coo((3, 4), 5, seed=19)
        rng = np.random.default_rng(20)
        y = rng.standard_normal((4, 2)).astype(np.float32)
        inp = rng.standard_normal((3, 2)).astype(np.float32)
        got = sparse.addmm(paddle.to_tensor(inp), sp, paddle.to_tensor(y),
                           beta=0.5, alpha=2.0)
        np.testing.assert_allclose(np.asarray(got._data),
                                   0.5 * inp + 2.0 * (dense @ y),
                                   rtol=1e-5, atol=1e-5)


class TestSparseNN:
    def test_relu_layer(self):
        sp, dense = _rand_coo(seed=21)
        out = sparse.nn.ReLU()(sp)
        np.testing.assert_allclose(np.asarray(out.to_dense()._data),
                                   np.maximum(dense, 0), rtol=1e-6)

    def test_softmax_over_stored_nonzeros(self):
        sp, dense = _rand_coo((3, 6), 8, seed=22)
        out = sparse.nn.functional.softmax(sp)
        got = np.asarray(out.to_dense()._data)
        for r in range(3):
            nz = dense[r] != 0
            if nz.sum() == 0:
                continue
            e = np.exp(dense[r][nz] - dense[r][nz].max())
            np.testing.assert_allclose(got[r][nz], e / e.sum(), rtol=1e-5)
            assert np.all(got[r][~nz] == 0)

    def test_conv3d_raises(self):
        with pytest.raises(NotImplementedError):
            sparse.nn.Conv3D(3, 3, 3)


class TestEdgeCases:
    """Regressions: empty operands, unsorted CSR cols, duplicate-index
    inputs through value-transforming ops."""

    def test_empty_operand_binary(self):
        a, da = _rand_coo(seed=30)
        empty = sparse.sparse_coo_tensor(np.zeros((2, 0), np.int64),
                                         np.zeros((0,), np.float32), (4, 5))
        np.testing.assert_allclose(
            np.asarray(sparse.add(a, empty).to_dense()._data), da,
            rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(sparse.add(empty, a).to_dense()._data), da,
            rtol=1e-6)
        assert float(np.asarray(sparse.sum(empty)._data)) == 0.0

    def test_unsorted_csr_cols_binary(self):
        # dense [[2, 0, 1]] with cols stored out of order within the row
        csr = sparse.sparse_csr_tensor([0, 2], [2, 0], [1.0, 2.0], (1, 3))
        other = sparse.sparse_csr_tensor([0, 1], [1], [10.0], (1, 3))
        got = np.asarray(sparse.add(csr, other).to_dense()._data)
        np.testing.assert_allclose(got, [[2.0, 10.0, 1.0]])

    def test_duplicate_indices_nonlinear_unary(self):
        sp = sparse.sparse_coo_tensor([[0, 0], [1, 1]], [1.0, 2.0], (2, 3))
        got = np.asarray(sparse.tanh(sp).to_dense()._data)
        assert abs(got[0, 1] - np.tanh(3.0)) < 1e-6

    def test_duplicate_indices_mask_as(self):
        sp = sparse.sparse_coo_tensor([[0, 0], [1, 1]], [1.0, 2.0], (2, 3))
        x = paddle.to_tensor(np.full((2, 3), 5.0, np.float32))
        got = np.asarray(sparse.mask_as(x, sp).to_dense()._data)
        assert got[0, 1] == 5.0

    def test_sum_axis_no_densify(self):
        sp, dense = _rand_coo((4, 5), 6, seed=31)
        np.testing.assert_allclose(np.asarray(sparse.sum(sp, axis=0)._data),
                                   dense.sum(0), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(sparse.sum(sp, axis=1, keepdim=True)._data),
            dense.sum(1, keepdims=True), rtol=1e-6)


def test_float64_initializer_precision():
    """Host-RNG init fast path must not round float64 draws through fp32."""
    import paddle_tpu.nn.initializer as init

    arr = np.asarray(init.Normal()((64,), dtype="float64"))
    assert arr.dtype == np.float64
    # float64 draws are float32-representable only with prob ~0
    assert np.any(arr != arr.astype(np.float32).astype(np.float64))


class TestSparseSliceAndPCA:
    """r5: the last two reference sparse.__all__ entries — slice and
    pca_lowrank."""

    def test_slice_coo_matches_dense(self):
        import paddle_tpu.sparse as sp

        d = np.zeros((4, 6), np.float32)
        d[0, 1] = 1.0
        d[2, 3] = 2.0
        d[3, 5] = 3.0
        t = paddle.to_tensor(d)
        coo = t.to_sparse_coo(2)
        out = sp.slice(coo, axes=[0, 1], starts=[1, 2], ends=[4, 6])
        np.testing.assert_allclose(np.asarray(out.to_dense()._data),
                                   d[1:4, 2:6])

    def test_slice_csr_and_negative_bounds(self):
        import paddle_tpu.sparse as sp

        d = np.arange(12, dtype=np.float32).reshape(3, 4)
        d[d % 3 != 0] = 0.0
        csr = paddle.to_tensor(d).to_sparse_csr()
        out = sp.slice(csr, axes=[1], starts=[-3], ends=[4])
        assert out.is_sparse_csr()
        np.testing.assert_allclose(np.asarray(out.to_dense()._data),
                                   d[:, -3:])

    def test_pca_lowrank_reconstructs(self):
        import paddle_tpu.sparse as sp

        rng = np.random.default_rng(0)
        base = rng.standard_normal((8, 2)) @ rng.standard_normal((2, 5))
        d = base.astype(np.float32)
        d[:, [1, 3]] = 0.0      # sparse-ish but still rank <= 2
        coo = paddle.to_tensor(d).to_sparse_coo(2)
        u, s, v = sp.pca_lowrank(coo, q=4, center=False)
        rec = (np.asarray(u._data) * np.asarray(s._data)) \
            @ np.asarray(v._data).T
        np.testing.assert_allclose(rec, d, atol=1e-4)
