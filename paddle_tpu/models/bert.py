"""BERT model family — the BASELINE config-3 target
(BERT-base fine-tune, dygraph AMP O2 + sharding stage 1).

Reference parity: the reference fine-tunes BERT through its dygraph AMP
path (GradScaler, amp/grad_scaler.py:645) + DygraphShardingOptimizer.
TPU-first: a plain pre-softmax-masked encoder in jnp; AMP O2 is the
bf16-param + fp32-master layout the optimizer already implements.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..ops import creation as C

__all__ = [
    "BertConfig", "BertModel", "BertForSequenceClassification",
    "BertForPretraining", "bert_config",
]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 0          # 0 -> 4*hidden
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02

    def __post_init__(self):
        if not self.intermediate_size:
            self.intermediate_size = 4 * self.hidden_size


BERT_CONFIGS = {
    "bert-base": dict(hidden_size=768, num_layers=12,
                      num_attention_heads=12),
    "bert-large": dict(hidden_size=1024, num_layers=24,
                       num_attention_heads=16),
}


def bert_config(name: str, **overrides) -> BertConfig:
    kw = dict(BERT_CONFIGS[name])
    kw.update(overrides)
    return BertConfig(**kw)


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(config.vocab_size,
                                            config.hidden_size)
        self.position_embeddings = nn.Embedding(
            config.max_position_embeddings, config.hidden_size)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size,
                                                  config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = C.arange(0, s, dtype="int64").unsqueeze(0)
        if token_type_ids is None:
            token_type_ids = C.zeros([b, s], dtype="int64")
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class BertSelfAttention(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = h // self.num_heads
        self.qkv = nn.Linear(h, 3 * h)
        self.out = nn.Linear(h, h)
        self.dropout_p = config.attention_dropout_prob

    def forward(self, x, attention_mask=None):
        b, s, h = x.shape
        qkv = self.qkv(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attention_mask, is_causal=False,
            dropout_p=self.dropout_p, training=self.training)
        return self.out(out.reshape([b, s, h]))


class BertLayer(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.attention = BertSelfAttention(config)
        self.attn_norm = nn.LayerNorm(config.hidden_size,
                                      epsilon=config.layer_norm_eps)
        self.fc1 = nn.Linear(config.hidden_size, config.intermediate_size)
        self.fc2 = nn.Linear(config.intermediate_size, config.hidden_size)
        self.out_norm = nn.LayerNorm(config.hidden_size,
                                     epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, x, attention_mask=None):
        # post-LN (original BERT)
        x = self.attn_norm(x + self.dropout(
            self.attention(x, attention_mask)))
        x = self.out_norm(x + self.dropout(
            self.fc2(F.gelu(self.fc1(x)))))
        return x


class BertPooler(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.dense = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, hidden):
        from .. import ops

        return ops.tanh(self.dense(hidden[:, 0]))


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = nn.LayerList([BertLayer(config)
                                     for _ in range(config.num_layers)])
        self.pooler = BertPooler(config)
        self._init_weights(config)

    def _init_weights(self, config):
        from ..framework.random import host_normal

        std = config.initializer_range
        for _, p in self.named_parameters():
            if p.ndim >= 2:
                p._data = host_normal(p._data.shape, std)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        if attention_mask is not None and attention_mask.ndim == 2:
            # [b, s] 1/0 padding mask -> additive [b, 1, 1, s]
            from ..ops._dispatch import unary

            attention_mask = unary(
                lambda m: (1.0 - m.astype(jnp.float32))[:, None, None, :]
                * jnp.float32(-1e9), attention_mask, "bert_mask")
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        for layer in self.encoder:
            x = layer(x, attention_mask)
        return x, self.pooler(x)


class BertForSequenceClassification(nn.Layer):
    """config-3 fine-tune head."""

    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids,
                              attention_mask=attention_mask)
        return self.classifier(self.dropout(pooled))


class BertForPretraining(nn.Layer):
    """MLM + NSP heads (reference BertForPretraining)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.mlm_transform = nn.Linear(config.hidden_size,
                                       config.hidden_size)
        self.mlm_norm = nn.LayerNorm(config.hidden_size,
                                     epsilon=config.layer_norm_eps)
        self.nsp = nn.Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        hidden, pooled = self.bert(input_ids, token_type_ids,
                                   attention_mask=attention_mask)
        h = self.mlm_norm(F.gelu(self.mlm_transform(hidden)))
        from .. import ops

        mlm_logits = ops.matmul(
            h, self.bert.embeddings.word_embeddings.weight,
            transpose_y=True)
        return mlm_logits, self.nsp(pooled)
