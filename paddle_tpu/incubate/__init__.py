"""paddle.incubate parity — experimental/advanced features."""
from . import distributed  # noqa: F401
