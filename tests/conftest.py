"""Test config: force an 8-device virtual CPU mesh (SURVEY.md environment
notes) so distributed tests run without TPU hardware, mirroring the
reference's multi-process-on-one-node test strategy (SURVEY.md §4).

NOTE: under the axon TPU tunnel, JAX_PLATFORMS=cpu does NOT stop jax from
registering the remote TPU as the default device — round 1's suite silently
ran every eager op over the tunnel (per-op remote dispatch ≈ 20× slower).
Pinning jax_default_device to cpu:0 keeps tests hermetic and fast; tests
that want the real chip opt in explicitly.
"""
import os
import sys

# Strip the axon plugin ENTIRELY (the dryrun's hermetic recipe,
# __graft_entry__.py): the suite never needs the remote chip, and a
# wedged tunnel otherwise HANGS jax backend init — observed r5 when a
# process was killed during the claim leg; every later jax.devices()
# call in every process blocked indefinitely, taking pytest down with
# it via this file.
for _k in list(os.environ):
    if _k.upper().startswith(("AXON_", "PALLAS_AXON", "TPU_", "LIBTPU")):
        os.environ.pop(_k)
os.environ["PYTHONPATH"] = os.pathsep.join(
    p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
    if p and ".axon_site" not in p.lower())
sys.path[:] = [p for p in sys.path if ".axon_site" not in p.lower()]
sys.modules.pop("axon", None)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# the axon plugin registers at INTERPRETER start (sitecustomize on
# PYTHONPATH), before this file can strip the env — deregister its
# factory so backend init can neither hang on a wedged tunnel nor
# raise for missing config (r5)
from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
# sitecustomize's register() can pin jax_platforms='axon' at the CONFIG
# level (overriding the env var) — force cpu after deregistration
jax.config.update("jax_platforms", "cpu")

jax.config.update("jax_default_device", jax.devices("cpu")[0])

# Persistent XLA compile cache: the suite's cost is dominated by eager
# per-op SPMD compiles (tiny models, hundreds of distinct ops); caching
# them across runs/processes cuts repeat wall-time several-fold
# (VERDICT r2 weak #2 — suite time budget). Keyed on HLO, so stale
# entries are impossible; the dir is gitignored.
_cache_dir = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_cache_dir))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
