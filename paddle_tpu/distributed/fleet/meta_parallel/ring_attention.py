"""Ring attention — exact long-context attention over a sequence-parallel
mesh axis.

Beyond-reference (SURVEY.md §5.7): the reference snapshot has only SEP
data-style sequence sharding (segment_parallel.py:26) and Megatron-SP; it
has NO ring/blockwise context parallelism. Here each device holds one
sequence block of q/k/v; k/v blocks rotate around the ring via
`ppermute` while an online-softmax accumulator (flash-attention math)
folds in one block per tick — memory O(seq/n) per device, comms riding
the ICI ring, and compute/transfer overlapped by XLA. The backward is the
reverse ring, derived by jax AD through the scan + ppermute (no
hand-written p2p bookkeeping).

Layout contract: q/k/v are [batch, seq, heads, head_dim] global arrays
sharded P(None, axis) on the sequence dim (SegmentParallel's layout).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

_NEG_INF = -1e30  # finite mask value: keeps exp/where AD clean vs real -inf


import functools


@functools.partial(jax.checkpoint, static_argnums=(5, 6))
def _block_attend(q, k, v, row0, col0, scale, causal):
    """One q-block × kv-block flash step.

    q: [b, sq, h, d], k/v: [b, sk, h, d]; row0/col0: global offsets of the
    blocks on the sequence axis. Returns (scores_max m [b,h,sq], partial
    numerator acc [b,sq,h,d], partial denominator l [b,h,sq]).

    Rematerialized: without the checkpoint, AD through the ring scan saves
    every tick's [b,h,blk,blk] score/prob residuals — O(seq^2/n) per
    device, the exact blow-up ring attention exists to avoid. Remat keeps
    backward memory at one block and recomputes scores in the reverse
    ring (flash-attention-style compute/memory trade).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        rows = row0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        cols = col0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    m = jnp.max(s, axis=-1)                                   # [b,h,q]
    p = jnp.exp(s - m[..., None])
    # fully-masked rows: m == NEG_INF -> p would be exp(0)=1; zero them
    alive = (m > _NEG_INF / 2)[..., None]
    p = jnp.where(alive, p, 0.0)
    # score/prob HBM residency in the input precision (the r2 bf16-score
    # lever, FLAGS_attention_fp32_scores restores fp32) — accumulation
    # and softmax stats stay fp32
    from ....utils import flags as _flags

    if (q.dtype in (jnp.bfloat16, jnp.float16)
            and not _flags.get_flag("FLAGS_attention_fp32_scores")):
        p = p.astype(q.dtype)
    l = jnp.sum(p.astype(jnp.float32), axis=-1)               # [b,h,q]
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(p.dtype),
                     preferred_element_type=jnp.float32)
    return m, acc, l


def ring_attention(q, k, v, *, mesh, axis="sep", causal=True, scale=None):
    """Exact attention with q/k/v sequence-sharded over `axis`.

    Returns [batch, seq, heads, head_dim] with the same sharding as q.
    Differentiable (AD reverses the ring). Requires seq % mesh.shape[axis]
    == 0.
    """
    b, s, h, d = q.shape
    n = int(mesh.shape[axis])
    if s % n:
        raise ValueError(f"ring size {n} must divide seq {s}")
    blk = s // n
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def local(qb, kb, vb):
        # local blocks [b, blk, h, d]; manual over `axis` only
        idx = jax.lax.axis_index(axis)
        row0 = idx * blk

        m0 = jnp.full((b, h, blk), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, blk), jnp.float32)
        a0 = jnp.zeros((b, blk, h, d), jnp.float32)

        def tick(carry, t):
            m_run, l_run, acc_run, kv = carry
            kt, vt = kv
            src = (idx - t) % n             # whose block we hold this tick
            m_b, acc_b, l_b = _block_attend(qb, kt, vt, row0, src * blk,
                                            scale, causal)
            m_new = jnp.maximum(m_run, m_b)
            c_run = jnp.exp(m_run - m_new)      # [b,h,q]
            c_b = jnp.exp(m_b - m_new)
            l_new = l_run * c_run + l_b * c_b
            acc_new = (acc_run * jnp.transpose(c_run, (0, 2, 1))[..., None]
                       + acc_b * jnp.transpose(c_b, (0, 2, 1))[..., None])
            kv = jax.lax.ppermute((kt, vt), axis, perm)
            return (m_new, l_new, acc_new, kv), None

        (m_f, l_f, acc_f, _), _ = jax.lax.scan(
            tick, (m0, l0, a0, (kb, vb)), jnp.arange(n))
        l_safe = jnp.maximum(l_f, 1e-30)
        out = acc_f / jnp.transpose(l_safe, (0, 2, 1))[..., None]
        return out.astype(qb.dtype)

    spec = P(None, axis, None, None)
    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names=frozenset({axis}),
        check_vma=False,
    )(q, k, v)


def sep_sharding(mesh, axis="sep"):
    """The NamedSharding ring_attention expects on q/k/v."""
    return NamedSharding(mesh, P(None, axis, None, None))


# ---------------------------------------------------------------------------
# flash-ring attention: the pallas flash kernels INSIDE the ring
#
# The plain ring above computes each tick's block attention as an XLA
# einsum — an O(blk^2) score tile in HBM per tick. Here each tick runs
# the pallas tiled flash kernel (ops/pallas/flash_attention._fwd), so
# per-device memory is O(blk*d) at every point, and the backward is a
# HAND-WRITTEN reverse ring (custom_vjp): dk/dv accumulators rotate with
# their kv blocks (n ticks = back home) and each tick runs the fused
# single-pass pallas backward with the GLOBAL lse/delta — the ring
# generalization of flash-attention-2, with jax AD nowhere on the
# O(seq^2) path.
# ---------------------------------------------------------------------------


def _flash_ring_local(axis, n, blk, scale, causal, interpret):
    """Build the per-shard (q,k,v)->out function with a custom ring VJP.
    Layout inside: kernel-native [b*h, blk, d]."""
    from ....ops.pallas import flash_attention as fa

    perm = [(i, (i + 1) % n) for i in range(n)]
    bq = fa._pick_block(blk)

    def fwd_pass(qb, kb, vb):
        idx = jax.lax.axis_index(axis)
        bh, _, d = qb.shape
        neg = jnp.float32(_NEG_INF)

        def attend(mode, kt, vt):
            # mode 0: diagonal (causal within block), 1: full, 2: skip
            def diag(_):
                return fa._fwd(qb, kt, vt, scale, True, bq, bq, interpret)

            def full(_):
                return fa._fwd(qb, kt, vt, scale, False, bq, bq, interpret)

            def skip(_):
                return (jnp.zeros((bh, blk, d), qb.dtype),
                        jnp.full((bh, blk, fa._LANES), neg, jnp.float32))

            return jax.lax.switch(mode, [diag, full, skip], None)

        def tick(carry, t):
            out_run, lse_run, kv = carry
            kt, vt = kv
            src = (idx - t) % n
            if causal:
                mode = jnp.where(src == idx, 0,
                                 jnp.where(src < idx, 1, 2))
            else:
                mode = jnp.ones((), jnp.int32)
            out_b, lse_b = attend(mode, kt, vt)
            l1 = lse_run[:, :, :1]
            l2 = lse_b[:, :, :1]
            lse_new = jnp.logaddexp(l1, l2)
            w1 = jnp.exp(l1 - lse_new)
            w2 = jnp.exp(l2 - lse_new)
            # out_run stays fp32 across the whole scan: casting back to the
            # input dtype every tick would accumulate O(n) rounding error
            # in the rescale-and-add merge instead of rounding once at end
            out_new = out_run * w1 + out_b.astype(jnp.float32) * w2
            kv = jax.lax.ppermute((kt, vt), axis, perm)
            lse_full = jnp.broadcast_to(lse_new, lse_run.shape)
            return (out_new, lse_full, kv), None

        out0 = jnp.zeros(qb.shape, jnp.float32)
        lse0 = jnp.full((bh, blk, fa._LANES), neg, jnp.float32)
        (out, lse, _), _ = jax.lax.scan(
            tick, (out0, lse0, (kb, vb)), jnp.arange(n))
        return out.astype(qb.dtype), lse

    @jax.custom_vjp
    def ring(qb, kb, vb):
        out, _ = fwd_pass(qb, kb, vb)
        return out

    def ring_fwd(qb, kb, vb):
        out, lse = fwd_pass(qb, kb, vb)
        return out, (qb, kb, vb, out, lse)

    def ring_bwd(res, do):
        qb, kb, vb, out, lse = res
        idx = jax.lax.axis_index(axis)
        bh, _, d = qb.shape

        def grads(mode, kt, vt):
            def diag(_):
                return fa._bwd(qb, kt, vt, out, lse, do, scale, True,
                               bq, bq, interpret)

            def full(_):
                return fa._bwd(qb, kt, vt, out, lse, do, scale, False,
                               bq, bq, interpret)

            def skip(_):
                return (jnp.zeros((bh, blk, d), qb.dtype),
                        jnp.zeros((bh, blk, d), kb.dtype),
                        jnp.zeros((bh, blk, d), vb.dtype))

            return jax.lax.switch(mode, [diag, full, skip], None)

        def tick(carry, t):
            dq_run, ring_state = carry
            kt, vt, dk_run, dv_run = ring_state
            src = (idx - t) % n
            if causal:
                mode = jnp.where(src == idx, 0,
                                 jnp.where(src < idx, 1, 2))
            else:
                mode = jnp.ones((), jnp.int32)
            dq_b, dk_b, dv_b = grads(mode, kt, vt)
            dq_run = dq_run + dq_b.astype(jnp.float32)
            dk_run = dk_run + dk_b.astype(jnp.float32)
            dv_run = dv_run + dv_b.astype(jnp.float32)
            ring_state = jax.lax.ppermute(
                (kt, vt, dk_run, dv_run), axis, perm)
            return (dq_run, ring_state), None

        dq0 = jnp.zeros((bh, blk, d), jnp.float32)
        dkv0 = (kb, vb, jnp.zeros((bh, blk, d), jnp.float32),
                jnp.zeros((bh, blk, d), jnp.float32))
        (dq, (_, _, dk, dv)), _ = jax.lax.scan(
            tick, (dq0, dkv0), jnp.arange(n))
        return (dq.astype(qb.dtype), dk.astype(kb.dtype),
                dv.astype(vb.dtype))

    ring.defvjp(ring_fwd, ring_bwd)
    return ring


def ring_flash_attention(q, k, v, *, mesh, axis="sep", causal=True,
                         scale=None, interpret=None):
    """Ring attention with the pallas flash kernels per tick (forward AND
    the reverse-ring backward). Same contract as `ring_attention`;
    requires the per-device block to be a multiple of 128 (kernel tiles)
    and q/k/v the same shape."""
    from ....ops.pallas import flash_attention as fa

    b, s, h, d = q.shape
    n = int(mesh.shape[axis])
    if s % n:
        raise ValueError(f"ring size {n} must divide seq {s}")
    blk = s // n
    if fa._pick_block(blk) is None:
        raise ValueError(f"flash ring needs block {blk} % 128 == 0")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        # the kernels run on the mesh's devices, which may differ from the
        # process default (axon tunnel keeps default backend 'tpu' even
        # when the mesh is built from cpu devices)
        interpret = mesh.devices.flat[0].platform != "tpu"
    local_ring = _flash_ring_local(axis, n, blk, float(scale),
                                   bool(causal), bool(interpret))

    def local(qb, kb, vb):
        # [b, blk, h, d] -> kernel layout [b*h, blk, d]
        def to_bh(x):
            return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, blk, d)

        ob = local_ring(to_bh(qb), to_bh(kb), to_bh(vb))
        return jnp.transpose(ob.reshape(b, h, blk, d), (0, 2, 1, 3))

    spec = P(None, axis, None, None)
    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names=frozenset({axis}),
        check_vma=False,
    )(q, k, v)
