"""Hermetic selftest for SHARDED PARAMETER STORAGE (ISSUE 11).

Run under a cpu-forced env (bench.py's stripped subprocess /
tools/cpu_env.sh) with an 8-virtual-device host platform:

    python -m paddle_tpu.jit.sharded_storage_selftest

One process, one JSON line. Asserts the ISSUE 11 acceptance triangle:

* **bit-parity**: the sharded-storage step's loss trajectory AND final
  params match the replicated-storage step on dp8, dp4×mp2 and dp2×pp2
  host meshes (measured 0.0 — the shards hold exactly the bytes the
  replicated stacks would; gate 1e-6);
* **live 1/N shards**: the param flat buckets live as N addressable
  shards of 1/N each, and the compiled-HLO probe certifies no
  full-parameter-set (or even single-stacked-leaf-sized) buffer exists
  in the sharded program while its peak buffer is strictly below the
  replicated program's;
* **checkpoint resharding**: a dp8-saved checkpoint restores onto a
  dp4 step (different mesh shape, different flat pad length) and the
  resumed trajectory matches an uninterrupted run;
* **quantized multi-axis legs**: the int8 scatter AND gather wire
  formats over a flattened (dp, mp) axis tuple hold the comm_quant
  rel-err bound;
* **dropout under pp**: the per-(micro, stage) PRNG offset scheme is
  deterministic, finite, and actually applies masks;
* **compile counts**: 1 executable per step signature;
* a host-mesh tok/s A/B (informational on CPU — the structural point
  is that the sharded program stays within a few percent; chip numbers
  land via bench --multichip).
"""
from __future__ import annotations

import json
import sys
import tempfile
import time

import numpy as np

TOL = {
    "loss_parity": 1e-6,     # sharded vs replicated, same mesh
    "resume": 5e-4,          # across a dp8 -> dp4 mesh change
    "quant_rel": 1e-2,
}

TINY = dict(vocab_size=92, hidden_size=36, num_layers=4,
            num_attention_heads=2, max_position_embeddings=16,
            hidden_dropout_prob=0.0, attention_dropout_prob=0.0)


def _batch(bs, seq=12, vocab=92, seed=0):
    import paddle_tpu as paddle

    rng = np.random.default_rng(seed)
    return (paddle.to_tensor(rng.integers(0, vocab, (bs, seq)),
                             dtype="int64"),
            paddle.to_tensor(rng.integers(0, vocab, (bs, seq)),
                             dtype="int64"))


def storage_probe(n_devices=8, steps=4, lr=1e-2, clip_norm=0.05,
                  seed=0):
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as popt
    from jax.sharding import Mesh
    from paddle_tpu.distributed import env as denv
    from paddle_tpu.jit import ShardedFusedScanTrainStep
    from paddle_tpu.jit.pipeline_step import PipelineScanTrainStep
    from paddle_tpu.models import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    devs = jax.devices("cpu")[:n_devices]
    if len(devs) < n_devices:
        return {"check": f"FAIL: {len(devs)} cpu devices < {n_devices}"}
    ids, labels = _batch(bs=n_devices, vocab=TINY["vocab_size"],
                         seed=seed)

    def build(kind, storage, nd=n_devices, seed_=seed, cfg_over=None):
        cfg = GPTConfig(**{**TINY, **(cfg_over or {})},
                        scan_layers=True)
        paddle.seed(seed_)
        model = GPTForCausalLM(cfg)
        opt = popt.AdamW(learning_rate=lr,
                         parameters=model.parameters(),
                         grad_clip=nn.ClipGradByGlobalNorm(clip_norm))
        crit = GPTPretrainingCriterion()
        if kind == "dp":
            mesh = Mesh(np.asarray(devs[:nd]), ("sharding",))
            denv.set_mesh(mesh)
            step = ShardedFusedScanTrainStep(
                model, opt, criterion=crit, mesh=mesh, axis="sharding",
                param_storage=storage)
        elif kind == "dpmp":
            mesh = Mesh(np.asarray(devs).reshape(4, 2), ("dp", "mp"))
            denv.set_mesh(mesh)
            step = ShardedFusedScanTrainStep(
                model, opt, criterion=crit, mesh=mesh, axis="dp",
                mp_axis="mp", param_storage=storage)
        else:  # dppp
            mesh = denv.build_mesh({"dp": 2, "pp": 2},
                                   devices=devs[:4])
            denv.set_mesh(mesh)
            step = PipelineScanTrainStep(
                model, opt, criterion=crit, mesh=mesh, axis="dp",
                pp_axis="pp", num_micro=2, param_storage=storage)
        return model, opt, step

    def run(kind, storage, nsteps=steps, cfg_over=None, seed_=seed):
        model, opt, step = build(kind, storage, cfg_over=cfg_over,
                                 seed_=seed_)
        t0 = time.perf_counter()
        losses = [float(step(ids, labels)) for _ in range(nsteps)]
        wall = time.perf_counter() - t0
        return losses, model, step, wall

    out = {"n_devices": n_devices, "steps": steps,
           "tolerances": TOL}

    # ---- 1. bit-parity sharded vs replicated per mesh family
    parity_ok = True
    for kind in ("dp", "dpmp", "dppp"):
        rep, m_rep, _, _ = run(kind, "replicated")
        sh, m_sh, st, _ = run(kind, "sharded")
        ldiff = max(abs(a - b) for a, b in zip(rep, sh))
        pdiff = max(
            float(np.max(np.abs(
                np.asarray(p1._data, np.float32)
                - np.asarray(p2._data, np.float32))))
            for (_, p1), (_, p2) in zip(m_rep.named_parameters(),
                                        m_sh.named_parameters()))
        compiles = (st._jitted._cache_size()
                    if hasattr(st._jitted, "_cache_size") else 1)
        out[f"parity_{kind}"] = {
            "max_abs_loss_diff": ldiff, "max_abs_param_diff": pdiff,
            "compile_count": compiles}
        parity_ok &= (ldiff <= TOL["loss_parity"]
                      and pdiff <= TOL["loss_parity"]
                      and compiles == 1)

    # ---- 2. live 1/N shard shapes + the compiled-HLO liveness receipt
    _, _, st, _ = run("dp", "sharded", nsteps=1)
    fp = st._param_shards["s"][0]
    shards_ok = (len(fp.addressable_shards) == n_devices
                 and fp.addressable_shards[0].data.shape[-1]
                 * n_devices == fp.shape[-1])
    out["param_shard_flat_shape"] = list(fp.shape)
    out["param_shard_local"] = list(
        fp.addressable_shards[0].data.shape)
    from .sharded_scan_selftest import param_storage_probe

    hlo_ok = True
    for cfg_name, kw in (("dp8", {}), ("dp4xmp2", {"mp": 2}),
                         ("dp4xpp2", {"pp": 2})):
        hlo = param_storage_probe(n_devices=n_devices, **kw)
        out[f"hlo_receipt_{cfg_name}"] = {
            **{k: hlo[k] for k in ("no_full_param_set",
                                   "no_stacked_param_buffer",
                                   "peak_reduced",
                                   "param_gather_all_gathers",
                                   "param_storage_ok")},
            "max_buffer_elems": {
                "sharded": hlo["sharded"]["max_buffer_elems"],
                "replicated": hlo["replicated"]["max_buffer_elems"]},
        }
        hlo_ok &= hlo["param_storage_ok"]

    # ---- 3. checkpoint round-trip onto a DIFFERENT mesh shape
    from paddle_tpu.distributed.checkpoint.manager import (
        CheckpointManager,
    )

    model, opt, step = build("dp", "sharded")
    straight = [float(step(ids, labels)) for _ in range(4)]
    model, opt, step = build("dp", "sharded")
    part1 = [float(step(ids, labels)) for _ in range(2)]
    tmp = tempfile.mkdtemp(prefix="sharded_storage_ck_")
    CheckpointManager(tmp, model=model, optimizer=opt).save(1)
    model2, opt2, step2 = build("dp", "sharded", nd=4, seed_=99)
    step2.ensure_built()
    restored = CheckpointManager(tmp, model=model2,
                                 optimizer=opt2).restore_or_init()
    part2 = [float(step2(ids, labels)) for _ in range(2)]
    resume_diff = max(abs(a - b)
                      for a, b in zip(straight, part1 + part2))
    out["reshard_restore"] = {
        "restored_step": restored, "from_devices": n_devices,
        "to_devices": 4, "max_abs_loss_diff": resume_diff}
    reshard_ok = restored == 1 and resume_diff <= TOL["resume"]

    # ---- 4. quantized multi-axis scatter + gather legs
    from jax.sharding import Mesh as _Mesh
    from paddle_tpu.distributed.collective import (
        comm_quant_multiaxis_selftest,
    )

    qmesh = _Mesh(np.asarray(devs).reshape(4, 2), ("dp", "mp"))
    denv.set_mesh(qmesh)
    quant = comm_quant_multiaxis_selftest(qformat="int8", mesh=qmesh,
                                          axes=("dp", "mp"))
    out["comm_quant_multiaxis"] = quant
    quant_ok = quant["pass"]

    # ---- 5. dropout under pp: deterministic, finite, masks applied
    d1, _, _, _ = run("dppp", "sharded",
                      cfg_over=dict(hidden_dropout_prob=0.1))
    d2, _, _, _ = run("dppp", "sharded",
                      cfg_over=dict(hidden_dropout_prob=0.1))
    base, _, _, _ = run("dppp", "sharded")
    drop_ok = (d1 == d2 and bool(np.isfinite(d1).all())
               and d1 != base)
    out["pp_dropout"] = {"deterministic": d1 == d2,
                         "distinct_from_p0": d1 != base}

    # ---- 6. host-mesh steady-state step-time A/B (informational on
    # CPU: the emulated mesh serializes the gathers a real chip's
    # latency-hiding scheduler overlaps — chip numbers land via bench
    # --multichip)
    # a config with a training-realistic compute/param-bytes ratio
    # (the TINY parity config is all gather, no compute — it would
    # measure pure collective overhead, which is exactly what real
    # chips hide); min-of-reps timing de-noises the throttled
    # container (the input_pipeline selftest's calibration pattern)
    ab_cfg = dict(TINY, hidden_size=64, num_layers=8,
                  max_position_embeddings=256)
    ab_ids, ab_labels = _batch(bs=2 * n_devices, seq=256,
                               vocab=TINY["vocab_size"], seed=seed)

    def steady(storage, reps=5):
        cfg = GPTConfig(**{**TINY, **ab_cfg}, scan_layers=True)
        paddle.seed(seed)
        model = GPTForCausalLM(cfg)
        opt = popt.AdamW(learning_rate=lr,
                         parameters=model.parameters())
        mesh = Mesh(np.asarray(devs), ("sharding",))
        denv.set_mesh(mesh)
        step = ShardedFusedScanTrainStep(
            model, opt, criterion=GPTPretrainingCriterion(),
            mesh=mesh, axis="sharding", param_storage=storage,
            scan_unroll=2)
        float(step(ab_ids, ab_labels))        # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            float(step(ab_ids, ab_labels))    # loss read = step sync
            best = min(best, time.perf_counter() - t0)
        return best

    w_rep = steady("replicated")
    w_sh = steady("sharded")
    tokens = int(np.prod(ab_ids.shape))
    out["host_step_ms"] = {"replicated": round(w_rep * 1e3, 2),
                           "sharded": round(w_sh * 1e3, 2),
                           "ratio": round(w_sh / max(w_rep, 1e-9), 3),
                           "tok_s_replicated": round(tokens / w_rep),
                           "tok_s_sharded": round(tokens / w_sh)}

    ok = (parity_ok and shards_ok and hlo_ok and reshard_ok
          and quant_ok and drop_ok)
    out["check"] = "pass" if ok else (
        f"FAIL: parity={parity_ok} shards={shards_ok} hlo={hlo_ok} "
        f"reshard={reshard_ok} quant={quant_ok} dropout={drop_ok}")
    return out


def _main():
    print(json.dumps({"sharded_storage": storage_probe()}))


if __name__ == "__main__":
    _main()
    sys.exit(0)
