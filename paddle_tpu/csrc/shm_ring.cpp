// SPSC shared-memory ring buffer — the DataLoader worker->parent tensor
// transport.
//
// Reference parity: the reference moves worker batches through its C++
// shared-memory path (paddle/fluid/imperative/data_loader.cc +
// python/paddle/io/dataloader/worker.py's _convert_to_tensor over shared
// memory). TPU build: one POSIX-shm ring per worker; the worker process is
// the single producer, the parent loader the single consumer, so a
// lock-free head/tail pair with acquire/release ordering suffices. Records
// are length-prefixed byte blobs (pickle-5 metadata + raw ndarray bytes).
//
// Build: g++ -O2 -shared -fPIC -o _shm_ring.so shm_ring.cpp -lrt
// Loaded via ctypes (paddle_tpu/io/shm_channel.py); a pure-Python fallback
// keeps the loader functional when the native lib is unavailable.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

struct RingHeader {
  uint64_t capacity;               // data bytes (power of two not required)
  std::atomic<uint64_t> head;      // next write offset (monotonic)
  std::atomic<uint64_t> tail;      // next read offset (monotonic)
  std::atomic<uint32_t> closed;    // producer hung up
};

struct Ring {
  RingHeader* hdr;
  uint8_t* data;
  uint64_t map_len;
  int owner;                       // created (vs attached): unlink on free
  char name[256];
};

inline uint64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000u + ts.tv_nsec / 1000000u;
}

// copy with wrap-around
void ring_write(Ring* r, uint64_t pos, const uint8_t* src, uint64_t n) {
  uint64_t cap = r->hdr->capacity;
  uint64_t off = pos % cap;
  uint64_t first = (n < cap - off) ? n : cap - off;
  memcpy(r->data + off, src, first);
  if (n > first) memcpy(r->data, src + first, n - first);
}

void ring_read(Ring* r, uint64_t pos, uint8_t* dst, uint64_t n) {
  uint64_t cap = r->hdr->capacity;
  uint64_t off = pos % cap;
  uint64_t first = (n < cap - off) ? n : cap - off;
  memcpy(dst, r->data + off, first);
  if (n > first) memcpy(dst + first, r->data, n - first);
}

Ring* map_ring(const char* name, int create, uint64_t capacity) {
  int flags = create ? (O_CREAT | O_EXCL | O_RDWR) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0) return nullptr;
  uint64_t map_len = sizeof(RingHeader) + capacity;
  if (create) {
    if (ftruncate(fd, (off_t)map_len) != 0) {
      close(fd);
      shm_unlink(name);
      return nullptr;
    }
  } else {
    struct stat st;
    if (fstat(fd, &st) != 0 || (uint64_t)st.st_size < sizeof(RingHeader)) {
      close(fd);
      return nullptr;
    }
    map_len = (uint64_t)st.st_size;
  }
  void* mem = mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Ring* r = new Ring();
  r->hdr = (RingHeader*)mem;
  r->data = (uint8_t*)mem + sizeof(RingHeader);
  r->map_len = map_len;
  r->owner = create;
  snprintf(r->name, sizeof(r->name), "%s", name);
  if (create) {
    r->hdr->capacity = capacity;
    r->hdr->head.store(0, std::memory_order_relaxed);
    r->hdr->tail.store(0, std::memory_order_relaxed);
    r->hdr->closed.store(0, std::memory_order_relaxed);
  }
  return r;
}

}  // namespace

extern "C" {

void* shm_ring_create(const char* name, uint64_t capacity) {
  return map_ring(name, 1, capacity);
}

void* shm_ring_attach(const char* name) {
  return map_ring(name, 0, 0);
}

// Push one length-prefixed record. Blocks (yielding) until space or
// timeout_ms elapses. Returns 0 ok, -1 timeout, -2 closed/invalid.
int shm_ring_push(void* ring, const uint8_t* buf, uint64_t n,
                  uint64_t timeout_ms) {
  Ring* r = (Ring*)ring;
  if (!r) return -2;
  uint64_t need = n + 8;
  uint64_t cap = r->hdr->capacity;
  if (need > cap) return -2;  // record larger than the whole ring
  uint64_t deadline = now_ms() + timeout_ms;
  for (;;) {
    uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
    uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
    if (cap - (head - tail) >= need) {
      uint64_t len_le = n;  // little-endian on all supported targets
      ring_write(r, head, (const uint8_t*)&len_le, 8);
      ring_write(r, head + 8, buf, n);
      r->hdr->head.store(head + need, std::memory_order_release);
      return 0;
    }
    if (r->hdr->closed.load(std::memory_order_relaxed)) return -2;
    if (now_ms() >= deadline) return -1;
    sched_yield();
  }
}

// Peek next record's size. Returns size, 0 if empty, -2 if closed+drained.
int64_t shm_ring_next_size(void* ring) {
  Ring* r = (Ring*)ring;
  if (!r) return -2;
  uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  uint64_t head = r->hdr->head.load(std::memory_order_acquire);
  if (head == tail) {
    return r->hdr->closed.load(std::memory_order_acquire) ? -2 : 0;
  }
  uint64_t n;
  ring_read(r, tail, (uint8_t*)&n, 8);
  return (int64_t)n;
}

// Pop one record into out (caller sized it via shm_ring_next_size).
// Returns 0 ok, -1 empty after timeout, -2 closed/invalid.
int shm_ring_pop(void* ring, uint8_t* out, uint64_t out_cap,
                 uint64_t timeout_ms) {
  Ring* r = (Ring*)ring;
  if (!r) return -2;
  uint64_t deadline = now_ms() + timeout_ms;
  for (;;) {
    uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
    uint64_t head = r->hdr->head.load(std::memory_order_acquire);
    if (head != tail) {
      uint64_t n;
      ring_read(r, tail, (uint8_t*)&n, 8);
      if (n > out_cap) return -2;
      ring_read(r, tail + 8, out, n);
      r->hdr->tail.store(tail + 8 + n, std::memory_order_release);
      return 0;
    }
    if (r->hdr->closed.load(std::memory_order_acquire)) return -2;
    if (now_ms() >= deadline) return -1;
    sched_yield();
  }
}

void shm_ring_close_producer(void* ring) {
  Ring* r = (Ring*)ring;
  if (r) r->hdr->closed.store(1, std::memory_order_release);
}

void shm_ring_free(void* ring) {
  Ring* r = (Ring*)ring;
  if (!r) return;
  int owner = r->owner;
  char name[256];
  snprintf(name, sizeof(name), "%s", r->name);
  munmap((void*)r->hdr, r->map_len);
  if (owner) shm_unlink(name);
  delete r;
}

}  // extern "C"
