"""Sharded checkpoint save.

Reference parity: python/paddle/distributed/checkpoint/save_state_dict.py:145
(save_state_dict) and its dedup of replicated shards (:107-144). TPU-first:
chunks come from ``jax.Array.addressable_shards`` — the global index of every
shard is known locally from the NamedSharding, so the metadata needs no
cross-rank gather of "local shapes"; dedup keys on ``replica_id == 0``
(exactly one device per distinct chunk writes it), which subsumes the
reference's rank-0-wins rule for replicated placements.

Layout on disk::

    path/
      0.metadata        # Metadata: tensor -> [chunks], chunk -> file
      {proc}_0.distcp   # pickle: {(tensor_key, global_offset): payload}
"""
from __future__ import annotations

import os
import pickle
from typing import Dict

import numpy as np

import jax

from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata
from .utils import (
    flatten_state_dict, fsync_dir, fsync_write_bytes, offsets_of,
    pack_numpy, to_jax_array,
)


def _dtype_name(arr) -> str:
    dt = arr.dtype
    return dt.name if hasattr(dt, "name") else str(dt)


def save_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0) -> None:
    """Save a (possibly nested) state_dict of sharded tensors.

    Every process writes the chunks it owns (``replica_id == 0`` shards of
    its addressable devices); the coordinator writes the global metadata.
    Single-process meshes (incl. virtual CPU meshes) save everything.
    """
    if not isinstance(state_dict, dict):
        raise TypeError("save_state_dict expects a dict")
    flat, mapping = flatten_state_dict(state_dict)

    os.makedirs(path, exist_ok=True)
    proc = jax.process_index()
    meta = Metadata(flat_mapping=mapping)
    file_name = f"{proc}_0.distcp"
    local_chunks = {}

    for key, value in flat.items():
        if isinstance(value, (int, float)):
            # scalars ride in the metadata file
            meta.state_dict_metadata[key] = value
            continue
        arr = to_jax_array(value)
        chunks = []
        seen_offsets = set()
        for shard in arr.addressable_shards:
            off = offsets_of(shard.index, arr.shape)
            if shard.replica_id != 0 or off in seen_offsets:
                continue
            seen_offsets.add(off)
            data = np.asarray(shard.data)
            chunks.append(LocalTensorMetadata(off, tuple(data.shape),
                                              _dtype_name(arr)))
            local_chunks[(key, off)] = pack_numpy(data)
            meta.storage_metadata[LocalTensorIndex(key, off)] = file_name
        meta.state_dict_metadata.setdefault(key, []).extend(chunks)

    # chunk file: durable atomic write, CRC32/size recorded in the
    # manifest — a crash mid-write leaves only a *.tmp.* file that no
    # reader opens, and a post-crash flipped byte is caught on read
    crc, size = fsync_write_bytes(os.path.join(path, file_name),
                                  pickle.dumps(local_chunks))
    meta.file_checksums[file_name] = (crc, size)

    if jax.process_count() > 1:
        # every process computed the same global chunk list for the
        # addressable part; merge via a metadata file per process and let
        # the coordinator fold them (control plane only, tiny).
        part = f"{proc}.metapart"
        fsync_write_bytes(os.path.join(path, part), pickle.dumps(meta))
        # rendezvous so the coordinator sees all parts
        from ..collective import barrier

        barrier()
        if proc == coordinator_rank:
            for p in range(jax.process_count()):
                part_path = os.path.join(path, f"{p}.metapart")
                with open(part_path, "rb") as f:
                    other = pickle.load(f)
                for k, v in other.state_dict_metadata.items():
                    if isinstance(v, list):
                        cur = meta.state_dict_metadata.setdefault(k, [])
                        for c in v:
                            if c not in cur:
                                cur.append(c)
                    else:
                        meta.state_dict_metadata[k] = v
                meta.storage_metadata.update(other.storage_metadata)
                meta.file_checksums.update(
                    getattr(other, "file_checksums", {}))
                os.remove(part_path)
            fsync_write_bytes(os.path.join(path, "0.metadata"),
                              pickle.dumps(meta))
            fsync_dir(path)
        # second barrier: no process returns before the manifest exists
        # (a non-coordinator may immediately load/validate the checkpoint)
        barrier()
        return

    # the manifest is written LAST: its presence is the commit marker a
    # validator/manager keys on — chunks without a manifest are garbage,
    # never a half-readable checkpoint
    fsync_write_bytes(os.path.join(path, "0.metadata"),
                      pickle.dumps(meta))
    fsync_dir(path)
