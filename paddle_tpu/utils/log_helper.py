"""Logging helper (reference python/paddle/base/log_helper.py).

One shared formatter/config path so framework modules log consistently and
user code can dial verbosity with ``GLOG_v``-style env control
(``PADDLE_TPU_LOG_LEVEL`` here, matching the reference's glog verbosity).
"""
from __future__ import annotations

import logging
import os

_DEFAULT_FMT = ("%(asctime)s - %(name)s - %(levelname)s: %(message)s")
_configured = {}


def get_logger(name: str, level=None, fmt: str = _DEFAULT_FMT
               ) -> logging.Logger:
    logger = logging.getLogger(name)
    if name in _configured:
        return logger
    if level is None:
        env = os.environ.get("PADDLE_TPU_LOG_LEVEL", "INFO").upper()
        level = getattr(logging, env, logging.INFO)
    logger.setLevel(level)
    handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter(fmt))
    logger.addHandler(handler)
    logger.propagate = False
    _configured[name] = True
    return logger
