"""paddle.nn.quant — TPU-native quantization.

Reference parity: python/paddle/nn/quant/ (quantized_linear.py
weight_quantize:56 / weight_dequantize:123 / weight_only_linear:183 /
llm_int8_linear:276 / apply_per_channel_scale:342, quant_layers.py
FakeQuantAbsMax:69 / FakeQuantMovingAverageAbsMax:172 /
FakeQuantChannelWiseAbsMax:310 / MovingAverageAbsMaxScale:424 /
QuantizedLinear:769, lsq.py FakeQuantWeightLSQPlus:245).

TPU-first: the reference dispatches to CUTLASS weight-only GEMMs gated
on SM arch; here int8 weights live half-width in HBM and XLA fuses the
dequant multiply into the matmul read (the memory-bound win), while
llm.int8 runs a REAL int8xint8->int32 MXU dot (lax.dot_general with
preferred_element_type=int32) with absmax dynamic activation scales and
fp16-outlier decomposition. Fake-quant training uses the straight-
through estimator expressed as ``x + stop_gradient(q - x)``, which jits
and differentiates with no custom VJP machinery.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ... import nn
from ...framework.tensor import Tensor
from ...ops._dispatch import unary, binary, nary, ensure_tensor

__all__ = [
    "weight_quantize", "weight_dequantize", "weight_only_linear",
    "llm_int8_linear", "apply_per_channel_scale",
    "FakeQuantAbsMax", "FakeQuantMovingAverageAbsMax",
    "FakeQuantChannelWiseAbsMax", "MovingAverageAbsMaxScale",
    "FakeQuantWeightLSQPlus", "FakeQuantActLSQPlus",
    "QuantizedLinear", "QuantStub", "Stub",
    "WeightOnlyLinear", "quantize_for_decode",
    "quantize_symmetric_q4", "pack_q4", "unpack_q4",
]


def _qmax(bits):
    return float(2 ** (bits - 1) - 1)


def _ste(x, q):
    """Straight-through estimator: forward q, backward identity."""
    return x + jax.lax.stop_gradient(q - x)


# ---------------------------------------------------------------------------
# int4 nibble format — THE storage format of the int4 paged KV pools
# (ISSUE 20, inference/kv_cache.py). Plain jnp functions (no Tensor
# wrapping) so the compiled decode/prefill steps call them directly.
# ---------------------------------------------------------------------------

def quantize_symmetric_q4(x, axis=-1):
    """Symmetric int4 quantization along ``axis``: one fp32 scale per
    row (max|x|/7, floored at 1e-30 so all-zero rows stay finite),
    payload = round(x/scale) clipped to [-7, 7] as UNPACKED int8.
    Returns ``(q int8, scales fp32 with axis removed)`` — pair with
    :func:`pack_q4` for the two-values-per-byte pool layout."""
    sc = jnp.maximum(jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis),
                     1e-30) / 7.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32)
                           / jnp.expand_dims(sc, axis)),
                 -7, 7).astype(jnp.int8)
    return q, sc


def pack_q4(q):
    """Pack int4 values (int-typed, in [-7, 7]) pairwise along the LAST
    axis into uint8: even lane -> high nibble, odd lane -> low nibble,
    offset-binary (+8, so nibbles land in [1, 15] and the byte is never
    0 for a live value pair unless both lanes are -8, which the
    quantizer never emits). Last dim must be even."""
    if q.shape[-1] % 2:
        raise ValueError(
            f"pack_q4 needs an even last dim, got {q.shape[-1]}")
    v = q.astype(jnp.int32) + 8
    return ((v[..., 0::2] << 4) | v[..., 1::2]).astype(jnp.uint8)


def unpack_q4(p):
    """Inverse of :func:`pack_q4`: uint8 ``[..., d//2]`` -> int32
    ``[..., d]`` values in [-8, 7] (high nibble first)."""
    v = p.astype(jnp.int32)
    hi = (v >> 4) - 8
    lo = (v & 0xF) - 8
    return jnp.stack([hi, lo], axis=-1).reshape(
        *p.shape[:-1], p.shape[-1] * 2)


# ---------------------------------------------------------------------------
# functional weight quantization (reference quantized_linear.py)
# ---------------------------------------------------------------------------

def _check_algo(algo):
    if algo not in ("weight_only_int8", "weight_only_int4", "llm.int8"):
        raise ValueError(f"unsupported quant algo {algo!r}")
    return 4 if algo == "weight_only_int4" else 8


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """Per-channel (or grouped) absmax int8/int4 weight quantization.
    x: [in, out] float16/bfloat16/float32. Returns (q [out, in] int8,
    scale float32 [out] or [in/group_size, out] for grouped). `arch` is
    accepted for API parity and ignored — XLA targets the current TPU.
    """
    bits = _check_algo(algo)
    if group_size not in (-1, 64, 128):
        raise ValueError(f"group_size must be -1/64/128, got {group_size}")
    x = ensure_tensor(x)
    qmax = _qmax(bits)

    def f(w):
        wf = w.astype(jnp.float32)
        if group_size == -1:
            scale = jnp.max(jnp.abs(wf), axis=0) / qmax        # [out]
            q = jnp.clip(jnp.round(wf / scale[None, :]), -qmax - 1, qmax)
            return q.T.astype(jnp.int8), scale
        k = wf.shape[0]
        if k % group_size:
            raise ValueError(f"in-dim {k} not divisible by group {group_size}")
        g = wf.reshape(k // group_size, group_size, -1)
        scale = jnp.max(jnp.abs(g), axis=1) / qmax             # [k/g, out]
        q = jnp.clip(jnp.round(g / scale[:, None, :]), -qmax - 1, qmax)
        return (q.reshape(k, -1).T.astype(jnp.int8), scale)

    out, scale = nary(f, [x], "weight_quantize")
    out.stop_gradient = True
    scale.stop_gradient = True
    return out, scale


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype="float16"):
    """Inverse of weight_quantize: q [out, in] + scale -> [in, out]."""
    _check_algo(algo)
    from ...framework.dtype import to_jax_dtype

    dt = to_jax_dtype(out_dtype)

    def f(q, s):
        w = q.astype(jnp.float32).T                            # [in, out]
        if s.ndim == 1:
            return (w * s[None, :]).astype(dt)
        k = w.shape[0]
        gs = k // s.shape[0]
        return (w.reshape(s.shape[0], gs, -1) * s[:, None, :]) \
            .reshape(k, -1).astype(dt)

    return binary(f, ensure_tensor(x), ensure_tensor(scale),
                  "weight_dequantize")


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """x [.., in] @ dequant(weight [out, in]) + bias. The int8 weight is
    the HBM-resident form (half the bytes of bf16); XLA fuses the scale
    multiply into the matmul operand read, so the bandwidth saving is
    real while the MXU still runs the dot in x's dtype."""
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)
    inputs = [x, weight]
    if weight_scale is not None:
        inputs.append(ensure_tensor(weight_scale))
    if bias is not None:
        inputs.append(ensure_tensor(bias))

    def f(xv, w, *rest):
        rest = list(rest)
        s = rest.pop(0) if weight_scale is not None else None
        b = rest.pop(0) if bias is not None else None
        wf = w.astype(xv.dtype)                                # [out, in]
        if s is not None:
            if s.ndim == 1:
                wf = wf * s[:, None].astype(xv.dtype)
            else:                                              # grouped
                o, k = wf.shape
                gs = k // s.shape[0]
                wf = (wf.reshape(o, s.shape[0], gs)
                      * s.T[:, :, None].astype(xv.dtype)).reshape(o, k)
        y = jnp.einsum("...k,ok->...o", xv, wf,
                       preferred_element_type=jnp.float32).astype(xv.dtype)
        if b is not None:
            y = y + b.astype(y.dtype)
        return y

    return nary(f, inputs, "weight_only_linear")


def llm_int8_linear(x, weight, bias=None, weight_scale=None, threshold=6.0):
    """LLM.int8 (Dettmers 2022): dynamic per-row absmax activation
    quantization, int8 x int8 -> int32 on the MXU, fp-outlier columns
    (absmax > threshold) decomposed to a small dense matmul."""
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)
    inputs = [x, weight]
    if weight_scale is not None:
        inputs.append(ensure_tensor(weight_scale))
    if bias is not None:
        inputs.append(ensure_tensor(bias))

    def f(xv, w, *rest):
        rest = list(rest)
        s = rest.pop(0) if weight_scale is not None else None
        b = rest.pop(0) if bias is not None else None
        xf = xv.astype(jnp.float32)
        # outlier decomposition: feature columns with any |x| > threshold
        col_max = jnp.max(jnp.abs(xf), axis=tuple(range(xf.ndim - 1)))
        outlier = col_max > threshold                          # [in]
        x_main = jnp.where(outlier, 0.0, xf)
        x_out = jnp.where(outlier, xf, 0.0)
        # dynamic per-row scales on the inlier part
        row_max = jnp.max(jnp.abs(x_main), axis=-1, keepdims=True)
        row_scale = jnp.where(row_max > 0, row_max / 127.0, 1.0)
        xq = jnp.clip(jnp.round(x_main / row_scale), -128, 127) \
            .astype(jnp.int8)
        # int8 x int8 -> int32 MXU dot
        acc = jax.lax.dot_general(
            xq, w, (((xq.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)                  # [.., out]
        ws = (s.astype(jnp.float32) if s is not None
              else jnp.ones((w.shape[0],), jnp.float32))
        y = acc.astype(jnp.float32) * row_scale * ws
        # outlier path in full precision against the dequantized weight
        wf = w.astype(jnp.float32) * ws[:, None]
        y = y + jnp.einsum("...k,ok->...o", x_out, wf)
        y = y.astype(xv.dtype)
        if b is not None:
            y = y + b.astype(y.dtype)
        return y

    return nary(f, inputs, "llm_int8_linear")


def apply_per_channel_scale(x, scales):
    """Pre-quant activation smoothing (smooth-quant): x / scales."""
    return binary(lambda v, s: (v.astype(jnp.float32)
                                / s.astype(jnp.float32)).astype(v.dtype),
                  ensure_tensor(x), ensure_tensor(scales),
                  "apply_per_channel_scale")


# ---------------------------------------------------------------------------
# QAT fake-quant layers (reference quant_layers.py)
# ---------------------------------------------------------------------------

class FakeQuantAbsMax(nn.Layer):
    """Per-tensor absmax fake quantization with STE gradients
    (reference quant_layers.py:69)."""

    def __init__(self, name=None, quant_bits=8, dtype="float32",
                 quant_on_weight=False, reduce_type=None):
        super().__init__()
        self._quant_bits = quant_bits

    def forward(self, x):
        x = ensure_tensor(x)
        qmax = _qmax(self._quant_bits)

        def f(v):
            scale = jnp.maximum(jnp.max(jnp.abs(v)).astype(jnp.float32),
                                1e-8) / qmax
            q = jnp.clip(jnp.round(v.astype(jnp.float32) / scale),
                         -qmax, qmax) * scale
            return _ste(v, q.astype(v.dtype))

        return unary(f, x, "fake_quant_abs_max")


class FakeQuantChannelWiseAbsMax(nn.Layer):
    """Per-output-channel absmax fake quant (reference :310)."""

    def __init__(self, name=None, channel_num=None, quant_bits=8,
                 quant_axis=0, dtype="float32", quant_on_weight=True,
                 reduce_type=None):
        super().__init__()
        self._quant_bits = quant_bits
        self._quant_axis = quant_axis

    def forward(self, x):
        x = ensure_tensor(x)
        qmax = _qmax(self._quant_bits)
        ax = self._quant_axis

        def f(v):
            vf = v.astype(jnp.float32)
            red = tuple(i for i in range(vf.ndim) if i != ax)
            scale = jnp.maximum(jnp.max(jnp.abs(vf), axis=red,
                                        keepdims=True), 1e-8) / qmax
            q = jnp.clip(jnp.round(vf / scale), -qmax, qmax) * scale
            return _ste(v, q.astype(v.dtype))

        return unary(f, x, "fake_quant_channel_abs_max")


class FakeQuantMovingAverageAbsMax(nn.Layer):
    """EMA absmax scale for activations (reference :172): the scale is a
    buffer updated in training, frozen in eval."""

    def __init__(self, name=None, moving_rate=0.9, quant_bits=8,
                 dtype="float32", reduce_type=None):
        super().__init__()
        self._rate = moving_rate
        self._quant_bits = quant_bits
        self.register_buffer("scale", Tensor(jnp.ones((), jnp.float32)))
        self.register_buffer("state", Tensor(jnp.zeros((), jnp.float32)))

    def forward(self, x):
        x = ensure_tensor(x)
        qmax = _qmax(self._quant_bits)
        if self.training:
            cur = jnp.max(jnp.abs(x._data)).astype(jnp.float32)
            st = self.state._data * self._rate + 1.0
            sc = (self.scale._data * self.state._data * self._rate
                  + cur) / st
            self.state._data = st
            self.scale._data = sc
        scale = jnp.maximum(self.scale._data, 1e-8) / qmax

        def f(v):
            q = jnp.clip(jnp.round(v.astype(jnp.float32) / scale),
                         -qmax, qmax) * scale
            return _ste(v, q.astype(v.dtype))

        return unary(f, x, "fake_quant_moving_avg")


class MovingAverageAbsMaxScale(nn.Layer):
    """Observer only (reference :424): tracks the EMA absmax, passes x
    through unchanged."""

    def __init__(self, name=None, moving_rate=0.9, dtype="float32",
                 reduce_type=None):
        super().__init__()
        self._rate = moving_rate
        self.register_buffer("scale", Tensor(jnp.ones((), jnp.float32)))
        self.register_buffer("state", Tensor(jnp.zeros((), jnp.float32)))

    def forward(self, x):
        x = ensure_tensor(x)
        if self.training:
            cur = jnp.max(jnp.abs(x._data)).astype(jnp.float32)
            st = self.state._data * self._rate + 1.0
            self.scale._data = (self.scale._data * self.state._data
                                * self._rate + cur) / st
            self.state._data = st
        return x


# ---------------------------------------------------------------------------
# LSQ+ (reference lsq.py): learned step size, STE with grad scaling
# ---------------------------------------------------------------------------

class FakeQuantWeightLSQPlus(nn.Layer):
    """Learned-step-size weight quantizer (reference lsq.py:245)."""

    def __init__(self, quant_bits=8, all_positive=False, per_channel=False,
                 channel_num=1, batch_init=20, dtype="float32", name=None,
                 reduce_type=None):
        super().__init__()
        self._bits = quant_bits
        self._per_channel = per_channel
        if all_positive:
            self.qmin, self.qmax = 0.0, float(2 ** quant_bits - 1)
        else:
            self.qmin = -float(2 ** (quant_bits - 1))
            self.qmax = float(2 ** (quant_bits - 1) - 1)
        n = channel_num if per_channel else 1
        self.s = self.create_parameter(
            [n], default_initializer=nn.initializer.Constant(1.0))
        self._initialized = False

    def _init_scale(self, v):
        init = 2.0 * jnp.mean(jnp.abs(v)) / (self.qmax ** 0.5)
        if self._per_channel:
            red = tuple(range(1, v.ndim))
            init = 2.0 * jnp.mean(jnp.abs(v), axis=red) / (self.qmax ** 0.5)
            self.s._data = jnp.maximum(init, 1e-8).astype(jnp.float32)
        else:
            self.s._data = jnp.maximum(
                init, 1e-8).reshape(1).astype(jnp.float32)
        self._initialized = True

    def forward(self, x):
        x = ensure_tensor(x)
        if not self._initialized:
            self._init_scale(x._data.astype(jnp.float32))
        qmin, qmax = self.qmin, self.qmax
        per_channel = self._per_channel
        # LSQ gradient scale keeps the step-size update well-conditioned
        g = 1.0 / float((x._data.size * qmax) ** 0.5)

        def f(v, s):
            sf = jnp.maximum(s.astype(jnp.float32), 1e-8)
            sg = sf * g + jax.lax.stop_gradient(sf * (1.0 - g))
            if per_channel:
                sg = sg.reshape((-1,) + (1,) * (v.ndim - 1))
            vf = v.astype(jnp.float32) / sg
            q = jnp.clip(vf, qmin, qmax)
            q = q + jax.lax.stop_gradient(jnp.round(q) - q)   # STE round
            return (q * sg).astype(v.dtype)

        return binary(f, x, self.s, "lsq_weight")


class FakeQuantActLSQPlus(nn.Layer):
    """LSQ+ activation quantizer with learned offset (reference lsq.py:138)."""

    def __init__(self, quant_bits=8, all_positive=False, symmetric=False,
                 batch_init=20, dtype="float32", name=None, reduce_type=None):
        super().__init__()
        if all_positive:
            self.qmin, self.qmax = 0.0, float(2 ** quant_bits - 1)
        else:
            self.qmin = -float(2 ** (quant_bits - 1))
            self.qmax = float(2 ** (quant_bits - 1) - 1)
        self._symmetric = symmetric
        self.s = self.create_parameter(
            [1], default_initializer=nn.initializer.Constant(1.0))
        self.beta = self.create_parameter(
            [1], default_initializer=nn.initializer.Constant(0.0))
        self._initialized = False

    def forward(self, x):
        x = ensure_tensor(x)
        if not self._initialized:
            v = x._data.astype(jnp.float32)
            self.s._data = jnp.maximum(
                2.0 * jnp.mean(jnp.abs(v)) / (self.qmax ** 0.5),
                1e-8).reshape(1).astype(jnp.float32)
            self._initialized = True
        qmin, qmax = self.qmin, self.qmax
        sym = self._symmetric
        g = 1.0 / float((x._data.size * qmax) ** 0.5)

        def f(v, s, beta):
            sf = jnp.maximum(s.astype(jnp.float32), 1e-8)
            sg = sf * g + jax.lax.stop_gradient(sf * (1.0 - g))
            off = 0.0 if sym else (beta.astype(jnp.float32) * g
                                   + jax.lax.stop_gradient(
                                       beta.astype(jnp.float32) * (1 - g)))
            vf = (v.astype(jnp.float32) - off) / sg
            q = jnp.clip(vf, qmin, qmax)
            q = q + jax.lax.stop_gradient(jnp.round(q) - q)
            return (q * sg + off).astype(v.dtype)

        return nary(f, [x, self.s, self.beta], "lsq_act")


# ---------------------------------------------------------------------------
# QAT layer wrappers
# ---------------------------------------------------------------------------

def _get_fake_quant_type(quant_type, **kwargs):
    """reference quant_layers.py:1197 factory."""
    table = {
        "abs_max": FakeQuantAbsMax,
        "moving_average_abs_max": FakeQuantMovingAverageAbsMax,
        "channel_wise_abs_max": FakeQuantChannelWiseAbsMax,
        "lsq_weight": FakeQuantWeightLSQPlus,
    }
    if quant_type not in table:
        raise ValueError(f"unknown fake quant type {quant_type!r}")
    cls = table[quant_type]
    accepted = {"abs_max": ("quant_bits",),
                "moving_average_abs_max": ("quant_bits", "moving_rate"),
                "channel_wise_abs_max": ("quant_bits", "quant_axis",
                                         "channel_num"),
                "lsq_weight": ("quant_bits", "per_channel", "channel_num")}
    kw = {k: v for k, v in kwargs.items() if k in accepted[quant_type]}
    return cls(**kw)


class QuantizedLinear(nn.Layer):
    """QAT linear (reference quant_layers.py:769): fake-quants activations
    and weights, runs the normal matmul — trains with quantization noise,
    exports via weight_quantize."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="channel_wise_abs_max",
                 activation_quantize_type="moving_average_abs_max", **kw):
        super().__init__()
        self.weight = layer.weight
        self.bias = getattr(layer, "bias", None)
        self._act_quant = _get_fake_quant_type(
            activation_quantize_type, quant_bits=activation_bits,
            moving_rate=moving_rate)
        self._w_quant = _get_fake_quant_type(
            weight_quantize_type, quant_bits=weight_bits, quant_axis=1,
            channel_num=self.weight.shape[1])

    def forward(self, x):
        x = self._act_quant(ensure_tensor(x))
        w = self._w_quant(self.weight)
        y = x.matmul(w)
        if self.bias is not None:
            y = y + self.bias
        return y


class Stub(nn.Layer):
    """Quantization stub (reference stub.py): placeholder replaced by an
    observer/quanter when a QAT config is applied; identity otherwise."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, x):
        if self._observer is not None:
            return self._observer(x)
        return x


QuantStub = Stub


class WeightOnlyLinear(nn.Layer):
    """Inference Linear with an int8 (or int4-packed) HBM-resident
    weight: half the weight bytes of bf16, 1/4 of fp32 — the decode
    regime is memory-bound on the weight stream, so this is the PERF.md
    "5x at bs1" lever, now reachable end to end via
    `quantize_for_decode(model)` + `model.generate()`.

    Built from an existing nn.Linear (weights quantized once, eagerly);
    the quantized weight and scale are registered parameters
    (trainable=False) so the compiled decode step threads them through
    its params pytree like any other weight.
    """

    def __init__(self, linear, algo="weight_only_int8"):
        super().__init__()
        if linear.weight is None:
            raise ValueError("linear has no weight")
        self.in_features = linear.in_features
        self.out_features = linear.out_features
        self.algo = algo
        self.weight_dtype = "int4" if "int4" in algo else "int8"
        qw, scale = weight_quantize(linear.weight, algo=algo)
        from ..layer.layers import Parameter

        self.quant_weight = Parameter(qw._data, trainable=False)
        self.weight_scale = Parameter(scale._data, trainable=False)
        self.bias = (None if linear.bias is None
                     else Parameter(linear.bias._data))

    def forward(self, x):
        return weight_only_linear(x, self.quant_weight, bias=self.bias,
                                  weight_scale=self.weight_scale,
                                  weight_dtype=self.weight_dtype)

    def extra_repr(self):
        return (f"in_features={self.in_features}, "
                f"out_features={self.out_features}, algo={self.algo}")


def quantize_for_decode(model, algo="weight_only_int8",
                        include=("qkv", "out_proj", "fc1", "fc2",
                                 "lm_head")):
    """Swap every matching nn.Linear in `model` for a WeightOnlyLinear
    (in place). `include` filters by attribute name — the default covers
    the GPT/LLaMA projection set; tied embeddings (lm_head=None) keep
    the fp embedding matmul, which the decode step reads once per token
    anyway. Returns the model for chaining."""
    from ..layer.common import Linear

    for layer in model.sublayers(include_self=True):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, Linear) and not isinstance(
                    sub, WeightOnlyLinear) and name in include:
                layer._sub_layers[name] = WeightOnlyLinear(sub,
                                                           algo=algo)
    return model
