"""Admission / preemption / retirement policy over the paged KV cache.

The scheduler owns the HOST side of continuous batching: which request
gets a slot, which running sequence is sacrificed when the page pool
runs dry, and when a slot's pages go back to the pool. It never touches
device compute — the engine runs the compiled steps; the scheduler only
rewrites the cache's host bookkeeping (slots, page tables, active
flags), which the steps pick up as refreshed inputs, never a retrace.

Policy:

* **Admission** — strict FIFO within priority (higher priority first,
  then arrival order; a resumed preempted request keeps its original
  arrival rank, so it re-enters ahead of everything that arrived after
  it). Only the head of the queue is considered: a small request never
  jumps a big one that is still waiting for pages (no head-of-line
  bypass — saturation stays fair). Admission probes capacity with
  `can_allocate` BEFORE committing, and keeps a watermark of one free
  page per decode-active sequence so an admission cannot instantly
  force a preemption.
* **Preemption** — when a decode step needs one more page and the pool
  is dry, the lowest-priority (then youngest-arrival) decode-active
  sequence is evicted: its pages return to the pool and the request
  re-queues for resume-by-re-prefill. Mid-prefill slots are never
  victims (their prompt pages were fully reserved at admission).
* **Retirement** — EOS / max_new_tokens frees the slot immediately so
  its pages recycle into the next admission.
"""
from __future__ import annotations

from .request import RequestHandle, RequestState

__all__ = ["RequestScheduler"]


class RequestScheduler:
    def __init__(self, cache, metrics, admit_watermark="auto",
                 tracer=None):
        self.cache = cache
        self.metrics = metrics
        self.waiting: list[RequestHandle] = []   # kept sorted (see _key)
        self.running: dict[int, RequestHandle] = {}   # slot -> handle
        self.admit_watermark = admit_watermark
        self.tracer = tracer            # set by the engine (ISSUE 13)
        # tokens one decode dispatch may append per slot (the engine
        # sets it: decode_burst, or spec_k+1 under speculative
        # decoding) — the "auto" admission watermark scales with it
        self.token_lookahead = 1
        # optional HostKVRing (ISSUE 18): preemption victims park their
        # KV pages in host memory instead of discarding them, and
        # re-admission imports the parked pages back (no re-prefill).
        # None = exact pre-fleet behaviour.
        self.host_ring = None

    # -- queue ------------------------------------------------------------
    @staticmethod
    def _key(h: RequestHandle):
        """Service order: min() = next to serve (highest priority,
        oldest arrival); max() = next preemption victim (lowest
        priority, youngest arrival)."""
        return (-h.request.priority, h.arrival_seq)

    def enqueue(self, handle: RequestHandle):
        self.waiting.append(handle)
        self.waiting.sort(key=self._key)

    def decode_slots(self) -> list[int]:
        """Slots with decode-active (fully prefilled) sequences."""
        return [s for s, h in self.running.items()
                if h.state is RequestState.RUNNING]

    def prefill_heads(self, k: int) -> list[RequestHandle]:
        """Up to `k` oldest mid-prefill residents (batched chunk
        prefill: one compiled call advances all of their prompts)."""
        cands = [h for h in self.running.values()
                 if h.state is RequestState.PREFILL]
        return sorted(cands, key=self._key)[:k]

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # -- admission --------------------------------------------------------
    def _watermark(self) -> int:
        if self.admit_watermark == "auto":
            # one dispatch can grow each decode-active sequence by
            # `token_lookahead` tokens — keep enough free pages that
            # every live slot can take its next dispatch without an
            # instant preemption (== the old one-page-per-slot rule
            # whenever the lookahead fits a page, i.e. plain decode)
            per_slot = -(-max(1, int(self.token_lookahead))
                         // self.cache.page_size)
            return len(self.decode_slots()) * per_slot
        return int(self.admit_watermark)

    def admit(self) -> list[RequestHandle]:
        """Admit from the head of the queue while capacity allows.
        Returns the handles admitted this call (slot + pages mapped for
        their FULL pending prompt, so prefill can never stall)."""
        cache = self.cache
        admitted = []
        while self.waiting:
            head = self.waiting[0]
            # re-onload probe: a preempted head whose pages are parked
            # in the host ring imports them instead of re-prefilling.
            # take() claims the blob atomically (the ring is shared
            # across replicas — a peek could lose it to a concurrent
            # overflow drop); a failed capacity check parks it back.
            parked = (self.host_ring.take(head.request.rid)
                      if self.host_ring is not None and head.preemptions
                      else None)
            need_len = (int(parked[0]["seq_len"]) if parked is not None
                        else len(head.pending))
            fits = cache.can_allocate(need_len)
            if fits and (admitted or self.decode_slots()):
                # an admission that would leave fewer free pages than
                # one per decode-active sequence invites instant
                # preemption churn — hold the head until a retirement
                # frees pages
                left = (cache.free_page_count
                        - cache.pages_needed(need_len))
                fits = left >= self._watermark()
            if not fits:
                if parked is not None:
                    self.host_ring.put(head.request.rid, *parked)
                break
            self.waiting.pop(0)
            if parked is not None:
                admitted.append(self._onload(head, parked))
                continue
            slot = cache.allocate(need_len)
            cache.set_active(slot, False)   # decode joins after prefill
            head.slot = slot
            head.prefill_pos = 0
            head.state = RequestState.PREFILL
            self.running[slot] = head
            self.metrics.on_admit(resumed=head.preemptions > 0)
            admitted.append(head)
        return admitted

    def _onload(self, head: RequestHandle, parked) -> RequestHandle:
        """Bring an evicted request's KV back from the host ring: the
        resume skips re-prefill entirely and rejoins decode where it
        left off. The import cost lands on the request's trace as a
        ``kv_onload`` span — the victim pays for its own migration,
        charged inside its queue-to-first-new-token gap."""
        blob, last_token = parked
        span = (self.tracer.begin("kv_onload", parent=head._span,
                                  pages=blob["pages"],
                                  bytes=blob["nbytes"])
                if self.tracer is not None and head._span is not None
                else None)
        slot = self.cache.import_slot(blob, active=True)
        if span is not None:
            self.tracer.end(span, slot=slot)
        head.slot = slot
        head.state = RequestState.RUNNING
        # the last sampled token was exported alongside the pages: the
        # next decode step writes it at position seq_len, exactly as if
        # the eviction never happened (the engine reloads it into its
        # per-slot token vector and refreshes its buffer dict)
        head._onload_token = int(last_token)
        self.running[slot] = head
        self.metrics.kv_onloads += 1
        self.metrics.on_admit(resumed=True)
        return head

    # -- preemption -------------------------------------------------------
    def _victim(self, protect: int) -> int | None:
        """Most victim-eligible decode-active slot other than `protect`
        (mid-prefill slots are never victims)."""
        cands = [s for s in self.decode_slots() if s != protect]
        if not cands:
            return None
        if self.host_ring is not None:
            # LRU-of-idle (ISSUE 18): with a host ring behind the pool,
            # eviction is a migration, not a kill — so pick the session
            # whose stream has been quiet longest (its KV is the
            # coldest and it is the most likely to tolerate the onload
            # round-trip), tie-broken by the usual policy key
            def idle_key(s):
                h = self.running[s]
                last = (h._token_times[-1] if h._token_times
                        else h.submit_time) or 0.0
                return (-last, self._key(h))
            return max(cands, key=idle_key)
        return max(cands, key=lambda s: self._key(self.running[s]))

    def preempt(self, slot: int, reason: str = "pool_dry"
                ) -> RequestHandle:
        """Evict `slot`: pages to the pool, request back to the queue
        (keeping its arrival rank) for resume-by-re-prefill.
        ``reason`` lands on the request's trace: "pool_dry" (evicted
        for a neighbour), "self_sacrifice" (every candidate outranked
        it), "abort" (engine recovery)."""
        handle = self.running.pop(slot)
        pages = len(self.cache._slot_pages.get(slot, ()))
        evicted_to_host = False
        if (self.host_ring is not None and reason != "abort"
                and handle.state is RequestState.RUNNING
                and handle.output_tokens):
            # park the victim's pages + its not-yet-cached last sample
            # in host memory; re-admission imports them back. If the
            # ring later drops the blob under byte pressure, the
            # handle's pending prompt below is the re-prefill fallback.
            self.host_ring.put(handle.request.rid,
                               self.cache.export_slot(slot),
                               handle.output_tokens[-1])
            self.metrics.kv_evictions += 1
            evicted_to_host = True
        self.cache.free(slot)
        if self.tracer is not None and handle._span is not None:
            self.tracer.instant("preempt", parent=handle._span,
                                reason=reason, slot=slot,
                                pages_reclaimed=pages,
                                evicted_to_host=evicted_to_host,
                                tokens_so_far=len(handle.output_tokens))
        handle._requeue_for_resume()
        self.enqueue(handle)
        if self.tracer is not None and handle._span is not None:
            handle._span_queue = self.tracer.begin(
                "queue_wait", parent=handle._span, resume=True)
        self.metrics.on_preempt(pages_reclaimed=pages)
        return handle

    def ensure_token_capacity(self, slot: int, lookahead: int = 1
                              ) -> bool:
        """Guarantee `slot` can hold `lookahead` more tokens, preempting
        victims while the pool is dry. Returns False when `slot` itself
        had to be sacrificed (it was the lowest-priority sequence)."""
        cache = self.cache
        handle = self.running[slot]
        need = self._context_len(handle) + int(lookahead)
        while not cache.can_reserve(slot, need):
            victim = self._victim(protect=slot)
            if victim is None or (self._key(handle)
                                  > self._key(self.running[victim])):
                # every other candidate outranks this sequence (or none
                # exists) — growing it by evicting a higher-priority
                # neighbour would invert the policy, so it sacrifices
                # itself
                self.preempt(slot, reason="self_sacrifice")
                return False
            self.preempt(victim, reason="pool_dry")
        cache.reserve(slot, need)
        return True

    @staticmethod
    def _context_len(handle: RequestHandle) -> int:
        """Tokens currently cached for a resident handle: the prefilled
        prefix plus every decode-written token. The last sampled token
        is NOT cached yet (it is written by the next decode step)."""
        if handle.state is RequestState.PREFILL:
            return handle.prefill_pos
        # RUNNING: prefill cached len(pending) tokens and sampled one;
        # each decode step since wrote one token and sampled the next —
        # so cached = prompt + output minus the one not-yet-written
        # last sample, independent of how many resumes happened
        return len(handle.request.prompt) + len(handle.output_tokens) - 1

    # -- retirement -------------------------------------------------------
    def retire(self, slot: int, reason, now: float) -> RequestHandle:
        handle = self.running.pop(slot)
        self.cache.free(slot)
        handle.slot = None
        handle.state = RequestState.FINISHED
        handle.finish_reason = reason
        handle.finish_time = now
        self.metrics.on_finish(handle)
        return handle

    def abort_all(self) -> list[RequestHandle]:
        """Recovery path (engine step failure): every resident request
        re-queues for resume; the caller rebuilds the cache."""
        return [self.preempt(slot, reason="abort")
                for slot in list(self.running)]
