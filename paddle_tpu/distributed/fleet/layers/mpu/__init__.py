from .mp_layers import (  # noqa: F401
    VocabParallelEmbedding,
    ColumnParallelLinear,
    RowParallelLinear,
    ParallelCrossEntropy,
    vocab_parallel_cross_entropy,
)
from .random import RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed  # noqa: F401
