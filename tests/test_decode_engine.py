"""Paged KV-cache decode engine tests (ISSUE 2).

Parity chain: ragged paged attention kernel (interpret) == XLA gather
fallback; paged decode logits == dense decode logits == full-sequence
forward (fp32 tolerance); greedy generate identical eager vs compiled.
Plus continuous-batching cache correctness across slot free/reuse and
the retrace guard: ONE compile for 64 decode steps, per-layer cache
update lowering to dynamic-update-slice (no per-token concat growth).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM


def tiny_model(**over):
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=96,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                    **over)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


class TestPagedAttentionKernel:
    def _setup(self, b=3, nh=4, kvh=2, d=32, ps=16, npages=16, pp=4,
               seed=0):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.standard_normal((b, nh, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((kvh, npages, ps, d)),
                        jnp.float32)
        v = jnp.asarray(rng.standard_normal((kvh, npages, ps, d)),
                        jnp.float32)
        pt = jnp.asarray(rng.choice(np.arange(1, npages), (b, pp),
                                    replace=False), jnp.int32)
        return q, k, v, pt

    def test_interpret_kernel_matches_xla(self):
        from paddle_tpu.ops.pallas import paged_attention as pa

        q, k, v, pt = self._setup()
        lens = jnp.asarray([37, 1, 64], jnp.int32)   # ragged
        ref = pa.paged_attention_xla(q, k, v, pt, lens)
        got = pa.paged_attention(q, k, v, pt, lens, interpret=True,
                                 use_kernel=True)
        assert float(jnp.max(jnp.abs(ref - got))) < 1e-5

    def test_empty_slot_zero_output(self):
        from paddle_tpu.ops.pallas import paged_attention as pa

        q, k, v, pt = self._setup()
        lens = jnp.asarray([0, 5, 64], jnp.int32)
        ref = pa.paged_attention_xla(q, k, v, pt, lens)
        got = pa.paged_attention(q, k, v, pt, lens, interpret=True,
                                 use_kernel=True)
        assert float(jnp.max(jnp.abs(ref - got))) < 1e-5
        assert bool(jnp.all(got[0] == 0.0))

    def test_ragged_matches_dense_reference(self):
        """The paged gather path equals plain masked attention over the
        densified per-slot history."""
        from paddle_tpu.ops.pallas import paged_attention as pa

        q, k, v, pt = self._setup(b=2, nh=4, kvh=4, d=16, ps=8,
                                  npages=12, pp=3)
        lens = np.array([13, 20], np.int32)
        got = np.asarray(pa.paged_attention_xla(
            q, k, v, pt, jnp.asarray(lens)))
        for i in range(2):
            hist_k = np.concatenate(
                [np.asarray(k)[:, int(p)] for p in np.asarray(pt)[i]],
                axis=1)[:, :lens[i]]                   # [kvh, L, d]
            hist_v = np.concatenate(
                [np.asarray(v)[:, int(p)] for p in np.asarray(pt)[i]],
                axis=1)[:, :lens[i]]
            s = np.einsum("hd,hkd->hk", np.asarray(q)[i], hist_k) \
                / np.sqrt(q.shape[-1])
            p_ = np.exp(s - s.max(-1, keepdims=True))
            p_ /= p_.sum(-1, keepdims=True)
            want = np.einsum("hk,hkd->hd", p_, hist_v)
            np.testing.assert_allclose(got[i], want, atol=1e-5)


class TestIncubateDecodeOps:
    def test_masked_multihead_attention_aligned(self):
        from paddle_tpu.incubate.nn import functional as IF

        rng = np.random.default_rng(0)
        b, nh, d, ms, hist = 2, 3, 8, 12, 4
        cache = np.zeros((2, b, nh, ms, d), np.float32)
        cache[:, :, :, :hist] = rng.standard_normal((2, b, nh, hist, d))
        x = rng.standard_normal((b, 3 * nh * d)).astype(np.float32)
        out, c2 = IF.masked_multihead_attention(
            paddle.to_tensor(x), paddle.to_tensor(cache),
            sequence_lengths=hist)
        qkv = x.reshape(b, 3, nh, d)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        K = np.concatenate([cache[0, :, :, :hist], k[:, :, None]], 2)
        V = np.concatenate([cache[1, :, :, :hist], v[:, :, None]], 2)
        s = np.einsum("bhd,bhkd->bhk", q, K) / np.sqrt(d)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        want = np.einsum("bhk,bhkd->bhd", p, V).reshape(b, nh * d)
        np.testing.assert_allclose(np.asarray(out._data), want,
                                   atol=1e-5)
        # cache append at position `hist`
        np.testing.assert_allclose(
            np.asarray(c2._data)[0, :, :, hist], k, atol=1e-6)

    def test_masked_multihead_attention_ragged(self):
        from paddle_tpu.incubate.nn import functional as IF

        rng = np.random.default_rng(1)
        b, nh, d, ms = 2, 2, 8, 10
        lens = np.array([5, 2], np.int32)
        cache = np.zeros((2, b, nh, ms, d), np.float32)
        for i, L in enumerate(lens):
            cache[:, i, :, :L] = rng.standard_normal((2, nh, L, d))
        x = rng.standard_normal((b, 3 * nh * d)).astype(np.float32)
        out, _ = IF.masked_multihead_attention(
            paddle.to_tensor(x), paddle.to_tensor(cache),
            sequence_lengths=paddle.to_tensor(lens))
        qkv = x.reshape(b, 3, nh, d)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        for i, L in enumerate(lens):
            K = np.concatenate([cache[0, i, :, :L], k[i][:, None]], 1)
            V = np.concatenate([cache[1, i, :, :L], v[i][:, None]], 1)
            s = np.einsum("hd,hkd->hk", q[i], K) / np.sqrt(d)
            p = np.exp(s - s.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            want = np.einsum("hk,hkd->hd", p, V).reshape(-1)
            np.testing.assert_allclose(np.asarray(out._data)[i], want,
                                       atol=1e-5)

    def test_masked_multihead_attention_numpy_seq_lens(self):
        """A raw numpy [bsz] sequence_lengths must route to the ragged
        path (review fix: detection was Tensor-only and the aligned
        branch crashed on the reshape)."""
        from paddle_tpu.incubate.nn import functional as IF

        rng = np.random.default_rng(3)
        b, nh, d, ms = 2, 2, 8, 10
        cache = rng.standard_normal((2, b, nh, ms, d)).astype(
            np.float32)
        x = rng.standard_normal((b, 3 * nh * d)).astype(np.float32)
        lens = np.array([4, 2], np.int32)
        out_np, _ = IF.masked_multihead_attention(
            paddle.to_tensor(x), paddle.to_tensor(cache),
            sequence_lengths=lens)
        out_t, _ = IF.masked_multihead_attention(
            paddle.to_tensor(x), paddle.to_tensor(cache),
            sequence_lengths=paddle.to_tensor(lens))
        np.testing.assert_allclose(np.asarray(out_np._data),
                                   np.asarray(out_t._data))

    def test_masked_multihead_attention_broadcast_src_mask(self):
        """A [1, 1, 1, max_seq] src_mask (broadcastable, reference
        contract) must broadcast over the batch, not be reshaped into
        it (review fix)."""
        from paddle_tpu.incubate.nn import functional as IF

        rng = np.random.default_rng(5)
        b, nh, d, ms = 2, 2, 8, 6
        cache = rng.standard_normal((2, b, nh, ms, d)).astype(
            np.float32)
        x = rng.standard_normal((b, 3 * nh * d)).astype(np.float32)
        bias = np.zeros((1, 1, 1, ms), np.float32)
        bias[..., 1] = -1e9          # block key position 1 everywhere
        out_m, _ = IF.masked_multihead_attention(
            paddle.to_tensor(x), paddle.to_tensor(cache),
            src_mask=paddle.to_tensor(bias), sequence_lengths=3)
        # reference: zero out position 1 manually in a full-bias mask
        full = np.broadcast_to(bias, (b, 1, 1, ms)).copy()
        out_f, _ = IF.masked_multihead_attention(
            paddle.to_tensor(x), paddle.to_tensor(cache),
            src_mask=paddle.to_tensor(full), sequence_lengths=3)
        np.testing.assert_allclose(np.asarray(out_m._data),
                                   np.asarray(out_f._data))
        # 1-D [max_seq] mask is also broadcastable per the contract
        out_1d, _ = IF.masked_multihead_attention(
            paddle.to_tensor(x), paddle.to_tensor(cache),
            src_mask=paddle.to_tensor(bias.reshape(ms)),
            sequence_lengths=3)
        np.testing.assert_allclose(np.asarray(out_1d._data),
                                   np.asarray(out_f._data))

    def test_block_multihead_attention_padding_rows_dropped(self):
        """Padding rows past cu_seqlens must be DROPPED, not wrapped to
        the pool's last row (review fix: -1 wraps before mode='drop')."""
        from paddle_tpu.incubate.nn import functional as IF

        rng = np.random.default_rng(4)
        nh, kvh, d, bs, nblocks = 2, 2, 4, 4, 4
        qkv = rng.standard_normal((2, (nh + 2 * kvh) * d)).astype(
            np.float32)
        kc = np.zeros((nblocks, kvh, bs, d), np.float32)
        vc = np.zeros((nblocks, kvh, bs, d), np.float32)
        sentinel = 123.0
        kc[-1, :, -1] = sentinel      # last row of the last pool page
        vc[-1, :, -1] = sentinel
        _, _, kc2, vc2 = IF.block_multihead_attention(
            paddle.to_tensor(qkv), paddle.to_tensor(kc),
            paddle.to_tensor(vc),
            paddle.to_tensor(np.array([1], np.int32)),
            paddle.to_tensor(np.array([0], np.int32)),
            paddle.to_tensor(np.array([1], np.int32)),
            cu_seqlens_q=paddle.to_tensor(np.array([0, 1], np.int32)),
            block_tables=paddle.to_tensor(np.array([[1, 2]], np.int32)),
            block_size=bs)   # 2 qkv rows, only 1 real token
        np.testing.assert_allclose(
            np.asarray(kc2._data)[-1, :, -1], sentinel)
        np.testing.assert_allclose(
            np.asarray(vc2._data)[-1, :, -1], sentinel)

    def test_block_multihead_attention_mixed_prefill_decode(self):
        from paddle_tpu.incubate.nn import functional as IF

        rng = np.random.default_rng(2)
        nh, kvh, d, bs, nblocks = 4, 2, 8, 4, 8
        enc = np.array([3, 0], np.int32)
        dec = np.array([0, 2], np.int32)
        this = np.array([3, 1], np.int32)
        cu = np.array([0, 3, 4], np.int32)
        bt = np.array([[1, 2, 0], [3, 0, 0]], np.int32)
        tok = 4
        qkv = rng.standard_normal(
            (tok, (nh + 2 * kvh) * d)).astype(np.float32)
        kc = np.zeros((nblocks, kvh, bs, d), np.float32)
        vc = np.zeros((nblocks, kvh, bs, d), np.float32)
        k_hist = rng.standard_normal((kvh, 2, d)).astype(np.float32)
        v_hist = rng.standard_normal((kvh, 2, d)).astype(np.float32)
        kc[3, :, :2] = k_hist
        vc[3, :, :2] = v_hist
        out, _, kc2, vc2 = IF.block_multihead_attention(
            paddle.to_tensor(qkv), paddle.to_tensor(kc),
            paddle.to_tensor(vc), paddle.to_tensor(enc),
            paddle.to_tensor(dec), paddle.to_tensor(this),
            cu_seqlens_q=paddle.to_tensor(cu),
            block_tables=paddle.to_tensor(bt), block_size=bs)
        out = np.asarray(out._data)
        qkv_h = qkv.reshape(tok, nh + 2 * kvh, d)
        q = qkv_h[:, :nh]
        kn, vn = qkv_h[:, nh:nh + kvh], qkv_h[:, nh + kvh:]
        grp = nh // kvh

        def naive(i):
            s_id = 0 if i < 3 else 1
            t = i - cu[s_id]
            if s_id == 0:
                K, V = kn[:t + 1], vn[:t + 1]
            else:
                K = np.concatenate(
                    [k_hist.transpose(1, 0, 2), kn[3:4]], 0)
                V = np.concatenate(
                    [v_hist.transpose(1, 0, 2), vn[3:4]], 0)
            o = np.zeros((nh, d), np.float32)
            for h in range(nh):
                g = h // grp
                s = (q[i, h] @ K[:, g].T) / np.sqrt(d)
                p = np.exp(s - s.max())
                p /= p.sum()
                o[h] = p @ V[:, g]
            return o.reshape(-1)

        for i in range(tok):
            np.testing.assert_allclose(out[i], naive(i), atol=1e-5)
        kc2 = np.asarray(kc2._data)
        np.testing.assert_allclose(kc2[1, :, 2], kn[2], atol=1e-6)
        np.testing.assert_allclose(kc2[3, :, 2], kn[3], atol=1e-6)
        np.testing.assert_allclose(kc2[3, :, :2], k_hist, atol=1e-6)


class TestDecodeParity:
    """Paged and dense cached decode logits match the full-sequence
    forward, greedy generate identical eager vs compiled — the ISSUE's
    acceptance criteria."""

    def _full_forward_logits(self, m, ids_row):
        logits = m(paddle.to_tensor(ids_row[None], dtype="int64"))
        return np.asarray(logits._data, np.float32)[0]

    @pytest.mark.parametrize("kind", ["dense", "paged"])
    def test_decode_logits_match_full_forward(self, kind):
        m = tiny_model()
        rng = np.random.default_rng(3)
        b, s, new = 2, 9, 4
        ids = rng.integers(1, 97, (b, s))
        out, logits = m.generate(
            paddle.to_tensor(ids, dtype="int64"), max_new_tokens=new,
            use_cache=kind, return_logits=True)
        out = np.asarray(out._data)
        logits = np.asarray(logits._data, np.float32)
        for i in range(b):
            full = np.concatenate([ids[i], out[i][:-1]])
            want = self._full_forward_logits(m, full)
            for t in range(new):
                np.testing.assert_allclose(
                    logits[i, t], want[s - 1 + t], rtol=2e-4,
                    atol=2e-4,
                    err_msg=f"{kind} seq {i} decode step {t}")

    def test_paged_ragged_matches_per_seq_full_forward(self):
        m = tiny_model()
        rng = np.random.default_rng(4)
        b, s, new = 2, 10, 3
        lens = np.array([10, 6], np.int32)
        ids = rng.integers(1, 97, (b, s))
        ids[1, 6:] = 0
        out, logits = m.generate(
            paddle.to_tensor(ids, dtype="int64"), max_new_tokens=new,
            use_cache="paged", seq_lens=lens, return_logits=True)
        out = np.asarray(out._data)
        logits = np.asarray(logits._data, np.float32)
        for i in range(b):
            full = np.concatenate([ids[i, :lens[i]], out[i][:-1]])
            want = self._full_forward_logits(m, full)
            for t in range(new):
                np.testing.assert_allclose(
                    logits[i, t], want[lens[i] - 1 + t], rtol=2e-4,
                    atol=2e-4, err_msg=f"ragged seq {i} step {t}")

    def test_greedy_generate_eager_matches_compiled(self):
        m = tiny_model()
        rng = np.random.default_rng(5)
        ids = rng.integers(1, 97, (2, 8))
        compiled = m.generate(paddle.to_tensor(ids, dtype="int64"),
                              max_new_tokens=6, use_cache="dense")
        eager = m.generate(paddle.to_tensor(ids, dtype="int64"),
                           max_new_tokens=6, use_cache="dense",
                           compiled=False)
        np.testing.assert_array_equal(np.asarray(compiled._data),
                                      np.asarray(eager._data))

    def test_dense_equals_paged_tokens(self):
        m = tiny_model()
        rng = np.random.default_rng(6)
        ids = rng.integers(1, 97, (2, 8))
        d = m.generate(paddle.to_tensor(ids, dtype="int64"),
                       max_new_tokens=6, use_cache="dense")
        p = m.generate(paddle.to_tensor(ids, dtype="int64"),
                       max_new_tokens=6, use_cache="paged")
        np.testing.assert_array_equal(np.asarray(d._data),
                                      np.asarray(p._data))

    def test_sampled_generate_deterministic_by_seed(self):
        m = tiny_model()
        ids = np.full((1, 4), 7)
        kw = dict(max_new_tokens=5, do_sample=True, top_k=20,
                  top_p=0.9, temperature=1.3)
        a = m.generate(paddle.to_tensor(ids, dtype="int64"), seed=11,
                       **kw)
        b = m.generate(paddle.to_tensor(ids, dtype="int64"), seed=11,
                       **kw)
        np.testing.assert_array_equal(np.asarray(a._data),
                                      np.asarray(b._data))

    def test_sampled_generate_varies_without_seed(self):
        """seed=None must draw from the framework RNG stream — repeated
        sampled generates differ (review fix: a fixed PRNGKey(0) made
        every call bit-identical)."""
        m = tiny_model()
        ids = np.full((1, 4), 7)
        kw = dict(max_new_tokens=8, do_sample=True, temperature=2.0)
        runs = [np.asarray(m.generate(
            paddle.to_tensor(ids, dtype="int64"), **kw)._data)
            for _ in range(3)]
        assert not all((r == runs[0]).all() for r in runs[1:]), runs

    def test_int8_weight_only_decode(self):
        from paddle_tpu.nn.quant import (
            WeightOnlyLinear, quantize_for_decode,
        )

        m = tiny_model()
        rng = np.random.default_rng(7)
        ids = rng.integers(1, 97, (2, 8))
        ref = np.asarray(m.generate(
            paddle.to_tensor(ids, dtype="int64"),
            max_new_tokens=4)._data)
        quantize_for_decode(m)
        assert isinstance(m.gpt.blocks[0].attn.qkv, WeightOnlyLinear)
        got = np.asarray(m.generate(
            paddle.to_tensor(ids, dtype="int64"),
            max_new_tokens=4)._data)
        # int8 weights perturb logits; greedy tokens of a tiny random
        # model still agree at step 0 where the margin is the raw argmax
        assert got.shape == ref.shape

    def test_eos_masks_tail(self):
        m = tiny_model()
        ids = np.full((1, 4), 3)
        out = m.generate(paddle.to_tensor(ids, dtype="int64"),
                         max_new_tokens=6)
        tok0 = int(np.asarray(out._data)[0, 0])
        out2 = m.generate(paddle.to_tensor(ids, dtype="int64"),
                          max_new_tokens=6, eos_token_id=tok0)
        assert (np.asarray(out2._data) == tok0).all()


class TestCacheSlotReuse:
    def test_slot_free_reuse_isolation(self):
        """Continuous batching: freeing a slot and reusing its pages for
        a new sequence must not disturb surviving slots."""
        from paddle_tpu.inference.kv_cache import (
            PagedKVCache, paged_write_prefill,
        )

        kvh, d, ps = 2, 4, 4
        cache = PagedKVCache(num_layers=1, num_kv_heads=kvh, head_dim=d,
                             num_pages=9, page_size=ps, max_slots=3,
                             pages_per_seq=4)
        rng = np.random.default_rng(0)

        def write(slot, length, seed):
            r = np.random.default_rng(seed)
            k = jnp.asarray(r.standard_normal((1, length, kvh, d)),
                            jnp.float32)
            nk, nv = paged_write_prefill(
                cache.k_layers[0], cache.v_layers[0],
                cache.page_tables, jnp.asarray([slot], jnp.int32),
                jnp.asarray([length], jnp.int32), k, k)
            cache.k_layers[0], cache.v_layers[0] = nk, nv
            # metadata is host numpy between steps (serving tier)
            cache.seq_lens = np.asarray(cache.seq_lens)
            cache.seq_lens[slot] = length
            return np.asarray(k[0])

        def read(slot, length):
            pt = np.asarray(cache.page_tables)[slot]
            pool = np.asarray(cache.k_layers[0])   # [kvh, np, ps, d]
            toks = np.concatenate([pool[:, p] for p in pt], axis=1)
            return toks[:, :length].transpose(1, 0, 2)   # [L, kvh, d]

        s0 = cache.allocate(6)
        s1 = cache.allocate(5)
        write(s0, 6, seed=10)
        k1 = write(s1, 5, seed=11)
        free_before = cache.free_page_count
        cache.free(s0)
        assert cache.free_page_count == free_before + 2   # 6 tok / 4 ps
        s2 = cache.allocate(7)   # reuses s0's pages
        k2 = write(s2, 7, seed=12)
        # survivor slot untouched, new slot reads back its own tokens
        np.testing.assert_allclose(read(s1, 5), k1, atol=1e-6)
        np.testing.assert_allclose(read(s2, 7), k2, atol=1e-6)
        # trash page (0) never mapped
        assert 0 not in np.asarray(cache.page_tables)[[s1, s2]][
            :, :2].tolist()

    def test_engine_survives_midloop_failure(self):
        """A failed generate must not leave the (model-cached) engine
        pointing at donated/stale cache buffers (review fix: the cache
        is rebuilt pristine on any mid-loop exception)."""
        from paddle_tpu.jit.decode_step import GenerationEngine

        m = tiny_model()
        eng = GenerationEngine(m, kind="paged", batch=1, max_len=24)
        ids = np.full((1, 8), 5)
        ref = np.asarray(eng.generate(ids, 6)._data)
        real = eng.decode_step
        calls = {"n": 0}

        class Boom:
            def __call__(self, *a):
                calls["n"] += 1
                if calls["n"] == 2:
                    raise RuntimeError("boom")
                return real(*a)

        eng.decode_step = Boom()
        with pytest.raises(RuntimeError):
            eng.generate(ids, 6)
        eng.decode_step = real
        out = np.asarray(eng.generate(ids, 6)._data)
        np.testing.assert_array_equal(out, ref)

    def test_engine_reuse_across_generate_calls(self):
        """A second generate() on the SAME engine (slots freed and
        re-allocated, cache buffers reused) matches a fresh model."""
        m = tiny_model()
        rng = np.random.default_rng(8)
        a = rng.integers(1, 97, (2, 8))
        b = rng.integers(1, 97, (2, 8))
        out_b_first = np.asarray(m.generate(
            paddle.to_tensor(b, dtype="int64"), max_new_tokens=5,
            use_cache="paged")._data)
        _ = m.generate(paddle.to_tensor(a, dtype="int64"),
                       max_new_tokens=5, use_cache="paged")
        out_b_reused = np.asarray(m.generate(
            paddle.to_tensor(b, dtype="int64"), max_new_tokens=5,
            use_cache="paged")._data)
        np.testing.assert_array_equal(out_b_first, out_b_reused)


class TestRetraceGuard:
    """ISSUE acceptance: the compile-count probe shows 1 compile for 64
    decode steps and the per-layer cache update lowers to
    dynamic-update-slice (no per-token concat growth)."""

    def test_decode_compiles_once_for_64_tokens(self):
        m = tiny_model()
        ids = np.full((1, 8), 5)
        out = m.generate(paddle.to_tensor(ids, dtype="int64"),
                         max_new_tokens=64, use_cache="dense")
        assert np.asarray(out._data).shape == (1, 64)
        (engine,) = m._generation_engines.values()
        assert engine.decode_step.trace_count == 1
        assert engine.prefill_step.trace_count == 1
        assert engine.decode_step.cache_size() in (1, -1)

    def test_paged_decode_compiles_once_for_64_tokens(self):
        m = tiny_model()
        ids = np.full((1, 8), 5)
        m.generate(paddle.to_tensor(ids, dtype="int64"),
                   max_new_tokens=64, use_cache="paged")
        (engine,) = m._generation_engines.values()
        assert engine.decode_step.trace_count == 1
        assert engine.decode_step.cache_size() in (1, -1)

    def test_prefill_buckets_bound_compiles(self):
        """Prompts inside one bucket share a prefill program; a prompt
        in a new bucket adds exactly one more compile, and decode never
        recompiles across any of it."""
        from paddle_tpu.jit.decode_step import GenerationEngine

        m = tiny_model()
        eng = GenerationEngine(m, kind="dense", batch=1, max_len=40)
        for s in (9, 10):       # both pad to the 16 bucket
            eng.generate(np.full((1, s), 5), 2)
        assert eng.prefill_step.trace_count == 1    # same 16-bucket
        eng.generate(np.full((1, 20), 5), 2)        # 32-bucket
        assert eng.prefill_step.trace_count == 2
        assert eng.decode_step.trace_count == 1     # decode never again

    def test_prompt_between_largest_bucket_and_max_len(self):
        """A prompt longer than the largest power-of-two bucket but
        within max_len is in capacity and must prefill (review fix:
        the bucket list always covers max_len)."""
        from paddle_tpu.jit.decode_step import GenerationEngine

        m = tiny_model()
        eng = GenerationEngine(m, kind="dense", batch=1, max_len=50)
        out = eng.generate(np.full((1, 40), 5), 10)   # 40 > bucket 32
        assert np.asarray(out._data).shape == (1, 10)

    def test_nearby_prompt_lengths_share_one_engine(self):
        """max_len rounds up to a shared granularity: generates with
        nearby prompt lengths reuse ONE engine (one KV cache, one
        compiled decode step) instead of keying per exact length."""
        m = tiny_model()
        for s in (8, 10, 12):
            m.generate(paddle.to_tensor(np.full((1, s), 5),
                                        dtype="int64"),
                       max_new_tokens=4)
        assert len(m._generation_engines) == 1
        (eng,) = m._generation_engines.values()
        assert eng.decode_step.trace_count == 1

    def test_dense_decode_hlo_dus_no_concat(self):
        """The decode step's HLO carries the cache via
        dynamic-update-slice; no concatenate touches the cache length
        axis (the O(seq) eager-concat anti-pattern)."""
        from paddle_tpu.jit.decode_step import (
            GenerationEngine, _split_state,
        )
        from paddle_tpu.jit.train_step import _tree_data

        m = tiny_model()
        eng = GenerationEngine(m, kind="dense", batch=2, max_len=24)
        buffers, meta = _split_state("dense",
                                     _tree_data(eng.cache.state()))
        text = eng.decode_step.lowered_text(
            eng._param_data(), buffers, meta,
            jnp.zeros((2,), jnp.int32), jax.random.PRNGKey(0))
        assert "dynamic_update_slice" in text or \
            "dynamic-update-slice" in text
        # cache max_len is 24: no concatenate may produce the grown
        # 25-length axis the O(seq) eager-concat anti-pattern would
        # (the k/v 2-stack concatenate along dim 0 is fine)
        import re

        for shape in re.findall(
                r"stablehlo\.concatenate[^\n]*->\s*tensor<([0-9x]+)x",
                text):
            dims = [int(d) for d in shape.split("x") if d.isdigit()]
            assert 24 + 1 not in dims, (
                f"decode step grew the cache axis by concat: {dims}")
        # inspecting HLO must not perturb the retrace probe
        assert eng.decode_step.trace_count == 0

    def test_engine_cache_is_lru(self):
        """The per-model engine cache (bound at 4) must evict least-
        recently-USED, not first-inserted — a hot engine survives new
        signatures (review fix)."""
        m = tiny_model()
        ids = np.full((1, 4), 7)

        def gen(temp):
            m.generate(paddle.to_tensor(ids, dtype="int64"),
                       max_new_tokens=2, do_sample=True,
                       temperature=temp, seed=0)

        for t in (1.0, 1.1, 1.2, 1.3):   # four distinct signatures
            gen(t)
        first_key = next(iter(m._generation_engines))
        gen(1.0)                          # re-hit the oldest
        gen(1.4)                          # fifth signature -> eviction
        assert first_key in m._generation_engines, (
            "LRU hit did not refresh; hot engine was evicted")
        assert len(m._generation_engines) == 4


@pytest.mark.slow
class TestLongDecode:
    def test_long_mixed_batch_decode(self):
        """Longer ragged decode crossing multiple page boundaries."""
        m = tiny_model()
        rng = np.random.default_rng(9)
        b, s, new = 4, 24, 40
        lens = np.array([24, 17, 9, 3], np.int32)
        ids = rng.integers(1, 97, (b, s))
        for i, L in enumerate(lens):
            ids[i, L:] = 0
        out, logits = m.generate(
            paddle.to_tensor(ids, dtype="int64"), max_new_tokens=new,
            use_cache="paged", seq_lens=lens, return_logits=True)
        out = np.asarray(out._data)
        logits = np.asarray(logits._data, np.float32)
        for i in range(b):
            full = np.concatenate([ids[i, :lens[i]], out[i][:-1]])
            want = np.asarray(m(paddle.to_tensor(
                full[None], dtype="int64"))._data, np.float32)[0]
            for t in (0, new // 2, new - 1):
                np.testing.assert_allclose(
                    logits[i, t], want[lens[i] - 1 + t], rtol=5e-4,
                    atol=5e-4)
