"""paddle.distributed.utils (reference distributed/utils/): launch/log
helpers + the MoE alltoall utilities. The substantive members
(global_scatter/global_gather) live in incubate.distributed.models.moe
on this build; log utils are std logging."""
from __future__ import annotations


def get_logger(log_level=20, name="root"):
    """reference log_utils.get_logger -> the shared log_helper config
    path (one formatter/propagation policy for the whole framework)."""
    from ...utils.log_helper import get_logger as _impl

    return _impl(name, level=log_level)
