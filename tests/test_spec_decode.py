"""Speculative decoding + int8 paged KV tests (ISSUE 16).

The losslessness contract, checked at every layer:

* greedy spec output is BIT-IDENTICAL to plain greedy decode on the
  dense, paged and int8-paged caches — for any draft, including a
  deliberately-mismatched random one;
* sampled spec output is DISTRIBUTION-equal to the target: a
  Monte-Carlo check of `spec_accept_sampled` against the analytic
  target distribution, plus fixed-seed token histograms engine-vs-
  engine on all three cache kinds;
* the KV "rewind" after rejection is pure bookkeeping: pool_stats
  invariants hold across heavy rejection churn and slots drain clean;
* int8-KV spec logits match fp spec logits within the documented
  tolerance;
* the retrace sentinel stays strict-clean while accept counts vary
  call to call (variable yield must be data, never a shape).

Plus the sampling-boundary satellites: top-p exactly on a cumulative-
probability edge and top-k >= vocab.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.jit.decode_step import GenerationEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM


def tiny_model(seed=0, **over):
    paddle.seed(seed)
    kw = dict(vocab_size=97, hidden_size=32, num_layers=2,
              num_attention_heads=4, max_position_embeddings=96,
              hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    kw.update(over)
    m = GPTForCausalLM(GPTConfig(**kw))
    m.eval()
    return m


def tiny_draft(seed=7, **over):
    """An INDEPENDENT small draft — different widths, different random
    weights. Losslessness must not depend on draft quality."""
    over.setdefault("hidden_size", 16)
    over.setdefault("num_layers", 1)
    over.setdefault("num_attention_heads", 2)
    return tiny_model(seed=seed, **over)


KINDS = [("dense", None), ("paged", None), ("paged", "int8")]


def _mk_engine(model, kind, quant, draft=None, k=3, **kw):
    extra = {} if quant is None else {"kv_quant": quant}
    if draft is not None:
        extra.update(draft_model=draft, spec_k=k)
    return GenerationEngine(model, kind=kind, batch=2, max_len=64,
                            **extra, **kw)


class TestGreedyParity:
    @pytest.mark.parametrize("kind,quant", KINDS)
    def test_bit_identical_to_plain_decode(self, kind, quant):
        tgt, drf = tiny_model(), tiny_draft()
        ids = np.random.default_rng(0).integers(0, 97, (2, 11))
        ref = _mk_engine(tgt, kind, quant).generate(ids, 17).numpy()
        eng = _mk_engine(tgt, kind, quant, draft=drf)
        out = eng.generate(ids, 17).numpy()
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))

    def test_logits_rows_are_the_emitted_tokens_distributions(self):
        # greedy: emitted token t must be argmax of returned logits
        # row t — i.e. logits stay aligned through accept/rollback
        tgt, drf = tiny_model(), tiny_draft()
        ids = np.random.default_rng(1).integers(0, 97, (2, 9))
        eng = _mk_engine(tgt, "paged", None, draft=drf)
        out, lg = eng.generate(ids, 11, return_logits=True)
        out, lg = np.asarray(out.numpy()), np.asarray(lg.numpy())
        assert lg.shape == (2, 11, 97)
        np.testing.assert_array_equal(out, lg.argmax(-1))

    def test_strong_draft_accepts_everything(self):
        # draft == target: every proposal must be accepted, so the
        # whole generation takes ceil((mnt-1)/(k+1)) spec dispatches
        tgt = tiny_model()
        eng = _mk_engine(tgt, "paged", None, draft=tgt, k=3)
        ids = np.random.default_rng(2).integers(0, 97, (2, 7))
        ref = _mk_engine(tgt, "paged", None).generate(ids, 13).numpy()
        out = eng.generate(ids, 13).numpy()
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
        d = eng.spec_step._sentinel.stats()["calls"]
        assert d == -(-(13 - 1) // (3 + 1))   # 3 dispatches, not 12


class TestRetraceSentinel:
    def test_variable_accept_counts_one_executable(self):
        tgt, drf = tiny_model(), tiny_draft()
        eng = _mk_engine(tgt, "paged", "int8", draft=drf)
        rng = np.random.default_rng(3)
        for mnt in (5, 9, 16):
            eng.generate(rng.integers(0, 97, (2, 8)), mnt)
        assert eng.spec_step.trace_count == 1
        st = eng.spec_step.retrace_stats()
        assert st["unexpected"] == 0, st
        assert st["signatures"] == 1, st


class TestSampledDistribution:
    def test_rejection_sampling_matches_target_analytically(self):
        # Monte-Carlo over seeds: the (accept | correct) output of
        # spec_accept_sampled must be distributed as the TARGET row,
        # for a draft that disagrees with it substantially. The
        # function is batched, so all n trials run as ONE call with
        # the trial index as the batch dimension (each row gets its
        # own seed, i.e. its own independent RNG stream).
        from paddle_tpu.nn.functional.sampling import (
            spec_accept_sampled, truncated_probs)

        v, k, n = 7, 2, 4000
        rng = np.random.default_rng(0)
        p1 = truncated_probs(jnp.asarray(
            rng.standard_normal((1, k + 1, v)), jnp.float32))
        q1 = truncated_probs(jnp.asarray(
            rng.standard_normal((1, k, v)), jnp.float32))
        p = jnp.broadcast_to(p1, (n, k + 1, v))
        q = jnp.broadcast_to(q1, (n, k, v))
        seeds = jnp.arange(n, dtype=jnp.uint32)

        # draw each trial's proposals from q with per-trial streams,
        # then accept/correct — exactly what the spec step does
        def draw(j):
            keys = jax.vmap(jax.random.PRNGKey)(seeds * 7 + 11 + j)
            return jax.vmap(jax.random.categorical)(
                keys, jnp.broadcast_to(jnp.log(q1[0, j]), (n, v)))

        prop = jnp.stack([draw(j) for j in range(k)], 1) \
            .astype(jnp.int32)
        a, nxt = spec_accept_sampled(p, q, prop, seeds,
                                     jnp.zeros((n,), jnp.uint32))
        # the FIRST emitted token per trial: prop[:,0] if a>0 else
        # the correction — must be ~ p[0]
        first = np.asarray(jnp.where(a > 0, prop[:, 0], nxt))
        emp = np.bincount(first, minlength=v) / n
        ref = np.asarray(p1[0, 0])
        tv = 0.5 * np.abs(emp - ref).sum()
        assert tv < 0.05, (tv, emp, ref)

    @pytest.mark.parametrize("kind,quant", KINDS)
    def test_engine_token_histograms_match_plain(self, kind, quant):
        # fixed-seed histograms: the first spec-emitted token (position
        # 1) over many seeds vs the plain sampled engine's. Same
        # PrefillStep stream means token 0 is identical, so position 1
        # compares like-for-like conditionals.
        tgt = tiny_model(vocab_size=13)
        drf = tiny_draft(vocab_size=13)
        ids = np.random.default_rng(4).integers(0, 13, (2, 6))
        skw = dict(do_sample=True, temperature=0.9, top_k=8, top_p=0.9)
        plain = _mk_engine(tgt, kind, quant, **skw)
        spec = _mk_engine(tgt, kind, quant, draft=drf, **skw)
        n = 150
        hp = np.zeros((13,), np.int64)
        hs = np.zeros((13,), np.int64)
        for s in range(n):
            p = np.asarray(plain.generate(ids, 2, seed=s).numpy())
            sp = np.asarray(spec.generate(ids, 2, seed=s).numpy())
            np.testing.assert_array_equal(p[:, 0], sp[:, 0])
            hp += np.bincount(p[:, 1], minlength=13)
            hs += np.bincount(sp[:, 1], minlength=13)
        tv = 0.5 * np.abs(hp / hp.sum() - hs / hs.sum()).sum()
        assert tv < 0.12, (tv, hp, hs)


class TestKVRewindInvariants:
    def test_pool_stats_stable_across_rejection_churn(self):
        tgt, drf = tiny_model(), tiny_draft()
        eng = _mk_engine(tgt, "paged", "int8", draft=drf)
        ids = np.random.default_rng(5).integers(0, 97, (2, 9))
        base = eng.cache.pool_stats()
        assert base["kv_dtype"] == "int8"
        for _ in range(3):
            eng.generate(ids, 14)
            st = eng.cache.pool_stats()
            # every page back in the pool, none leaked to rollbacks
            assert st["used_pages"] + st["free_pages"] \
                == st["total_pages"]
            assert st["used_pages"] == 0
            assert st["free_pages"] == base["free_pages"]
        # draft pool drains too (shared page-table geometry)
        dst = eng.draft_cache.pool_stats()
        assert dst["used_pages"] + dst["free_pages"] \
            == dst["total_pages"]

    def test_failed_generate_rebuilds_both_caches(self):
        tgt, drf = tiny_model(), tiny_draft()
        eng = _mk_engine(tgt, "paged", None, draft=drf)
        ids = np.random.default_rng(6).integers(0, 97, (2, 8))
        c0, d0 = eng.cache, eng.draft_cache
        with pytest.raises(ValueError):
            eng.generate(ids, 1000)   # exceeds max_len
        # donated-buffer recovery replaces only on mid-loop failure;
        # the capacity check fires before any dispatch
        assert eng.cache is c0 and eng.draft_cache is d0
        out = eng.generate(ids, 9)
        assert np.asarray(out.numpy()).shape == (2, 9)


class TestEngineReuse:
    """Engine reuse must be deterministic: every compiled step indexes
    the batch as row i == slot i, so the free-all/reallocate cycle at
    the top of each generate() call has to hand slots back in identity
    order. A LIFO free list permuted them on the SECOND call, silently
    crossing rows between sequences (and driving the spec loop's host
    seq_lens bookkeeping past the page budget)."""

    def test_allocate_lowest_free_slot_any_free_order(self):
        from paddle_tpu.inference.kv_cache import PagedKVCache
        cache = PagedKVCache(1, 1, 8, 9, 4, 3, 2)
        assert [cache.allocate(4) for _ in range(3)] == [0, 1, 2]
        for order in ([0, 1, 2], [2, 1, 0], [1, 2, 0]):
            for s in order:
                cache.free(s)
            assert [cache.allocate(4) for _ in range(3)] == [0, 1, 2]

    @pytest.mark.parametrize("quant", [None, "int8"])
    def test_repeat_generate_bit_identical(self, quant):
        tgt, drf = tiny_model(), tiny_draft()
        eng = _mk_engine(tgt, "paged", quant, draft=drf)
        ids = np.random.default_rng(21).integers(0, 97, (2, 9))
        # ragged lengths: the row<->slot crossing only shows up when
        # the sequences are distinguishable
        reps = [np.asarray(eng.generate(ids, 12,
                                        seq_lens=[9, 6]).numpy())
                for _ in range(3)]
        assert (reps[0] == reps[1]).all() and (reps[0] == reps[2]).all()


class TestInt8SpecLogits:
    def test_int8_spec_logits_close_to_fp(self):
        tgt, drf = tiny_model(), tiny_draft()
        ids = np.random.default_rng(7).integers(0, 97, (2, 9))
        _, lf = _mk_engine(tgt, "paged", None, draft=drf).generate(
            ids, 9, return_logits=True)
        _, lq = _mk_engine(tgt, "paged", "int8", draft=drf).generate(
            ids, 9, return_logits=True)
        diff = np.abs(np.asarray(lf.numpy()) - np.asarray(lq.numpy()))
        # documented int8-KV tolerance for this tiny config: per-row
        # symmetric scales keep decode logits within a few 1e-2
        assert float(diff.max()) < 5e-2, float(diff.max())


class TestServingSpec:
    def _engines(self, **kw):
        from paddle_tpu.serving.engine import ServingEngine

        tgt = tiny_model(max_position_embeddings=256)
        return ServingEngine(tgt, max_slots=4, max_len=96,
                             page_size=16, chunk_size=16, **kw), tgt

    def test_greedy_parity_and_spec_metrics(self):
        drf = tiny_draft(max_position_embeddings=256)
        rng = np.random.default_rng(8)
        prompts = [rng.integers(0, 97, (n,)) for n in (5, 11, 23, 8)]
        ref_eng, tgt = self._engines()
        hs = [ref_eng.submit(p, 12) for p in prompts]
        ref_eng.run()
        ref = [list(h.output_tokens) for h in hs]
        for quant in (None, "int8"):
            eng, _ = self._engines(draft_model=drf, spec_k=3,
                                   kv_quant=quant)
            hs = [eng.submit(p, 12) for p in prompts]
            eng.run()
            assert [list(h.output_tokens) for h in hs] == ref
            snap = eng.metrics_snapshot()
            assert snap["spec_dispatches"] > 0
            assert snap["spec_emitted"] >= snap["spec_dispatches"]
            assert 0.0 <= snap["spec_accept_rate"] <= 1.0
            assert snap["spec_tokens_per_dispatch"] >= 1.0
            # spec gauges are scraped on /metrics (names are
            # prometheus-sanitized: dots become underscores)
            txt = eng.metrics_text()
            assert "serving_spec_accept_rate" in txt
            assert "serving_spec_tokens_per_dispatch" in txt
            # one decode executable across variable accept counts
            assert eng.compile_counts()["decode_traces"] == 1
            assert eng.retrace_stats()["spec"]["unexpected"] == 0
            lk = eng.leak_check()
            assert lk["free_pages"] == lk["total_pages"]

    def test_decode_span_carries_yield_attribution(self):
        drf = tiny_draft(max_position_embeddings=256)
        eng, _ = self._engines(draft_model=drf, spec_k=3)
        h = eng.submit(np.arange(1, 9, dtype=np.int32), 8)
        eng.run()
        trace = eng.request_trace(h.request.rid)
        stack, bursts = [trace], []
        while stack:
            s = stack.pop()
            stack.extend(s.children)
            if s.name == "decode_burst":
                bursts.append(s)
        assert bursts, "no decode_burst spans on the request trace"
        for sp in bursts:
            assert sp.attrs.get("spec") is True
            # proposed = cap-usable proposals (< spec_k on the tail
            # dispatch of a request), accepted never exceeds it
            assert 0 <= sp.attrs.get("proposed") <= 3
            assert 0 <= sp.attrs.get("accepted") \
                <= sp.attrs.get("proposed")
            assert 1 <= sp.attrs.get("yielded") \
                <= sp.attrs.get("proposed") + 1

    def test_sampled_serving_deterministic_per_seed(self):
        drf = tiny_draft(max_position_embeddings=256)
        rng = np.random.default_rng(9)
        prompts = [rng.integers(0, 97, (n,)) for n in (6, 14)]

        def run():
            eng, _ = self._engines(draft_model=drf, spec_k=2,
                                   do_sample=True, temperature=0.8,
                                   top_k=16)
            hs = [eng.submit(p, 10, seed=50 + i)
                  for i, p in enumerate(prompts)]
            eng.run()
            return [list(h.output_tokens) for h in hs]

        assert run() == run()


class TestSamplingBoundaries:
    """Satellite: truncation tie-break regression tests."""

    def test_top_p_exactly_on_cumulative_edge(self):
        from paddle_tpu.nn.functional.sampling import truncated_probs

        # probs 0.5/0.25/0.125/0.125; p=0.75 lands exactly on the edge
        # after two tokens -> `before < p` keeps exactly those two
        logits = jnp.log(jnp.asarray([[0.5, 0.25, 0.125, 0.125]]))
        probs = np.asarray(truncated_probs(logits, top_p=0.75))
        np.testing.assert_allclose(
            probs[0], [2 / 3, 1 / 3, 0.0, 0.0], atol=1e-6)
        # and a p just past the edge admits the boundary token(s) —
        # ties at the boundary logit BOTH survive (threshold cut)
        probs = np.asarray(truncated_probs(logits, top_p=0.76))
        assert probs[0, 2] > 0 and probs[0, 3] > 0

    def test_top_p_never_empty(self):
        from paddle_tpu.nn.functional.sampling import truncated_probs

        logits = jnp.asarray([[3.0, 0.0, -1.0]])
        probs = np.asarray(truncated_probs(logits, top_p=1e-9))
        # the top token's exclusive prefix mass is 0 < p: always kept
        np.testing.assert_allclose(probs[0], [1.0, 0.0, 0.0],
                                   atol=1e-6)

    def test_top_k_at_least_vocab_keeps_everything(self):
        from paddle_tpu.nn.functional.sampling import truncated_probs

        logits = jnp.asarray([[0.3, -0.7, 1.1, 0.0]])
        ref = np.asarray(truncated_probs(logits))
        for k in (4, 5, 100):
            got = np.asarray(truncated_probs(logits, top_k=k))
            np.testing.assert_allclose(got, ref, atol=1e-7)

    def test_top_k_boundary_ties_survive(self):
        from paddle_tpu.nn.functional.sampling import truncated_probs

        # k=2 with a tie at the 2nd value: the threshold cut keeps
        # BOTH tied tokens (documented tie-break rule)
        logits = jnp.asarray([[2.0, 1.0, 1.0, 0.0]])
        probs = np.asarray(truncated_probs(logits, top_k=2))
        assert probs[0, 1] > 0 and probs[0, 2] > 0
        assert probs[0, 3] == 0


class TestSpecValidation:
    def test_dense_kv_quant_rejected(self):
        with pytest.raises(ValueError, match="paged"):
            GenerationEngine(tiny_model(), kind="dense", max_len=64,
                             kv_quant="int8")

    def test_vocab_mismatch_rejected(self):
        with pytest.raises(ValueError, match="vocab"):
            GenerationEngine(tiny_model(), kind="paged", max_len=64,
                             draft_model=tiny_draft(vocab_size=31))

    def test_spec_k_floor(self):
        with pytest.raises(ValueError, match="spec_k"):
            GenerationEngine(tiny_model(), kind="paged", max_len=64,
                             draft_model=tiny_draft(), spec_k=0)
