"""Dynamic loss scaling.

Reference parity: AmpScaler / GradScaler (python/paddle/amp/grad_scaler.py:62,
645): scale -> backward -> unscale (found_inf via check_finite_and_unscale
kernel) -> conditional step -> scale update. The unscale is ONE fused XLA
program over all grads (check_finite_and_unscale parity — not a per-param
dispatch loop), and found_inf stays ON DEVICE until the step decision:
exactly one scalar readback per step, at the last possible moment
(SURVEY.md §7 hard-parts).

Compiled path: pass the scaler to TrainStep/FusedScanTrainStep/
ShardedFusedScanTrainStep (``scaler=``) and the same semantics trace
into the step program itself (jit/nonfinite_guard.py) — found_inf never
reaches the host at all and the scale lives as traced state.
"""
from __future__ import annotations

import enum

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor


class OptimizerState(enum.Enum):
    INIT = 0
    UNSCALED = 1
    STEPPED = 2


@jax.jit
def _fused_unscale(grads, inv):
    """One XLA program: every grad unscaled + a single fused finiteness
    reduction. Retraces only per grad-structure (cached by pytree)."""
    finite = [jnp.isfinite(g).all()
              if jnp.issubdtype(g.dtype, jnp.floating) else jnp.bool_(True)
              for g in grads]
    found = ~jnp.stack(finite).all() if finite else jnp.bool_(False)
    out = [(g.astype(jnp.float32) * inv).astype(
        jnp.float32 if g.dtype == jnp.float32 else g.dtype)
        for g in grads]
    return out, found


class AmpScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0**15, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._use_dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._opt_states = {}

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._use_dynamic

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def _unscale(self, optimizer):
        """check_finite_and_unscale parity: ONE fused XLA program over
        all grads (unscale + finiteness reduction). found_inf stays a
        device scalar here — the host readback happens once, at the
        step/minimize decision."""
        if not self._enable:
            return
        if self._opt_states.get(id(optimizer)) == OptimizerState.UNSCALED:
            return
        params = [p for p in (optimizer._parameter_list or [])
                  if p.grad is not None]
        if params:
            inv = jnp.float32(1.0 / float(self._scale))
            out, found = _fused_unscale([p.grad._data for p in params],
                                        inv)
            for p, g in zip(params, out):
                p.grad._data = g
            self._found_inf = found     # device scalar, NOT synced yet
        else:
            self._found_inf = False
        self._opt_states[id(optimizer)] = OptimizerState.UNSCALED

    def unscale_(self, optimizer):
        return self._unscale(optimizer)

    def _found(self):
        """The single device->host readback of found_inf."""
        self._found_inf = bool(self._found_inf)
        return self._found_inf

    def minimize(self, optimizer, loss, *args, **kwargs):
        self._unscale(optimizer)
        if not self._found():
            optimizer.step()
        self._update()
        self._opt_states.pop(id(optimizer), None)
        optimizer.clear_grad()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self._unscale(optimizer)
        if not self._found():
            optimizer.step()
        self._opt_states[id(optimizer)] = OptimizerState.STEPPED

    def update(self):
        if not self._enable:
            return
        self._update()
        self._opt_states.clear()

    def _update(self):
        if not self._use_dynamic:
            return
        if self._found():
            self._bad_steps = int(self._bad_steps) + 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(float(self._scale) * self._decr_ratio,
                                  1.0)
                self._bad_steps = 0
        else:
            self._good_steps = int(self._good_steps) + 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale = float(self._scale) * self._incr_ratio
                self._good_steps = 0

    # -- introspection ---------------------------------------------------
    def get_loss_scaling(self):
        return Tensor(float(self._scale))

    def set_init_loss_scaling(self, value):
        self._scale = float(value)

    def state_dict(self):
        # a compiled step (scaler= binding) mirrors scale/counters back
        # as DEVICE scalars; the state dict is plain host numbers so it
        # pickles and rides CheckpointManager saves unchanged
        return {
            "scale": float(self._scale),
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
            "good_steps": int(self._good_steps),
            "bad_steps": int(self._bad_steps),
            "use_dynamic_loss_scaling": bool(self._use_dynamic),
        }

    def load_state_dict(self, state):
        self._scale = float(state.get("scale", self._scale))
        self._good_steps = int(state.get("good_steps", 0))
        self._bad_steps = int(state.get("bad_steps", 0))
        self._use_dynamic = bool(state.get("use_dynamic_loss_scaling",
                                           self._use_dynamic))


class GradScaler(AmpScaler):
    """Public API (grad_scaler.py:645)."""

    pass
