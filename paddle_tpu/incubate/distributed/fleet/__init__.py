"""paddle.incubate.distributed.fleet (reference
incubate/distributed/fleet/__init__.py): recompute re-exports."""
from ....distributed.fleet.recompute import (  # noqa: F401
    recompute_hybrid,
    recompute_sequential,
)

