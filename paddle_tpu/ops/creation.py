"""Tensor creation ops.

Reference parity: python/paddle/tensor/creation.py + the nullary/fill kernels
(paddle/phi/kernels/full_kernel.h, empty_kernel.h, arange kernel).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor, to_tensor  # noqa: F401  (re-export)
from ..framework.dtype import to_jax_dtype
from ..framework.random import default_generator
from ._dispatch import ensure_tensor, resolve_dtype


def _shape_tuple(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def zeros(shape, dtype=None):
    return Tensor._wrap(jnp.zeros(_shape_tuple(shape), resolve_dtype(dtype)))


def ones(shape, dtype=None):
    return Tensor._wrap(jnp.ones(_shape_tuple(shape), resolve_dtype(dtype)))


def full(shape, fill_value, dtype=None):
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype_j = jnp.bool_
        elif isinstance(fill_value, int):
            dtype_j = jnp.int64
        else:
            dtype_j = resolve_dtype(None)
    else:
        dtype_j = to_jax_dtype(dtype)
    return Tensor._wrap(jnp.full(_shape_tuple(shape), fill_value, dtype_j))


def empty(shape, dtype=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None):
    x = ensure_tensor(x)
    d = to_jax_dtype(dtype) if dtype is not None else x._data.dtype
    return Tensor._wrap(jnp.zeros(x._data.shape, d))


def ones_like(x, dtype=None):
    x = ensure_tensor(x)
    d = to_jax_dtype(dtype) if dtype is not None else x._data.dtype
    return Tensor._wrap(jnp.ones(x._data.shape, d))


def full_like(x, fill_value, dtype=None):
    x = ensure_tensor(x)
    d = to_jax_dtype(dtype) if dtype is not None else x._data.dtype
    return Tensor._wrap(jnp.full(x._data.shape, fill_value, d))


def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    for v in (start, end, step):
        if isinstance(v, Tensor):
            v = v.item()
    start = start.item() if isinstance(start, Tensor) else start
    end = end.item() if isinstance(end, Tensor) else end
    step = step.item() if isinstance(step, Tensor) else step
    if dtype is None:
        if all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            d = jnp.int64
        else:
            d = resolve_dtype(None)
    else:
        d = to_jax_dtype(dtype)
    return Tensor._wrap(jnp.arange(start, end, step, dtype=d))


def linspace(start, stop, num, dtype=None):
    start = start.item() if isinstance(start, Tensor) else start
    stop = stop.item() if isinstance(stop, Tensor) else stop
    return Tensor._wrap(jnp.linspace(start, stop, int(num), dtype=resolve_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None):
    return Tensor._wrap(
        jnp.logspace(float(start), float(stop), int(num), base=base, dtype=resolve_dtype(dtype))
    )


def eye(num_rows, num_columns=None, dtype=None):
    return Tensor._wrap(jnp.eye(int(num_rows), num_columns and int(num_columns), dtype=resolve_dtype(dtype)))


def diag(x, offset=0, padding_value=0):
    x = ensure_tensor(x)
    from ..framework.autograd import apply_op

    if x.ndim == 1 and padding_value != 0:
        def f(v):
            n = v.shape[0] + abs(offset)
            out = jnp.full((n, n), padding_value, v.dtype)
            return out + jnp.diag(v, k=offset) - jnp.diag(jnp.full(v.shape, padding_value, v.dtype), k=offset)
        return apply_op(f, [x], name="diag")
    return apply_op(lambda v: jnp.diag(v, k=offset), [x], name="diag")


def diagflat(x, offset=0):
    from ..framework.autograd import apply_op

    return apply_op(lambda v: jnp.diagflat(v, k=offset), [ensure_tensor(x)], name="diagflat")


def tril(x, diagonal=0):
    from ..framework.autograd import apply_op

    return apply_op(lambda v: jnp.tril(v, k=diagonal), [ensure_tensor(x)], name="tril")


def triu(x, diagonal=0):
    from ..framework.autograd import apply_op

    return apply_op(lambda v: jnp.triu(v, k=diagonal), [ensure_tensor(x)], name="triu")


def meshgrid(*args):
    tensors = [ensure_tensor(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    from ..framework.autograd import apply_op

    return list(apply_op(lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")), tensors, name="meshgrid"))


def assign(x, output=None):
    x = ensure_tensor(x)
    from ..framework.autograd import apply_op

    out = apply_op(lambda v: v + 0, [x], name="assign")
    if output is not None:
        output._inplace_from(out)
        return output
    return out


def clone(x):
    return assign(x)


def numel(x):
    return Tensor._wrap(jnp.asarray(ensure_tensor(x)._data.size, jnp.int64))


# -- random creation --------------------------------------------------------

def rand(shape, dtype=None):
    key = default_generator().next_key()
    return Tensor._wrap(jax.random.uniform(key, _shape_tuple(shape), resolve_dtype(dtype)))


def randn(shape, dtype=None):
    key = default_generator().next_key()
    return Tensor._wrap(jax.random.normal(key, _shape_tuple(shape), resolve_dtype(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0):
    key = jax.random.PRNGKey(seed) if seed else default_generator().next_key()
    return Tensor._wrap(
        jax.random.uniform(key, _shape_tuple(shape), resolve_dtype(dtype), minval=min, maxval=max)
    )


def normal(mean=0.0, std=1.0, shape=None):
    if shape is None:
        if isinstance(mean, Tensor):
            shape = mean.shape
        elif isinstance(std, Tensor):
            shape = std.shape
        else:
            shape = []
    key = default_generator().next_key()
    base = jax.random.normal(key, _shape_tuple(shape), resolve_dtype(None))
    mean_v = mean._data if isinstance(mean, Tensor) else mean
    std_v = std._data if isinstance(std, Tensor) else std
    return Tensor._wrap(base * std_v + mean_v)


def randint(low=0, high=None, shape=(1,), dtype=None):
    if high is None:
        low, high = 0, low
    key = default_generator().next_key()
    d = to_jax_dtype(dtype) if dtype is not None else jnp.int64
    return Tensor._wrap(jax.random.randint(key, _shape_tuple(shape), low, high, dtype=d))


def randint_like(x, low=0, high=None, dtype=None):
    x = ensure_tensor(x)
    return randint(low, high, x.shape, dtype or x.dtype)


def randperm(n, dtype=None):
    key = default_generator().next_key()
    d = to_jax_dtype(dtype) if dtype is not None else jnp.int64
    return Tensor._wrap(jax.random.permutation(key, int(n)).astype(d))


def bernoulli(x):
    x = ensure_tensor(x)
    key = default_generator().next_key()
    return Tensor._wrap(
        jax.random.bernoulli(key, np.asarray(x._data)).astype(x._data.dtype)
        if False
        else (jax.random.uniform(key, x._data.shape) < x._data).astype(x._data.dtype)
    )


def multinomial(x, num_samples=1, replacement=False):
    x = ensure_tensor(x)
    key = default_generator().next_key()
    logits = jnp.log(jnp.maximum(x._data, 1e-30))
    if replacement or num_samples == 1:
        out = jax.random.categorical(key, logits, axis=-1, shape=(*x._data.shape[:-1], num_samples) if x.ndim > 1 else (num_samples,))
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(key, logits.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor._wrap(out.astype(jnp.int64))


def poisson(x, name=None):
    """Per-element Poisson draws with rate x (reference tensor/random.py
    poisson, kernel paddle/phi/kernels/poisson_kernel.h)."""
    x = ensure_tensor(x)
    key = default_generator().next_key()
    rate = x._data if jnp.issubdtype(x._data.dtype, jnp.floating) \
        else x._data.astype(jnp.float32)
    return Tensor._wrap(
        jax.random.poisson(key, rate, x._data.shape)
        .astype(x._data.dtype))


def create_tensor(dtype="float32", name=None, persistable=False):
    """reference tensor/creation.py create_tensor — an empty typed
    tensor placeholder (static-era API; eager code assigns into it)."""
    from ..framework.dtype import to_jax_dtype

    return Tensor(jnp.zeros((0,), to_jax_dtype(dtype)))
