#!/bin/bash
# axon-stripped CPU test environment (the dryrun's hermetic recipe)
env -u PYTHONPATH -u PALLAS_AXON_POOL_IPS -u PALLAS_AXON_REMOTE_COMPILE \
    -u AXON_LOOPBACK_RELAY -u AXON_POOL_SVC_OVERRIDE -u TPU_SKIP_MDS_QUERY \
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="${XLA_FLAGS:+$XLA_FLAGS }--xla_force_host_platform_device_count=8" "$@"
# Usage: tools/cpu_env.sh python -m pytest tests/ -q
# Why: a wedged axon tunnel (claim-leg kill) hangs EVERY jax backend
# init that can see the plugin; stripping the env makes
# JAX_PLATFORMS=cpu genuinely cpu-only. tests/conftest.py applies the
# same hardening in-process; this wrapper is for ad-hoc scripts.
