"""Regression tests for the round-4 advisor findings (ADVICE.md):
matrix_nms semantics live in test_vision_ops.py; these cover the four
lows — to_static TypeError latch, eager-collective multi-mesh cache,
[N, 1] label acceptance in margin/hsigmoid losses, and 1-element
list args in the 1-D pooling lifts."""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


class TestToStaticTypeErrorNoLatch:
    def test_bad_call_does_not_disable_compilation(self):
        from paddle_tpu import jit

        calls = {"n": 0}

        @jit.to_static
        def f(x):
            calls["n"] += 1
            return x * 2 + x.shape[0]

        x = paddle.to_tensor(np.ones(4, np.float32))
        _ = f(x)
        # a genuinely mis-typed call raises (surfaced by the eager
        # re-run), but must NOT latch eager mode
        with pytest.raises(TypeError):
            f(object())
        assert not f._eager
        # later well-typed calls still hit the compiled path: the traced
        # python body does not re-run for a cache hit
        n_before = calls["n"]
        _ = f(x)
        assert calls["n"] == n_before


class TestEagerCollectiveCacheMultiMesh:
    def test_alternating_groups_keep_entries(self):
        import jax
        import numpy as _np
        from jax.sharding import Mesh

        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import collective as C
        from paddle_tpu.distributed import env as denv

        prev = denv.get_mesh() if denv.is_initialized() else None
        denv.set_mesh(Mesh(_np.array(jax.devices("cpu")[:8]), ("dp",)))
        try:
            self._check(dist, C)
        finally:
            # ALWAYS drop the test mesh: leaving it ambient poisons later
            # eager runs (jaxlib 0.4.x segfaults reusing executables over
            # the dead mesh in test_auto_tuner's engine)
            if prev is not None:
                denv.set_mesh(prev)
            else:
                denv.reset()

    def _check(self, dist, C):
        g_sub = dist.new_group(ranks=[0, 1, 2, 3])
        x = paddle.to_tensor(np.arange(8, dtype=np.float32))
        C._eager_fn_cache.clear()
        dist.all_reduce(x)
        dist.all_reduce(paddle.to_tensor(np.ones(4, np.float32)),
                        group=g_sub)
        n = len(C._eager_fn_cache)
        assert n >= 2
        # alternating between the groups must not evict each other
        for _ in range(3):
            dist.all_reduce(paddle.to_tensor(np.ones(8, np.float32)))
            dist.all_reduce(paddle.to_tensor(np.ones(4, np.float32)),
                            group=g_sub)
        assert len(C._eager_fn_cache) == n


class TestLabelShape:
    def test_margin_cross_entropy_2d_label(self):
        rng = np.random.default_rng(0)
        logits = paddle.to_tensor(
            np.clip(rng.standard_normal((6, 10)), -0.99, 0.99)
            .astype(np.float32))
        y1 = paddle.to_tensor(rng.integers(0, 10, (6,)), dtype="int64")
        y2 = paddle.to_tensor(np.asarray(y1._data).reshape(6, 1))
        a = float(F.margin_cross_entropy(logits, y1))
        b = float(F.margin_cross_entropy(logits, y2))
        np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_hsigmoid_2d_label(self):
        rng = np.random.default_rng(1)
        x = paddle.to_tensor(rng.standard_normal((5, 8)).astype(np.float32))
        w = paddle.to_tensor(rng.standard_normal((9, 8)).astype(np.float32))
        y1 = paddle.to_tensor(rng.integers(0, 10, (5,)), dtype="int64")
        y2 = paddle.to_tensor(np.asarray(y1._data).reshape(5, 1))
        a = np.asarray(F.hsigmoid_loss(x, y1, 10, w)._data)
        b = np.asarray(F.hsigmoid_loss(x, y2, 10, w)._data)
        np.testing.assert_allclose(a, b, rtol=1e-6)


class TestPooling1dListArgs:
    def test_lp_pool1d_list_args(self):
        x = paddle.to_tensor(
            np.arange(24, dtype=np.float32).reshape(1, 2, 12))
        a = np.asarray(F.lp_pool1d(x, 2.0, 3, stride=2, padding=1)._data)
        b = np.asarray(F.lp_pool1d(x, 2.0, [3], stride=[2],
                                   padding=[1])._data)
        np.testing.assert_allclose(a, b)

    def test_max_unpool1d_list_args(self):
        x = paddle.to_tensor(
            np.asarray([[[5.0, 7.0, 9.0]]], np.float32))
        idx = paddle.to_tensor(np.asarray([[[1, 3, 5]]], np.int32))
        a = np.asarray(F.max_unpool1d(x, idx, 2)._data)
        b = np.asarray(F.max_unpool1d(x, idx, [2], stride=[2],
                                      padding=[0])._data)
        np.testing.assert_allclose(a, b)
