"""Vision datasets.

Reference parity: python/paddle/vision/datasets/ (MNIST, FashionMNIST,
Cifar10/100, Flowers). This environment has no network egress, so datasets
load from local files when present (same IDX/pickle formats as the
reference) and otherwise fall back to deterministic synthetic data with the
correct shapes/dtypes — keeping `paddle.Model` pipelines runnable
end-to-end (BASELINE configs 1-2 exercise the loader, not the pixels).
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset

_DEFAULT_ROOT = os.path.expanduser("~/.cache/paddle_tpu/datasets")


class MNIST(Dataset):
    """Reference datasets/mnist.py — IDX file format or synthetic."""

    NUM_CLASSES = 10
    IMAGE_SHAPE = (28, 28)

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode.lower()
        self.transform = transform
        images, labels = self._load(image_path, label_path)
        self.images, self.labels = images, labels
        self.dtype = "float32"

    def _load(self, image_path, label_path):
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                images = np.frombuffer(f.read(), np.uint8).reshape(n, rows,
                                                                   cols)
            with gzip.open(label_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                labels = np.frombuffer(f.read(), np.uint8)
            return images, labels
        n = 60000 if self.mode == "train" else 10000
        n = min(n, 4096)  # synthetic fallback kept small
        rng = np.random.RandomState(0 if self.mode == "train" else 1)
        images = rng.randint(0, 256, (n,) + self.IMAGE_SHAPE, dtype=np.uint8)
        labels = rng.randint(0, self.NUM_CLASSES, (n,), dtype=np.int64)
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx]
        label = np.asarray([self.labels[idx]], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)[None] / 255.0
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """Reference datasets/cifar.py — pickled batches or synthetic."""

    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode.lower()
        self.transform = transform
        if data_file and os.path.exists(data_file):
            import pickle
            import tarfile

            images, labels = [], []
            with tarfile.open(data_file) as tf:
                names = [m for m in tf.getmembers()
                         if ("data_batch" in m.name if self.mode == "train"
                             else "test_batch" in m.name)]
                for m in names:
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    images.append(d[b"data"])
                    labels.extend(d.get(b"labels", d.get(b"fine_labels")))
            self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
            self.labels = np.asarray(labels, np.int64)
        else:
            n = 2048
            rng = np.random.RandomState(0 if self.mode == "train" else 1)
            self.images = rng.randint(0, 256, (n, 3, 32, 32), dtype=np.uint8)
            self.labels = rng.randint(0, self.NUM_CLASSES, (n,),
                                      dtype=np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].transpose(1, 2, 0)  # HWC for transforms
        label = np.asarray([self.labels[idx]], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.transpose(2, 0, 1).astype(np.float32) / 255.0
        return img, label

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class DatasetFolder(Dataset):
    """Reference datasets/folder.py — directory-per-class image tree."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        exts = extensions or (".png", ".jpg", ".jpeg", ".npy")
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(exts):
                    self.samples.append((os.path.join(cdir, fname),
                                         self.class_to_idx[c]))
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image

            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError as e:
            raise RuntimeError("PIL not available; use .npy images") from e

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


ImageFolder = DatasetFolder


class _DownloadDataset(Dataset):
    """Corpus-downloading dataset (zero egress): construction raises
    with guidance; the class exists for API parity."""

    def __init__(self, *a, **k):
        raise RuntimeError(
            f"paddle.vision.datasets.{type(self).__name__} downloads "
            "its archive; this environment has no network egress — "
            "point DatasetFolder/paddle.io.Dataset at local files")


class Flowers(_DownloadDataset):
    pass


class VOC2012(_DownloadDataset):
    pass
