"""TrainStep whole-step compilation tests (paddle_tpu.jit.train_step).

Covers the central architectural bet (SURVEY.md §7 hard parts): one XLA
program per train step, stable across steps (no retrace), loss decreasing,
state threading (params/accumulators/step count/RNG offset).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.optimizer as popt
from paddle_tpu.jit import TrainStep, to_static
from paddle_tpu.models import (
    GPTForCausalLM, GPTPretrainingCriterion, GPTConfig,
)


def tiny_cfg(**kw):
    base = dict(vocab_size=128, hidden_size=64, num_layers=2,
                num_attention_heads=4, max_position_embeddings=32)
    base.update(kw)
    return GPTConfig(**base)


def make_batch(cfg, b=2, s=16, seed=0):
    rng = np.random.default_rng(seed)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (b, s)), dtype="int64")
    labels = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (b, s)), dtype="int64")
    return ids, labels


def build_step(cfg, opt_cls=popt.AdamW, **opt_kw):
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    opt = opt_cls(learning_rate=1e-3, parameters=model.parameters(), **opt_kw)
    step = TrainStep(model, lambda m, i, l: crit(m(i), l), opt)
    return model, opt, step


class TestTrainStep:
    def test_loss_decreases(self):
        cfg = tiny_cfg()
        model, opt, step = build_step(cfg)
        ids, labels = make_batch(cfg)
        losses = [float(step(ids, labels)) for _ in range(5)]
        assert losses[-1] < losses[0]

    def test_single_compile(self):
        """State avals must be stable: the executable count must not GROW
        after the first call (growth = aval drift retrace). Asserted as
        no-growth rather than == 1 because jax's global jit cache may
        EVICT entries under a full-suite load (observed at 850+ tests:
        cache_size 0 right after successful calls), which is not the
        regression this test guards."""
        cfg = tiny_cfg()
        model, opt, step = build_step(cfg, multi_precision=True)
        model.bfloat16()
        ids, labels = make_batch(cfg)
        step(ids, labels)
        after_first = step._jitted._cache_size()
        for _ in range(2):
            step(ids, labels)
        assert step._jitted._cache_size() <= max(after_first, 1)

    def test_step_count_advances(self):
        cfg = tiny_cfg()
        model, opt, step = build_step(cfg)
        ids, labels = make_batch(cfg)
        step(ids, labels)
        step(ids, labels)
        assert int(opt._step_count) == 2

    def test_matches_eager(self):
        """Compiled step must produce the same params as the eager path."""
        cfg = tiny_cfg(hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
        paddle.seed(7)
        m1, o1, step = build_step(cfg)
        paddle.seed(7)
        m2 = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion()
        o2 = popt.AdamW(learning_rate=1e-3, parameters=m2.parameters())
        ids, labels = make_batch(cfg)
        for _ in range(2):
            l1 = step(ids, labels)
        for _ in range(2):
            l2 = crit(m2(ids), labels)
            l2.backward()
            o2.step()
            o2.clear_grad()
        np.testing.assert_allclose(float(l1), float(l2), rtol=2e-4)
        p1 = list(m1.parameters())[0].numpy()
        p2 = list(m2.parameters())[0].numpy()
        np.testing.assert_allclose(p1, p2, rtol=2e-3, atol=2e-5)

    def test_recompute_matches(self):
        cfg = tiny_cfg(hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
        paddle.seed(3)
        m1, o1, s1 = build_step(cfg)
        paddle.seed(3)
        m2, o2, s2 = build_step(tiny_cfg(hidden_dropout_prob=0.0,
                                         attention_dropout_prob=0.0,
                                         use_recompute=True))
        ids, labels = make_batch(cfg)
        l1 = s1(ids, labels)
        l2 = s2(ids, labels)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)

    def test_no_float64_creep(self):
        """x64 mode is enabled for float64 parity; the train step must stay
        in f32/bf16 (TPU has no fast f64)."""
        import jax.numpy as jnp

        cfg = tiny_cfg()
        model, opt, step = build_step(cfg, multi_precision=True)
        model.bfloat16()
        ids, labels = make_batch(cfg)
        step(ids, labels)
        state = step._extract_state()
        import jax

        for leaf in jax.tree.leaves(state):
            assert leaf.dtype not in (jnp.float64, jnp.complex128), leaf.dtype


class TestToStatic:
    def test_function(self):
        @to_static
        def f(x, y):
            return x * y + 2

        out = f(paddle.to_tensor([1.0, 2.0]), paddle.to_tensor([3.0, 4.0]))
        np.testing.assert_allclose(out.numpy(), [5.0, 10.0])

    def test_layer_follows_param_updates(self):
        import paddle_tpu.nn as nn

        layer = nn.Linear(4, 2)
        layer_static = to_static(layer)
        x = paddle.to_tensor(np.ones((1, 4), np.float32))
        y1 = layer_static(x).numpy()
        layer.weight.set_value(layer.weight.numpy() * 2.0)
        layer.bias.set_value(layer.bias.numpy() + 1.0)
        y2 = layer_static(x).numpy()
        np.testing.assert_allclose(y2, y1 * 2.0 + 1.0, rtol=1e-6)


class TestAccumulateSteps:
    def test_accumulation_matches_full_batch(self):
        """TrainStep(accumulate_steps=N) == one full-batch step (same total
        gradient; mean-loss scaling)."""
        cfg = tiny_cfg(hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
        paddle.seed(41)
        m1, o1, s1 = build_step(cfg)
        paddle.seed(41)
        m2 = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion()
        o2 = popt.AdamW(learning_rate=1e-3, parameters=m2.parameters())
        s2 = TrainStep(m2, lambda m, i, l: crit(m(i), l), o2,
                       accumulate_steps=2)
        ids, labels = make_batch(cfg, b=4)
        l1 = s1(ids, labels)
        l2 = s2(ids, labels)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
        for p1, p2 in zip(m1.parameters(), m2.parameters()):
            np.testing.assert_allclose(
                np.asarray(p1._data), np.asarray(p2._data),
                rtol=2e-4, atol=1e-6)

    def test_single_executable(self):
        cfg = tiny_cfg()
        model = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion()
        opt = popt.AdamW(learning_rate=1e-3, parameters=model.parameters())
        step = TrainStep(model, lambda m, i, l: crit(m(i), l), opt,
                         accumulate_steps=4)
        ids, labels = make_batch(cfg, b=8)
        for _ in range(3):
            step(ids, labels)
        assert step._jitted._cache_size() == 1


class TestOptimizerProtocol:
    """The traced-step protocol (optimizer.py): a USER-SUBCLASSED optimizer
    that overrides step() and _append_optimize_op works under TrainStep —
    no monkeypatching of get_lr/_set_accumulator/_write_param anywhere."""

    def test_custom_optimizer_subclass(self):
        import jax.numpy as jnp
        from paddle_tpu.optimizer.optimizer import Optimizer

        class SignSGD(Optimizer):
            """Custom rule with its own accumulator and an overridden
            step() that adds a grad-norm running stat."""

            def __init__(self, **kw):
                super().__init__(**kw)
                self.step_calls = 0

            def _append_optimize_op(self, p, g):
                ema = self._get_accumulator("sign_ema", p)
                ema_new = 0.9 * ema + 0.1 * jnp.sign(g)
                self._set_accumulator("sign_ema", p, ema_new)
                lr = self._cur_lr()   # must see the frozen traced lr
                self._write_param(p, self._param_value(p) - lr * ema_new)

            def step(self):
                self.step_calls += 1
                super().step()

        cfg = tiny_cfg()
        model = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion()
        opt = SignSGD(learning_rate=1e-3, parameters=model.parameters())
        step = TrainStep(model, lambda m, i, l: crit(m(i), l), opt)
        ids, labels = make_batch(cfg)
        losses = [float(step(ids, labels)) for _ in range(3)]
        assert np.all(np.isfinite(losses))
        assert losses[-1] < losses[0]
        assert step._jitted._cache_size() == 1
        # the overridden step() ran during the single whole-step trace
        # (later calls replay the compiled program — the architecture)
        assert opt.step_calls == 1
        # the custom accumulator is threaded state: nonzero after steps
        ema_store = opt._accumulators["sign_ema"]
        assert any(float(jnp.abs(v).sum()) > 0 for v in ema_store.values())

    def test_lr_frozen_restores(self):
        opt = popt.SGD(learning_rate=0.5, parameters=[])
        with opt.lr_frozen(0.25):
            assert opt.get_lr() == 0.25
        assert opt.get_lr() == 0.5
