"""Activation functionals (python/paddle/nn/functional/activation.py parity;
reference kernels paddle/phi/kernels/activation_kernel.h).

All are single-HLO elementwise ops that XLA fuses into surrounding matmuls.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops._dispatch import unary, binary, ensure_tensor


def relu(x, name=None):
    return unary(jax.nn.relu, x, "relu")


def relu_(x, name=None):
    out = relu(x)
    x._inplace_from(out)
    return x


def relu6(x, name=None):
    return unary(jax.nn.relu6, x, "relu6")


def sigmoid(x, name=None):
    return unary(jax.nn.sigmoid, x, "sigmoid")


def tanh(x, name=None):
    return unary(jnp.tanh, x, "tanh")


def gelu(x, approximate=False, name=None):
    return unary(lambda v: jax.nn.gelu(v, approximate=approximate), x, "gelu")


def silu(x, name=None):
    return unary(jax.nn.silu, x, "silu")


def swish(x, name=None):
    return unary(jax.nn.silu, x, "swish")


def mish(x, name=None):
    return unary(lambda v: v * jnp.tanh(jax.nn.softplus(v)), x, "mish")


def hardswish(x, name=None):
    return unary(lambda v: v * jnp.clip(v + 3, 0, 6) / 6, x, "hardswish")


def hardsigmoid(x, slope=1.0 / 6, offset=0.5, name=None):
    return unary(lambda v: jnp.clip(slope * v + offset, 0, 1), x, "hardsigmoid")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return unary(lambda v: jnp.clip(v, min, max), x, "hardtanh")


def leaky_relu(x, negative_slope=0.01, name=None):
    return unary(lambda v: jnp.where(v >= 0, v, negative_slope * v), x, "leaky_relu")


def elu(x, alpha=1.0, name=None):
    return unary(lambda v: jax.nn.elu(v, alpha=alpha), x, "elu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return unary(lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)), x, "selu")


def celu(x, alpha=1.0, name=None):
    return unary(lambda v: jax.nn.celu(v, alpha=alpha), x, "celu")


def prelu(x, weight, data_format="NCHW", name=None):
    def f(v, w):
        if w.size == 1:
            wv = w.reshape(())
        else:
            shape = [1] * v.ndim
            ch_axis = 1 if data_format[1] == "C" else v.ndim - 1
            shape[ch_axis] = w.size
            wv = w.reshape(shape)
        return jnp.where(v >= 0, v, wv * v)

    return binary(f, x, ensure_tensor(weight), "prelu")


def rrelu(x, lower=1.0 / 8, upper=1.0 / 3, training=False, name=None):
    from ...framework.random import next_key

    if training:
        key = next_key()
        x = ensure_tensor(x)

        def f(v):
            slope = jax.random.uniform(key, v.shape, v.dtype, lower, upper)
            return jnp.where(v >= 0, v, slope * v)

        return unary(f, x, "rrelu")
    mid = (lower + upper) / 2
    return leaky_relu(x, mid)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return unary(
        lambda v: jnp.where(beta * v > threshold, v, jax.nn.softplus(beta * v) / beta),
        x, "softplus",
    )


def softsign(x, name=None):
    return unary(jax.nn.soft_sign, x, "softsign")


def softshrink(x, threshold=0.5, name=None):
    return unary(
        lambda v: jnp.where(v > threshold, v - threshold, jnp.where(v < -threshold, v + threshold, 0.0)),
        x, "softshrink",
    )


def hardshrink(x, threshold=0.5, name=None):
    return unary(lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), x, "hardshrink")


def tanhshrink(x, name=None):
    return unary(lambda v: v - jnp.tanh(v), x, "tanhshrink")


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return unary(lambda v: jnp.where(v > threshold, v, value), x, "thresholded_relu")


def softmax(x, axis=-1, dtype=None, name=None):
    from ...framework.dtype import to_jax_dtype

    d = to_jax_dtype(dtype) if dtype is not None else None

    def f(v):
        if d is not None:
            v = v.astype(d)
        return jax.nn.softmax(v, axis=axis)

    return unary(f, x, "softmax")


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...framework.dtype import to_jax_dtype

    d = to_jax_dtype(dtype) if dtype is not None else None

    def f(v):
        if d is not None:
            v = v.astype(d)
        return jax.nn.log_softmax(v, axis=axis)

    return unary(f, x, "log_softmax")


def softmax_(x, axis=-1, dtype=None, name=None):
    out = softmax(x, axis, dtype)
    x._inplace_from(out)
    return x


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework.random import next_key

    key = next_key()

    def f(v):
        g = jax.random.gumbel(key, v.shape, v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False) if hasattr(jnp, "put_along_axis") else jax.nn.one_hot(jnp.squeeze(idx, axis), v.shape[axis], axis=axis, dtype=v.dtype)
            y = y_hard + jax.lax.stop_gradient(-y) + y  # straight-through
        return y

    return unary(f, ensure_tensor(x), "gumbel_softmax")


def maxout(x, groups, axis=1, name=None):
    def f(v):
        c = v.shape[axis]
        new_shape = list(v.shape)
        new_shape[axis] = c // groups
        new_shape.insert(axis + 1, groups)
        return jnp.max(v.reshape(new_shape), axis=axis + 1)

    return unary(f, x, "maxout")


def glu(x, axis=-1, name=None):
    return unary(lambda v: jax.nn.glu(v, axis=axis), x, "glu")


def elu_(x, alpha=1.0, name=None):
    out = elu(x, alpha)
    x._inplace_from(out)
    return x


def hardtanh_(x, min=-1.0, max=1.0, name=None):
    out = hardtanh(x, min, max)
    x._inplace_from(out)
    return x


def leaky_relu_(x, negative_slope=0.01, name=None):
    out = leaky_relu(x, negative_slope)
    x._inplace_from(out)
    return x


def tanh_(x, name=None):
    out = tanh(x)
    x._inplace_from(out)
    return x


def thresholded_relu_(x, threshold=1.0, name=None):
    out = thresholded_relu(x, threshold)
    x._inplace_from(out)
    return x
