"""Hybrid-parallel topology.

Reference parity: CommunicateTopology (fleet/base/topology.py:66) and
HybridCommunicateGroup (:178, group creation :201-226) — an N-D process grid
in order [pipe, data, sharding, sep, model], with a communication group per
axis plus fused groups (dp+sep "check" groups).

TPU-first: the grid IS a jax.sharding.Mesh with named axes; a "comm group"
is a Group bound to one or more mesh axes (collective.Group). Instead of
creating NCCL communicators per axis, replica groups fall out of the mesh
axis structure when XLA lowers the collectives.
"""
from __future__ import annotations

import itertools

import numpy as np

from .. import env
from ..collective import Group

_AXIS_NAME = {"pipe": "pp", "data": "dp", "sharding": "sharding",
              "sep": "sep", "model": "mp"}
_NAME_AXIS = {v: k for k, v in _AXIS_NAME.items()}


class CommunicateTopology:
    """Reference topology.py:66 — coordinate math over the hybrid grid."""

    def __init__(self, hybrid_group_names=("pipe", "data", "sharding", "sep",
                                           "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = list(
            itertools.product(*(range(d) for d in dims)))
        self._world_size = int(np.prod(dims))
        self._coord2rank = {c: i for i, c in enumerate(
            itertools.product(*(range(d) for d in dims)))}
        self._rank2coord = {v: k for k, v in self._coord2rank.items()}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world_size

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate on `axis_name` equals index."""
        axis = self._parallel_names.index(axis_name)
        return sorted(r for c, r in self._coord2rank.items()
                      if c[axis] == index)

    def get_comm_list(self, axis_name):
        """Groups of ranks that communicate along `axis_name` (vary that
        coordinate, fix the others)."""
        axis = self._parallel_names.index(axis_name)
        other_dims = [d for i, d in enumerate(self._dims) if i != axis]
        comm_list = []
        for other in itertools.product(*(range(d) for d in other_dims)):
            ranks = []
            for v in range(self._dims[axis]):
                coord = list(other)
                coord.insert(axis, v)
                ranks.append(self._coord2rank[tuple(coord)])
            comm_list.append(ranks)
        return comm_list

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = list(self.get_coord(global_rank))
        for name, v in kwargs.items():
            coord[self._parallel_names.index(name)] = v
        return self._coord2rank[tuple(coord)]


class HybridCommunicateGroup:
    """Reference topology.py:178. Exposes per-axis degrees, this-rank
    coordinates (single-controller: rank 0's coordinates), and per-axis
    Groups bound to the global mesh."""

    def __init__(self, topology: CommunicateTopology = None, mesh=None):
        if mesh is None:
            if topology is None:
                raise ValueError("need a topology or a mesh")
            degrees = {
                _AXIS_NAME[n]: topology.get_dim(n)
                for n in topology.get_hybrid_group_names()
            }
            mesh = env.build_mesh(degrees)
        self._mesh = mesh
        env.set_mesh(mesh)
        if topology is None:
            dims = [mesh.shape.get(_AXIS_NAME[n], 1)
                    for n in ("pipe", "data", "sharding", "sep", "model")]
            topology = CommunicateTopology(dims=dims)
        self._topo = topology

        def deg(ax):
            return int(self._mesh.shape.get(ax, 1))

        self._dp_degree = deg("dp")
        self._mp_degree = deg("mp")
        self._pp_degree = deg("pp")
        self._sharding_degree = deg("sharding")
        self._sep_degree = deg("sep")

        self.global_rank = env.get_rank()

        # per-axis groups (reference _set_comm_group per axis, :201-226)
        self._dp_group = self._make_group(("dp",))
        self._mp_group = self._make_group(("mp",))
        self._pp_group = self._make_group(("pp",))
        self._sharding_group = self._make_group(("sharding",))
        self._sep_group = self._make_group(("sep",)) if self._sep_degree > 1 \
            else None
        # fused dp+sep group for grad sync (hybrid_parallel_util.py:254-269)
        if self._sep_degree > 1:
            self._dp_sep_group = self._make_group(("dp", "sep"))
        else:
            self._dp_sep_group = self._dp_group

    def _make_group(self, axes):
        axes = tuple(a for a in axes if a in self._mesh.axis_names)
        if not axes:
            axes = (self._mesh.axis_names[0],)
        return Group(self._mesh, axes)

    @property
    def mesh(self):
        return self._mesh

    def topology(self):
        return self._topo

    def get_hybrid_group_names(self):
        return self._topo.get_hybrid_group_names()

    # -- degrees / ranks (single-controller: coordinate of rank 0) ---------
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_rank(self):
        return 0

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_stage_id(self):
        return 0

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_rank(self):
        return 0

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_rank(self):
        return 0

    # -- groups ------------------------------------------------------------
    def get_data_parallel_group(self):
        return self._dp_group

    def get_model_parallel_group(self):
        return self._mp_group

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sep_parallel_group(self):
        return self._sep_group

    def get_dp_sep_parallel_group(self):
        return self._dp_sep_group

    def get_check_parallel_group(self, sharding=False):
        return self._make_group(("pp", "mp"))

    def get_data_parallel_group_src_rank(self):
        return 0

    def get_model_parallel_group_src_rank(self):
        return 0

    # pipeline neighbors (used by P2P; traced ppermute handles the actual
    # transfer, these are for schedule bookkeeping)
    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1


_hcg = None


def set_hybrid_communicate_group(hcg):
    global _hcg
    _hcg = hcg


def get_hybrid_communicate_group():
    return _hcg
