"""Unified runtime telemetry tests (ISSUE 12): MetricsRegistry,
StepTimeline, RetraceSentinel, flight recorder, and the producer
integrations (train step, serving metrics, profile_step)."""
import json
import os
import re

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counter_gauge_histogram(self):
        r = obs.MetricsRegistry()
        r.counter("c").inc()
        r.counter("c").inc(2.5)
        assert r.counter("c").value == 3.5
        r.gauge("g").set(7)
        assert r.gauge("g").value == 7
        h = r.histogram("h", window=4)
        for v in (1, 2, 3, 4, 5, 6):
            h.observe(v)
        # ring keeps the LAST window samples; count/sum cover all
        assert h.samples() == [3.0, 4.0, 5.0, 6.0]
        assert h.count == 6 and h.total == 21.0
        assert h.percentile(50) == 5.0
        snap = h.snapshot()
        assert snap["min"] == 1.0 and snap["max"] == 6.0
        assert snap["p99"] == 6.0

    def test_lazy_gauge_evaluated_at_scrape(self):
        r = obs.MetricsRegistry()
        calls = []
        r.gauge("lazy").set_fn(lambda: calls.append(1) or 42)
        assert not calls                      # nothing until scraped
        assert r.gauge("lazy").value == 42
        assert len(calls) == 1

    def test_type_conflict_raises(self):
        r = obs.MetricsRegistry()
        r.counter("x")
        with pytest.raises(TypeError):
            r.gauge("x")

    def test_reset_prefix(self):
        r = obs.MetricsRegistry()
        r.counter("a.n").inc(5)
        r.counter("b.n").inc(5)
        r.reset(prefix="a.")
        assert r.counter("a.n").value == 0
        assert r.counter("b.n").value == 5

    def test_percentile_nearest_rank(self):
        assert obs.percentile([], 50) is None
        assert obs.percentile([3, 1, 2], 50) == 2
        assert obs.percentile([1, 2, 3, 4], 99) == 4

    def test_global_registry_singleton(self):
        assert obs.registry() is obs.registry()

    def test_prometheus_exposition_format(self):
        r = obs.MetricsRegistry()
        r.counter("serving.finished").inc(3)
        r.gauge("queue depth!").set(2)        # name gets sanitized
        h = r.histogram("serving.ttft_s")
        h.observe(0.5)
        h.observe(1.5)
        text = r.expose()
        assert text.endswith("\n")
        lines = text.splitlines()
        assert "# TYPE serving_finished counter" in lines
        assert "serving_finished 3.0" in lines
        assert "# TYPE queue_depth_ gauge" in lines
        assert 'serving_ttft_s{quantile="0.5"}' in text
        assert "serving_ttft_s_count 2" in lines
        sample = re.compile(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{quantile="[0-9.]+"\})? [^ ]+$')
        for ln in lines:
            if ln and not ln.startswith("#"):
                assert sample.match(ln), ln

    def test_prometheus_conformance_roundtrip(self):
        """ISSUE 13 satellite: every name registered by the real
        producers (plus adversarial ones) survives `_prom_name` as a
        valid, collision-free metric name, and every value renders as
        a spec-conformant token (incl. +Inf/-Inf/NaN)."""
        from paddle_tpu.observability.registry import (
            _PROM_NAME_OK, _prom_name,
        )

        # the process-global registry holds whatever the producer
        # modules registered so far this test session — round-trip all
        # of them, plus names crafted to stress the sanitizer
        r = obs.MetricsRegistry()
        for name in obs.registry().names():
            r.gauge(name).set(1.0)
        r.gauge("0starts.with.digit").set(float("inf"))
        r.gauge("").set(float("-inf"))
        r.gauge("häagen-dazs metrics!").set(float("nan"))
        r.gauge("a.b").set(1.0)
        r.gauge("a/b").set(2.0)                 # collides with a.b
        names = r.names()
        assert names                            # producers registered
        text = r.expose()
        value_re = re.compile(r"^(NaN|[+-]Inf|[-+]?[0-9.eE+-]+)$")
        seen = set()
        for ln in text.splitlines():
            if not ln or ln.startswith("#"):
                continue
            metric, value = ln.rsplit(" ", 1)
            metric = metric.split("{")[0]
            assert _PROM_NAME_OK.match(metric), ln
            assert value_re.match(value), ln
            assert metric not in seen, f"duplicate sample {metric}"
            seen.add(metric)
        # every registered instrument produced exactly one gauge
        # sample and no two collapsed onto the same exposition name
        assert len(seen) == len(names)
        for name in names:
            assert _PROM_NAME_OK.match(_prom_name(name)), name
        assert "_2" in text                     # a/b disambiguated
        assert "+Inf" in text and "-Inf" in text and "NaN" in text


# ---------------------------------------------------------------------------
# timeline
# ---------------------------------------------------------------------------

class TestTimeline:
    def test_jsonl_roundtrip_and_schema(self, tmp_path):
        path = str(tmp_path / "tl.jsonl")
        tl = obs.StepTimeline(sinks=[obs.JsonlSink(path)], lane="train")
        want = [tl.record(step=i, host_ms=1.0 + i, note="x")
                for i in range(3)]
        tl.close()
        got = obs.read_jsonl(path)
        assert got == want
        for r in got:
            assert set(r) >= {"ts", "lane", "step"}
            assert r["lane"] == "train"

    def test_auto_step_numbers(self):
        tl = obs.StepTimeline(lane="t_auto")
        assert tl.record(host_ms=1)["step"] == 0
        assert tl.record(host_ms=1)["step"] == 1

    def test_registry_mirror_and_chrome_counters(self):
        obs.drain_chrome_counters()           # start clean
        tl = obs.StepTimeline(lane="t_mirror")
        tl.record(step=0, host_ms=5.0, label="not-numeric")
        h = obs.registry().get("timeline.t_mirror.host_ms")
        assert h is not None and h.count >= 1
        counters = obs.drain_chrome_counters()
        names = {c["name"] for c in counters}
        assert "t_mirror/host_ms" in names
        assert all(c["ph"] == "C" for c in counters)
        # drained means drained
        assert obs.drain_chrome_counters() == []

    def test_failing_sink_does_not_break_recording(self):
        def bad(rec):
            raise RuntimeError("sink down")

        tl = obs.StepTimeline(sinks=[bad], lane="t_bad")
        assert tl.record(step=0, host_ms=1.0)["step"] == 0


# ---------------------------------------------------------------------------
# retrace sentinel
# ---------------------------------------------------------------------------

class TestSentinel:
    def test_hit_and_signature_counting(self):
        import jax.numpy as jnp

        s = obs.RetraceSentinel("t_counts")
        x = jnp.ones((2, 2))
        s.observe((x,), names=("x",))
        s.observe((x,), names=("x",))
        st = s.stats()
        assert st["signatures"] == 1 and st["hits"] == 1
        assert st["unexpected"] == 0

    def test_dtype_flip_attributed(self):
        import jax.numpy as jnp

        s = obs.RetraceSentinel("t_flip")
        s.observe((jnp.ones((2, 2)), jnp.ones((2,), jnp.int32)),
                  names=("x", "ids"))
        ev = s.observe((jnp.ones((2, 2)), jnp.ones((2,), jnp.int64)),
                       names=("x", "ids"))
        assert ev is not None and not ev["expected"]
        assert any("ids" in c and "dtype" in c for c in ev["changes"])
        assert s.stats()["unexpected"] == 1

    def test_shape_change_attributed(self):
        import jax.numpy as jnp

        s = obs.RetraceSentinel("t_shape")
        s.observe((jnp.ones((2, 4)),), names=("x",))
        ev = s.observe((jnp.ones((2, 8)),), names=("x",))
        assert any("x" in c and "shape" in c for c in ev["changes"])
        assert s.stats()["unexpected"] == 1

    def test_bucketed_shape_change_expected(self):
        import jax.numpy as jnp

        s = obs.RetraceSentinel("t_bucket", bucketed=("ids",))
        s.observe((jnp.ones((2, 16), jnp.int32), jnp.float32(0)),
                  names=("ids", "lr"))
        ev = s.observe((jnp.ones((2, 32), jnp.int32), jnp.float32(0)),
                       names=("ids", "lr"))
        assert ev["expected"]
        assert s.stats()["unexpected"] == 0
        # but a DTYPE change on the bucketed arg is still unexpected
        ev = s.observe((jnp.ones((2, 32), jnp.int64), jnp.float32(0)),
                       names=("ids", "lr"))
        assert not ev["expected"]

    def test_optional_presence_expected(self):
        import jax.numpy as jnp

        s = obs.RetraceSentinel("t_opt", optional=("seg",))
        s.observe((jnp.ones((2,)), None), names=("x", "seg"))
        ev = s.observe((jnp.ones((2,)), jnp.ones((2,), jnp.int32)),
                       names=("x", "seg"))
        assert ev["expected"], ev
        assert s.stats()["unexpected"] == 0

    def test_numpy_vs_device_kind_attributed(self):
        """The PR-6 silent-recompile class: a host-numpy leaf turning
        into a device array (or back) is an attributed kind change."""
        import jax.numpy as jnp

        s = obs.RetraceSentinel("t_kind")
        s.observe((np.ones((2,), np.int32),), names=("meta",))
        ev = s.observe((jnp.ones((2,), jnp.int32),), names=("meta",))
        assert ev is not None and not ev["expected"]
        assert any("meta" in c and "kind" in c for c in ev["changes"])

    def test_strict_mode_raises(self):
        import jax.numpy as jnp

        s = obs.RetraceSentinel("t_strict", strict=True)
        s.observe((jnp.ones((2,)),), names=("x",))
        with pytest.raises(obs.RetraceError, match="x: dtype"):
            s.observe((jnp.ones((2,), jnp.int32),), names=("x",))

    def test_strict_refused_signature_re_raises(self):
        """A strict-mode refusal must NOT register the bad signature:
        a retry with the same drifted args re-detects and re-raises
        instead of counting as a cache hit and silently compiling."""
        import jax.numpy as jnp

        s = obs.RetraceSentinel("t_strict_retry", strict=True)
        s.observe((jnp.ones((2,)),), names=("x",))
        for _ in range(2):
            with pytest.raises(obs.RetraceError):
                s.observe((jnp.ones((2,), jnp.int32),), names=("x",))
        st = s.stats()
        assert st["signatures"] == 1      # bad signature never kept
        assert st["unexpected"] == 2      # each retry re-detected

    def test_global_strict_toggle(self):
        import jax.numpy as jnp

        s = obs.RetraceSentinel("t_gstrict")
        obs.set_strict_retrace(True)
        try:
            s.observe((jnp.ones((2,)),), names=("x",))
            with pytest.raises(obs.RetraceError):
                s.observe((jnp.ones((3,)),), names=("x",))
        finally:
            obs.set_strict_retrace(False)

    def test_registry_counters_published(self):
        import jax.numpy as jnp

        s = obs.RetraceSentinel("t_reg")
        s.observe((jnp.ones((2,)),))
        s.observe((jnp.ones((3,)),))
        g = obs.registry().get("retrace.t_reg.signatures")
        assert g is not None and g.value == 2
        c = obs.registry().get("retrace.t_reg.unexpected")
        assert c is not None and c.value == 1

    def test_retrace_summary_aggregates(self):
        import jax.numpy as jnp

        s = obs.RetraceSentinel("t_sum")
        s.observe((jnp.ones((2,)),))
        summary = obs.retrace_summary()
        assert "t_sum" in summary["sentinels"]
        assert summary["sentinels"]["t_sum"]["signatures"] == 1


# ---------------------------------------------------------------------------
# train-step integration + HLO cost accounting
# ---------------------------------------------------------------------------

class TestTrainStepIntegration:
    def _build(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as popt
        from paddle_tpu.jit import TrainStep

        paddle.seed(0)
        m = nn.Linear(8, 4)
        opt = popt.AdamW(learning_rate=1e-3,
                         parameters=m.parameters())
        step = TrainStep(m, lambda mm, a, b: ((mm(a) - b) ** 2).mean(),
                         opt)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 8).astype(np.float32))
        y = paddle.to_tensor(
            np.random.RandomState(1).randn(4, 4).astype(np.float32))
        return step, x, y

    def test_clean_run_one_signature(self):
        step, x, y = self._build()
        for _ in range(3):
            step(x, y)
        st = step.retrace_stats()
        assert st["signatures"] == 1
        assert st["hits"] == 2
        assert st["unexpected"] == 0
        if hasattr(step._jitted, "_cache_size"):
            assert step._jitted._cache_size() == 1

    def test_injected_dtype_flip_names_leaf(self):
        step, x, y = self._build()
        step(x, y)
        y64 = y.astype("float64")
        step(x, y64)
        st = step.retrace_stats()
        assert st["unexpected"] == 1
        ev = st["events"][-1]
        assert any("batch[1]" in c and "dtype" in c
                   for c in ev["changes"]), ev

    def test_cost_analysis_surface(self):
        step, x, y = self._build()
        step(x, y)
        ca = step.cost_analysis(x, y)
        assert ca["flops_per_step"] and ca["flops_per_step"] > 0
        assert ca["collectives"] is not None
        assert ca["collectives"]["total_comm_bytes"] == 0  # one chip
        # published into the global registry
        g = obs.registry().get("hlo.flops_per_step")
        assert g is not None and g.value > 0

    def test_cost_analysis_requires_built_step(self):
        step, x, y = self._build()
        with pytest.raises(RuntimeError, match="built"):
            step.cost_analysis(x, y)


class TestDecodeStepSentinel:
    def test_decode_flip_attributed_and_buckets_expected(self):
        """The decode/serve `_Step` paths carry the sentinel too: a
        token-dtype flip is attributed by argument name, while prefill
        length buckets are declared expected shape families."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.jit.decode_step import (
            GenerationEngine, _split_state,
        )
        from paddle_tpu.jit.train_step import _tree_data
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=96,
                        hidden_dropout_prob=0.0,
                        attention_dropout_prob=0.0)
        m = GPTForCausalLM(cfg)
        m.eval()
        eng = GenerationEngine(m, kind="dense", batch=1, max_len=64)
        ids = np.arange(1, 9, dtype=np.int64)[None]
        eng.generate(ids, 4)
        # longer prompt -> next prefill bucket: expected, not flagged
        eng.generate(np.arange(1, 20, dtype=np.int64)[None], 4)
        pst = eng.prefill_step.retrace_stats()
        assert pst["signatures"] == 2 and pst["unexpected"] == 0, pst
        dst = eng.decode_step.retrace_stats()
        assert dst["signatures"] == 1 and dst["unexpected"] == 0, dst
        # inject a dtype flip straight into the decode program's args
        buffers, meta = _split_state(
            "dense", _tree_data(eng.cache.state()))
        bad_tokens = jnp.zeros((1,), jnp.int64)   # decode feeds int32
        eng.decode_step(eng._param_data(), buffers, meta, bad_tokens,
                        jax.random.PRNGKey(0))
        dst = eng.decode_step.retrace_stats()
        assert dst["unexpected"] == 1, dst
        ev = dst["events"][-1]
        assert any("tokens" in c and "dtype" in c
                   for c in ev["changes"]), ev


# ---------------------------------------------------------------------------
# producers: serving metrics, profile_step, flight recorder
# ---------------------------------------------------------------------------

class _Handle:
    ttft = 0.25
    inter_token_latencies = [0.01, 0.02]
    preemptions = 1


class TestServingMetrics:
    def test_percentiles_via_registry_histograms(self):
        from paddle_tpu.serving.metrics import ServingMetrics

        m = ServingMetrics()
        assert isinstance(m.ttft_s, obs.Histogram)
        m.on_submit()
        m.on_finish(_Handle())
        snap = m.snapshot()
        assert snap["ttft_p50_s"] == 0.25
        # nearest-rank p50 of [0.01, 0.02] (round-half-even index 0)
        assert snap["itl_p50_s"] == 0.01
        assert snap["finished"] == 1

    def test_metrics_text_scrape_format(self):
        from paddle_tpu.serving.metrics import ServingMetrics

        m = ServingMetrics()
        m.on_submit()
        m.on_finish(_Handle())
        m.observe(queue_depth=3, running=2)
        text = m.expose()
        lines = text.splitlines()
        assert "# TYPE serving_ttft_s summary" in lines
        assert 'serving_ttft_s{quantile="0.5"} 0.25' in lines
        assert "serving_ttft_s_count 1" in lines
        assert "serving_finished 1" in text
        assert "serving_queue_depth 3" in text
        sample = re.compile(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{quantile="[0-9.]+"\})? [^ ]+$')
        for ln in lines:
            if ln and not ln.startswith("#"):
                assert sample.match(ln), ln

    def test_engines_isolated(self):
        from paddle_tpu.serving.metrics import ServingMetrics

        a, b = ServingMetrics(), ServingMetrics()
        a.on_preempt(pages_reclaimed=4)
        assert a.preemptions == 1 and b.preemptions == 0


class TestProfileStepAlwaysOn:
    def test_records_without_profiler(self):
        """Regression (ISSUE 12 satellite): the docstring promises
        'time one span even with no Profiler active' — the span must
        land somewhere observable when no Profiler cycle is RECORDing."""
        from paddle_tpu.profiler import profile_step

        h = obs.registry().histogram("profile_step.orphan_span_ms")
        before = h.count
        with profile_step("orphan_span"):
            pass
        assert h.count == before + 1

    def test_still_joins_profiler_events_when_recording(self):
        from paddle_tpu.profiler import Profiler, profile_step

        p = Profiler(on_trace_ready=lambda prof: None)
        p.start()
        with profile_step("in_cycle"):
            pass
        res = p.stop()
        assert any(e.name == "in_cycle" for e in res.events)


class TestFlightRecorder:
    def test_note_and_dump(self, tmp_path):
        rec = obs.FlightRecorder(capacity=4)
        for i in range(6):
            rec.note("step", step=i)
        events = rec.snapshot()
        assert len(events) == 4               # bounded ring
        assert events[-1]["step"] == 5
        path = str(tmp_path / "crash.json")
        try:
            raise ValueError("boom")
        except ValueError as e:
            out = rec.dump(reason="test", exc=e, path=path)
        assert out == path and os.path.exists(path)
        with open(path) as f:
            data = json.load(f)
        assert data["reason"] == "test"
        assert data["exception"]["type"] == "ValueError"
        assert len(data["events"]) == 4
        assert "metrics" in data

    def test_global_recorder_singleton(self):
        assert obs.recorder() is obs.recorder()


class TestHloByteCensus:
    def test_async_start_payload_not_double_counted(self):
        """An all-reduce-start's tuple result is (aliased operand,
        output) — the census must count the payload once."""
        mod = obs.load_hlo_overlap()
        text = (
            "HloModule m\n\n"
            "ENTRY %main (p: f32[1024]) -> f32[1024] {\n"
            "  %p = f32[1024]{0} parameter(0)\n"
            "  %ar = (f32[1024]{0}, f32[1024]{0}) all-reduce-start("
            "f32[1024]{0} %p), replica_groups={{0,1}}\n"
            "  ROOT %d = f32[1024]{0} all-reduce-done("
            "(f32[1024]{0}, f32[1024]{0}) %ar)\n"
            "}\n")
        v = mod.analyze(text)
        assert v["counts"] == {"all-reduce": 1}
        assert v["total_comm_bytes"] == 4096

    def test_sync_tuple_elements_summed(self):
        """The sync tuple form (all-to-all over several arrays)
        carries REAL outputs in every element — those do sum."""
        mod = obs.load_hlo_overlap()
        text = (
            "HloModule m\n\n"
            "ENTRY %main (p: f32[64]) -> f32[64] {\n"
            "  %p = f32[64]{0} parameter(0)\n"
            "  %a2a = (f32[64]{0}, f32[64]{0}) all-to-all("
            "f32[64]{0} %p, f32[64]{0} %p), replica_groups={{0,1}}\n"
            "  ROOT %r = f32[64]{0} get-tuple-element((f32[64]{0}, "
            "f32[64]{0}) %a2a), index=0\n"
            "}\n")
        v = mod.analyze(text)
        assert v["total_comm_bytes"] == 2 * 64 * 4


class TestGuardGauges:
    def test_gauges_follow_latest_guard_via_weakref(self):
        import gc

        from paddle_tpu.jit.nonfinite_guard import GuardSpec

        spec = GuardSpec()
        spec.writeback(spec.init_state())
        g = obs.registry().get("train.guard_skipped_steps")
        assert g is not None and g.value == 0
        assert obs.registry().gauge("train.loss_scale").value == 1.0
        del spec
        gc.collect()
        # superseded guard is NOT pinned by the registry closure
        assert g.value is None


class TestCheckpointTelemetry:
    def test_save_timings_published(self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.checkpoint import CheckpointManager

        paddle.seed(0)
        m = nn.Linear(4, 2)
        mgr = CheckpointManager(str(tmp_path), model=m)
        before = obs.registry().counter("checkpoint.saves").value
        mgr.save(0)
        assert obs.registry().counter(
            "checkpoint.saves").value == before + 1
        assert obs.registry().histogram(
            "checkpoint.snapshot_ms").count >= 1
        assert obs.registry().histogram(
            "checkpoint.io_ms").count >= 1
