"""Spectral normalization.

Reference parity: python/paddle/nn/utils/spectral_norm_hook.py (the
spectral_norm wrapper) and nn.SpectralNorm — largest-singular-value
normalization of a weight via power iteration, the u/v vectors carried as
buffers.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...framework.autograd import apply_op, no_grad
from ...framework.random import next_key
from ..layer.layers import Layer


def _l2norm(v, eps):
    return v / jnp.maximum(jnp.linalg.norm(v), eps)


class SpectralNorm(Layer):
    """Standalone layer: forward(weight) -> spectrally-normalized weight
    (reference nn.SpectralNorm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        import jax

        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.register_buffer("weight_u", Tensor(
            _l2norm(jax.random.normal(next_key(), (h,), jnp.float32), eps)))
        self.register_buffer("weight_v", Tensor(
            _l2norm(jax.random.normal(next_key(), (w,), jnp.float32), eps)))

    def forward(self, weight):
        dim, iters, eps = self.dim, self.power_iters, self.eps

        def f(w, u, v):
            mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            for _ in range(iters):
                v = _l2norm(mat.T @ u, eps)
                u = _l2norm(mat @ v, eps)
            sigma = u @ mat @ v
            return w / sigma, u, v

        out, u, v = apply_op(f, [weight, self.weight_u, self.weight_v],
                             name="spectral_norm")
        with no_grad():
            self.weight_u._data = u._data
            self.weight_v._data = v._data
        return out


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Wrap `layer` so `layer.weight` is spectrally normalized on every
    forward (reference spectral_norm hook)."""
    weight = getattr(layer, name)
    if dim is None:
        dim = 0
    sn = SpectralNorm(weight.shape, dim=dim, power_iters=n_power_iterations,
                      eps=eps)
    layer.add_sublayer(f"{name}_spectral_norm", sn)
    raw_name = f"{name}_orig"
    layer.add_parameter(raw_name, weight)
    if name in layer._parameters:
        del layer._parameters[name]

    orig_forward = layer.forward

    def hooked_forward(*args, **kwargs):
        setattr(layer, name, sn(getattr(layer, raw_name)))
        return orig_forward(*args, **kwargs)

    layer.forward = hooked_forward
    return layer
