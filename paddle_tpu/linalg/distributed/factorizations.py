"""Blocked Cholesky + TSQR QR on the distributed grid.

Cholesky (right-looking, square g×g grid): the classic blocked loop —
factor the diagonal block, triangular-solve the panel below it, rank-k
update the trailing matrix — with each block owned by one rank. Per
iteration the wire carries the [nb, nb] diagonal block (two one-axis
broadcasts) and the column-k panel ([n, nb] — an all_gather along
``rows``); the trailing update is local. No rank ever holds more than
its block plus one panel.

QR (TSQR, 1-D row layout over the flattened grid): each rank QRs its
row block, the [w·n, n] stack of local R factors is gathered (n is the
SKINNY dim — the tall dim never gathers) and QR'd redundantly, and the
final thin Q is the local Q times this rank's block of the second-stage
Q. Communication: ONE all_gather of n×n factors. Requires full column
rank (the standard TSQR contract; rank-deficient inputs should go
through svd).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ._grid import (
    COLS, ROWS, as_array, build_grid, cached_jit, grid_shape, pad2,
    place, wrap_like,
)

__all__ = ["cholesky", "qr", "cholesky_lowered", "qr_lowered"]


# ---------------------------------------------------------------------------
# blocked Cholesky
# ---------------------------------------------------------------------------

def _chol_fn(g):
    """Per-rank body over one [nb, nb] block of the padded SPD matrix."""

    def fn(a):
        i = lax.axis_index(ROWS)
        j = lax.axis_index(COLS)
        L = jnp.zeros_like(a)
        for k in range(g):
            # diagonal block -> everyone (two one-axis broadcasts)
            akk = lax.psum(jnp.where((i == k) & (j == k), a,
                                     jnp.zeros_like(a)), ROWS)
            akk = lax.psum(akk, COLS)
            lkk = jnp.linalg.cholesky(akk)
            # panel below the diagonal: L_ik = A_ik @ L_kk^{-T}
            # (computed by every rank; only column k's blocks are real)
            pan = jax.scipy.linalg.solve_triangular(
                lkk, a.swapaxes(-1, -2), lower=True).swapaxes(-1, -2)
            pan = jnp.where(i == k, lkk, pan)
            # broadcast column k's blocks across the grid row...
            pan = lax.psum(jnp.where(j == k, pan, jnp.zeros_like(pan)),
                           COLS)
            # ...and gather the whole column-k panel along rows: every
            # rank sees L_{*,k} ([g, nb, nb] = an [n, nb] panel)
            panel = lax.all_gather(pan, ROWS, axis=0, tiled=False)
            l_ik = pan                       # block (i, k)
            l_jk = jnp.take(panel, j, axis=0)  # block (j, k)
            L = jnp.where((j == k) & (i >= k), l_ik, L)
            upd = jnp.dot(l_ik, l_jk.swapaxes(-1, -2),
                          preferred_element_type=jnp.float32)
            a = jnp.where((i > k) & (j > k), a - upd.astype(a.dtype), a)
        return L

    return fn


def _build_chol(grid, g):
    spec = P(ROWS, COLS)
    return jax.jit(jax.shard_map(_chol_fn(g), mesh=grid,
                                 in_specs=(spec,), out_specs=spec,
                                 check_vma=False))


def _chol_grid(grid):
    if grid is None:
        grid = build_grid(square=True)
    r, c = grid_shape(grid)
    if r != c:
        raise ValueError(
            f"blocked Cholesky needs a square grid (block (i,k)/(j,k) "
            f"indexing aligns row and column blocks); got {r}x{c} — "
            "build_grid(square=True)")
    return grid, r


def cholesky(x, upper=False, grid=None):
    """Distributed lower Cholesky of an SPD matrix on a g×g grid.

    Non-divisible sizes are padded with an identity tail (keeps the
    padded matrix SPD; the pad factors to itself and is sliced away).
    """
    a, wrap = as_array(x)
    if a.ndim != 2 or a.shape[0] != a.shape[1]:
        raise ValueError(f"cholesky needs a square matrix, got {a.shape}")
    grid, g = _chol_grid(grid)
    a_p, (n, _) = pad2(a, g, g)
    if a_p.shape[0] != n:
        pad_idx = jnp.arange(n, a_p.shape[0])
        a_p = a_p.at[pad_idx, pad_idx].set(jnp.asarray(1, a_p.dtype))
    a_p = place(a_p, grid, P(ROWS, COLS))
    fn = cached_jit(("chol", grid, a_p.shape, str(a_p.dtype)),
                    lambda: _build_chol(grid, g))
    L = fn(a_p)[:n, :n]
    if upper:
        L = L.swapaxes(-1, -2)
    return wrap_like(L, wrap)


def cholesky_lowered(n, grid=None, dtype=jnp.float32):
    grid, g = _chol_grid(grid)
    a = jnp.zeros((n + (-n) % g,) * 2, dtype)
    return _build_chol(grid, g).lower(place(a, grid, P(ROWS, COLS)))


# ---------------------------------------------------------------------------
# TSQR
# ---------------------------------------------------------------------------

def _tsqr_fn(w, c):
    """Per-rank body over one [m/w, n] row block."""

    def fn(a):
        # flattened (rows, cols) rank, first axis major — matches the
        # P((ROWS, COLS), ...) split order
        rank = lax.axis_index(ROWS) * c + lax.axis_index(COLS)
        q1, r1 = jnp.linalg.qr(a, mode="reduced")      # [mL, n], [n, n]
        # the ONLY collective: stack the skinny R factors everywhere
        rs = lax.all_gather(r1, (ROWS, COLS), axis=0,
                            tiled=False)               # [w, n, n]
        n = a.shape[1]
        q2, r2 = jnp.linalg.qr(rs.reshape(w * n, n), mode="reduced")
        q2_block = lax.dynamic_slice_in_dim(q2, rank * n, n, 0)
        return jnp.dot(q1, q2_block,
                       preferred_element_type=jnp.float32) \
            .astype(a.dtype), r2

    return fn


def _build_tsqr(grid, w):
    row_spec = P((ROWS, COLS), None)
    _, c = grid_shape(grid)
    return jax.jit(jax.shard_map(_tsqr_fn(w, c), mesh=grid,
                                 in_specs=(row_spec,),
                                 out_specs=(row_spec, P()),
                                 check_vma=False))


def qr(x, mode="reduced", grid=None):
    """Distributed thin QR of a tall [m, n] matrix (TSQR): A row-sharded
    over ALL grid devices, one n×n-factor all_gather, full-rank
    contract. Returns (Q [m, n], R [n, n])."""
    if mode != "reduced":
        raise NotImplementedError(
            f"distributed.qr supports mode='reduced' (thin TSQR); "
            f"got {mode!r}")
    a, wrap = as_array(x)
    if a.ndim != 2:
        raise ValueError(f"qr needs a 2-D matrix, got {a.shape}")
    if grid is None:
        grid = build_grid()
    r, c = grid_shape(grid)
    w = r * c
    a_p, (m, n) = pad2(a, w, 1)
    if m < n:
        raise ValueError(
            f"TSQR is for tall matrices (m >= n), got {a.shape}")
    a_p = place(a_p, grid, P((ROWS, COLS), None))
    fn = cached_jit(("tsqr", grid, a_p.shape, str(a_p.dtype)),
                    lambda: _build_tsqr(grid, w))
    q, r_out = fn(a_p)
    return wrap_like(q[:m], wrap), wrap_like(r_out, wrap)


def qr_lowered(m, n, grid=None, dtype=jnp.float32):
    if grid is None:
        grid = build_grid()
    r, c = grid_shape(grid)
    w = r * c
    a = jnp.zeros((m + (-m) % w, n), dtype)
    return _build_tsqr(grid, w).lower(
        place(a, grid, P((ROWS, COLS), None)))
